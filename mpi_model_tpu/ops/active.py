"""Active-tile stepping: skip the quiet ocean, step only where the physics is.

BASELINE's round-5/6 analysis proved ~3.2 ms/step is the per-cell-RATE
bound for a radius-1 stencil on this chip — but the reference's live
workload (``/root/reference/src/Main.cpp``: one point flow on the grid)
spends most of a run with the wavefront covering a few percent of the
domain. The remaining order-of-magnitude win is in TOTAL WORK, not rate:
track activity at tile granularity, compute only active tiles, keep
static shapes via fixed-capacity compaction (the sparse-CA /
blockwise-conditional-compute shape: Hashlife-style activity
exploitation, MoE/paged-block routing).

The activity rule and why skipping is EXACT
-------------------------------------------
The grid is cut into ``(th, tw)`` tiles. A tile is **active** this step
iff any cell in it *or in its ring-1 neighbor tiles* is nonzero (the
3x3 tile dilation of the per-tile any-nonzero map). For the uniform-rate
linear flows this engine serves (``Diffusion``: ``out = v - rate*v +
Σ share(neighbors)``), an INACTIVE tile's cells and all cells within
distance 1 of them are zero, so their update is exactly ``0 - rate*0 +
Σ 0 = 0``: skipping the tile is *exactly equal* to computing it —
zero stays zero, and frontier tiles activate one step BEFORE flux can
arrive (the dilation), so no arriving mass is ever missed. One
sign-of-zero caveat: a stored ``-0.0`` cell counts as zero (``v != 0``)
and a skipped tile KEEPS it, while the dense update canonicalizes it
to ``+0.0`` (``-0.0 - (rate*-0.0) = +0.0`` in IEEE). The two outputs
are equal under ``==``/``np.array_equal`` — the contract every gate
and test checks — but differ at the sign bit under ``tobytes()``
hashing; seed grids with ``+0.0`` (the default) for bit-level
reproducibility across impls. The active
tiles' update mirrors the dense XLA path (``ops.stencil.transport``)
term for term — same ops, same accumulation order, same neighbor-count
values — so an active-path step equals the dense step bitwise at every
dtype (proven at f64 and f32 in ``tests/test_active.py``).

Capacity / fallback contract
----------------------------
Tile indices are cumsum-compacted into a fixed-capacity ``[K]`` buffer
(static shapes under ``jit``); per-tile windows are gathered, updated,
and scattered back with trip counts bounded by the *actual* active
count, so work scales with activity, not capacity. When the active
count exceeds the capacity OR the activity-fraction threshold, the
engine falls back to the DENSE step **that same step** (a ``lax.cond``
— never a wrong result, never a silent truncation), and the serial
runner counts those steps so ``Report.backend_report`` stays honest
(the same pattern as the point-subsystem routing in
``parallel/executors.py``).

Integration map
---------------
``Model.make_step(impl="active")`` (stateless per-step form; composes
with point flows, partitions and substeps), the amortized
``SerialExecutor(step_impl="active")`` runner (pads once, carries the
tile map and update buffer across the whole run — the bench path),
shard-local active sets in ``ShardMapExecutor(step_impl="active")``
(activity is per-shard; the ppermute ghost ring both feeds the windows
and activates edge tiles), per-scenario activity in
``ensemble.EnsembleExecutor(impl="active")`` (one lane = one active
set, traced per-lane rates), ``--impl=active`` on the CLI, and
``bench.bench_active`` (speedup-vs-activity-fraction at the timed
geometry).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.cell import MOORE_OFFSETS
from .stencil import neighbor_counts_traced, transport


def _pick_tile_dim(dim: int, preferred: int) -> int:
    """Largest divisor of ``dim`` that is <= preferred (tiles must tile
    the grid exactly — a remainder tile would need its own shape)."""
    for t in range(min(dim, preferred), 0, -1):
        if dim % t == 0:
            return t
    return dim


@dataclasses.dataclass(frozen=True)
class ActivePlan:
    """Static geometry of the active-tile engine for one grid shape:
    tile dims, tile-grid dims, the fixed compaction capacity ``K`` and
    the dense-fallback threshold (in tiles). Hashable — safe to close
    over in jitted steps and to key runner caches with."""

    shape: tuple[int, int]
    tile: tuple[int, int]
    grid: tuple[int, int]          #: (gi, gj) tile-grid dims
    capacity: int                  #: K — compaction buffer lanes
    fallback_tiles: int            #: dense fallback when count exceeds this

    @property
    def ntiles(self) -> int:
        return self.grid[0] * self.grid[1]


def plan_for(shape: tuple[int, int], tile: Optional[tuple[int, int]] = None,
             capacity: Optional[int] = None,
             max_active_frac: float = 0.25,
             preferred_tile: int = 128) -> ActivePlan:
    """Build the engine geometry for ``shape``.

    ``tile`` defaults to the largest divisors <= ``preferred_tile``
    (128² tiles → 16k tiles at the 16384² bench geometry). ``capacity``
    defaults to ``ceil(max_active_frac * ntiles)``; the dense fallback
    engages when the dilated active count exceeds
    ``min(capacity, ceil(max_active_frac * ntiles))`` — capacity
    overflow can therefore NEVER truncate the active set."""
    h, w = shape
    if tile is None:
        tile = (_pick_tile_dim(h, preferred_tile),
                _pick_tile_dim(w, preferred_tile))
    th, tw = int(tile[0]), int(tile[1])
    if th < 1 or tw < 1 or h % th or w % tw:
        raise ValueError(
            f"tile {tile} does not tile grid {shape} exactly; pick "
            "divisors of the grid dims (or tile=None to auto-pick)")
    gi, gj = h // th, w // tw
    ntiles = gi * gj
    if not 0.0 < max_active_frac <= 1.0:
        raise ValueError(
            f"max_active_frac must be in (0, 1], got {max_active_frac}")
    frac_tiles = max(1, min(ntiles, math.ceil(max_active_frac * ntiles)))
    cap = frac_tiles if capacity is None else int(capacity)
    if cap < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    cap = min(cap, ntiles)
    return ActivePlan(shape=(h, w), tile=(th, tw), grid=(gi, gj),
                      capacity=cap, fallback_tiles=min(cap, frac_tiles))


# -- activity map ------------------------------------------------------------

def tile_nonzero_map(v: jax.Array, plan: ActivePlan) -> jax.Array:
    """Per-tile any-nonzero: bool ``[gi, gj]``. (``v != 0`` — a -0.0
    background counts as zero and a skipped tile keeps its sign bit,
    whereas the dense update canonicalizes -0.0 to +0.0: equal under
    ``==``, one sign bit apart under byte hashing — module docstring.)"""
    (th, tw), (gi, gj) = plan.tile, plan.grid
    return jnp.any((v != 0).reshape(gi, th, gj, tw), axis=(1, 3))


def dilate_tile_map(tmap: jax.Array) -> jax.Array:
    """3x3 (ring-1) dilation of the tile map — the frontier rule: a tile
    activates one step before flux can arrive. A superset dilation is
    always exact (extra tiles compute zeros), so one rule serves every
    radius-1 neighborhood."""
    gi, gj = tmap.shape
    p = jnp.pad(tmap, 1)
    out = jnp.zeros_like(tmap)
    for dx in (-1, 0, 1):
        for dy in (-1, 0, 1):
            out = out | p[1 + dx:1 + dx + gi, 1 + dy:1 + dy + gj]
    return out


def ghost_flags(padded: jax.Array, plan: ActivePlan) -> jax.Array:
    """Edge-tile activations from a one-cell ghost ring (``[h+2, w+2]``
    padded shard): a nonzero ghost cell activates every edge tile whose
    window contains it — a ghost cell one column past a tile seam sits
    in TWO tiles' windows, so the per-tile strip map is dilated along
    the strip. This is what makes shard-local active sets exact: flux
    arriving from a neighbor shard is seen one step early, exactly like
    the interior dilation."""
    (th, tw), (gi, gj) = plan.tile, plan.grid
    h, w = plan.shape

    def strip(cells: jax.Array, t: int, g: int) -> jax.Array:
        per = jnp.any(cells.reshape(g, t), axis=1)
        pad = jnp.pad(per, 1)
        return per | pad[:-2] | pad[2:]

    flags = jnp.zeros((gi, gj), bool)
    flags = flags.at[0, :].set(flags[0, :]
                               | strip(padded[0, 1:w + 1] != 0, tw, gj))
    flags = flags.at[-1, :].set(flags[-1, :]
                                | strip(padded[h + 1, 1:w + 1] != 0, tw, gj))
    flags = flags.at[:, 0].set(flags[:, 0]
                               | strip(padded[1:h + 1, 0] != 0, th, gi))
    flags = flags.at[:, -1].set(flags[:, -1]
                                | strip(padded[1:h + 1, w + 1] != 0, th, gi))
    # corner ghosts neighbor exactly the corner cell of the corner tile
    flags = flags.at[0, 0].set(flags[0, 0] | (padded[0, 0] != 0))
    flags = flags.at[0, -1].set(flags[0, -1] | (padded[0, w + 1] != 0))
    flags = flags.at[-1, 0].set(flags[-1, 0] | (padded[h + 1, 0] != 0))
    flags = flags.at[-1, -1].set(flags[-1, -1] | (padded[h + 1, w + 1] != 0))
    return flags


def changed_tile_map(prev, new, plan: ActivePlan) -> np.ndarray:
    """Per-tile any-CHANGED map between two states of one channel: bool
    ``[gi, gj]`` host array, True where any byte of the tile differs.
    The delta-checkpoint writer's fallback dirtiness source for dense/
    composed runs (``io.delta``): one vectorized compare over the grid,
    no state carried. Compares raw bytes, not values — a ``-0.0`` vs
    ``+0.0`` flip or a NaN cell reads as changed (NaN != NaN would too,
    but byte compare keeps the map deterministic for any payload), so a
    skipped tile is bit-identical by construction."""
    (th, tw), (gi, gj) = plan.tile, plan.grid
    a = np.ascontiguousarray(prev).view(np.uint8).reshape(gi, th, gj, -1)
    b = np.ascontiguousarray(new).view(np.uint8).reshape(gi, th, gj, -1)
    return np.any(a != b, axis=(1, 3))


def compact_tile_ids(flags: jax.Array,
                     plan: ActivePlan) -> tuple[jax.Array, jax.Array]:
    """Cumsum-compact the active map into the fixed ``[K]`` index buffer:
    returns ``(ids, count)`` — row-major tile indices of the active
    tiles in lanes ``[0, count)`` (lanes past the capacity are dropped
    by the scatter; the caller's fallback predicate fires before such a
    truncated set could ever be consumed)."""
    f = flags.reshape(-1)
    count = jnp.sum(f, dtype=jnp.int32)
    pos = jnp.cumsum(f.astype(jnp.int32)) - 1
    dest = jnp.where(f, pos, plan.capacity)
    ids = jnp.zeros((plan.capacity,), jnp.int32).at[dest].set(
        jnp.arange(f.shape[0], dtype=jnp.int32), mode="drop")
    return ids, count


# -- the per-tile update (bitwise-mirrors ops.stencil.transport) -------------

def active_pass(padded: jax.Array, upd: jax.Array, ids: jax.Array,
                count: jax.Array, rate, plan: ActivePlan,
                origin, global_shape: tuple[int, int],
                offsets: Sequence[tuple[int, int]],
                dtype) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One flow step over the compacted active set; returns
    ``(padded, upd, anyf)`` where ``anyf`` is the ``[K]`` bool per-lane
    any-nonzero of the computed tiles (lanes past ``count`` are False).

    ``padded`` is the ``[h+2, w+2]`` value array (ring = zeros on a full
    grid / partition boundary, real ghost data under sharding); ``upd``
    the carried ``[K, th, tw]`` update buffer (lanes past ``count`` are
    stale and never scattered). Two dynamic-trip-count loops — gather+
    compute into ``upd``, then scatter back — so every read precedes
    every write (neighboring active tiles must all see PRE-step values)
    and total work is O(active), not O(capacity): the per-lane flags
    are computed HERE, on the tile just produced, precisely so the
    next-step tile map never has to reduce over the whole capacity
    buffer (at the bench geometry that reduction reads 268 MB/step —
    measured ~80 ms on the CPU rig, a third of the entire step).

    The update expression mirrors the dense path term for term:
    ``outflow = rate*v``; ``share = outflow/count``; inflow accumulated
    from zeros in ``offsets`` order; ``(v - outflow) + inflow`` — with
    neighbor counts from ``neighbor_counts_traced`` at the window's
    GLOBAL coordinates, so the result is bitwise equal to
    ``ops.stencil.flow_step`` at every dtype.
    """
    (th, tw), (gi, gj) = plan.tile, plan.grid
    wh, ww = th + 2, tw + 2
    H, W = global_shape
    ox = jnp.asarray(origin[0], jnp.int32)
    oy = jnp.asarray(origin[1], jnp.int32)
    rate_c = jnp.asarray(rate, dtype)
    one = jnp.asarray(1, dtype)
    cmin = jnp.minimum(count, np.int32(plan.capacity))

    def rc_of(i):
        return (i // gj) * th, (i % gj) * tw

    def compute_body(l, carry):
        u, f = carry
        r, c = rc_of(ids[l])
        win = lax.dynamic_slice(padded, (r, c), (wh, ww))
        # off-grid window cells can have count 0; their value is 0 anyway
        cnt = jnp.maximum(
            neighbor_counts_traced((wh, ww), offsets,
                                   (ox + r - 1, oy + c - 1), (H, W), dtype),
            one)
        # the barrier materializes outflow so the subtraction below
        # consumes the SAME value the share divides — without it, XLA's
        # per-consumer recompute inside fusions hands LLVM a single-use
        # multiply that contracts to fma(-rate, v, v), a 1-ulp drift
        # from the dense path's uncontracted v - rate*v (measured; the
        # bitwise gate exists to catch exactly this class)
        outflow = lax.optimization_barrier(rate_c * win)
        share = outflow / cnt
        inflow = jnp.zeros((th, tw), dtype)
        for dx, dy in offsets:
            inflow = inflow + lax.slice(
                share, (1 + dx, 1 + dy), (1 + dx + th, 1 + dy + tw))
        tile_out = (win[1:-1, 1:-1] - outflow[1:-1, 1:-1]) + inflow
        return (lax.dynamic_update_index_in_dim(u, tile_out, l, 0),
                f.at[l].set(jnp.any(tile_out != 0)))

    anyf = jnp.zeros((plan.capacity,), bool)
    upd, anyf = lax.fori_loop(0, cmin, compute_body, (upd, anyf))

    def scatter_body(l, p):
        r, c = rc_of(ids[l])
        return lax.dynamic_update_slice(p, upd[l], (r + 1, c + 1))

    padded = lax.fori_loop(0, cmin, scatter_body, padded)
    return padded, upd, anyf


def next_tile_map(anyf: jax.Array, ids: jax.Array, count: jax.Array,
                  plan: ActivePlan) -> jax.Array:
    """Exact post-step tile map from ``active_pass``'s per-lane flags:
    tiles outside the active set are zero by the engine invariant, so
    scattering the ``[K]`` any-nonzero flags over a False map is the
    full answer — O(capacity) on BOOLS, never a read of the update
    buffer itself."""
    gi, gj = plan.grid
    lanes = jnp.arange(plan.capacity, dtype=jnp.int32)
    valid = lanes < jnp.minimum(count, np.int32(plan.capacity))
    flat = jnp.zeros((gi * gj,), bool).at[
        jnp.where(valid, ids, np.int32(gi * gj))].set(anyf & valid,
                                                      mode="drop")
    return flat.reshape(gi, gj)


# -- dense fallbacks ---------------------------------------------------------

def dense_from_padded(padded: jax.Array, rate, counts: jax.Array,
                      offsets: Sequence[tuple[int, int]],
                      dtype) -> jax.Array:
    """Full-grid dense step on the padded representation (zero ring):
    ``ops.stencil.flow_step``'s exact expression — the shares crossing
    the ring are the zero-padded shifts — returning a re-padded array
    (the ring stays zero, preserving the engine invariant)."""
    v = padded[1:-1, 1:-1]
    new = transport(v, jnp.asarray(rate, dtype) * v, counts, offsets)
    return jnp.pad(new, 1)


def dense_from_ghost_padded(padded: jax.Array, rate, counts_pad: jax.Array,
                            offsets: Sequence[tuple[int, int]],
                            dtype) -> jax.Array:
    """Per-shard dense step consuming a REAL ghost ring: shares are
    computed on the padded array (a ghost cell's share equals the value
    the owning shard computes — same expression, same operands — so the
    result matches the share-exchanging XLA shard step bitwise).
    Returns the bare ``[h, w]`` interior (the caller re-exchanges)."""
    h, w = padded.shape[0] - 2, padded.shape[1] - 2
    # barrier: same anti-FMA-contraction discipline as active_pass
    outflow_p = lax.optimization_barrier(
        jnp.asarray(rate, dtype) * padded)
    share_p = outflow_p / counts_pad
    inflow = jnp.zeros((h, w), dtype)
    for dx, dy in offsets:
        inflow = inflow + lax.slice(
            share_p, (1 + dx, 1 + dy), (1 + dx + h, 1 + dy + w))
    return (padded[1:-1, 1:-1] - outflow_p[1:-1, 1:-1]) + inflow


# -- stateless per-step form (Model.make_step impl="active") -----------------

class ActiveDiffusionStep:
    """Stateless active-tile flow step for one channel: pad → activity →
    compact → active pass (or dense fallback, same step) → unpad. The
    form ``Model.make_step(impl="active")`` composes with point flows,
    partitions and substeps — activity is recomputed from the values
    each call, so any interleaved update (a point-flow deposit, a
    checkpoint restore) is seen next step. ``SerialExecutor``'s
    amortized runner is the fast path for whole runs (pads once,
    carries the tile map and buffers, and keeps the dense fallback out
    of the per-step path — this form pays a per-step ``lax.cond``
    buffer copy on top of the re-pad).

    ``dense_fn`` (values→values on the bare grid) is the same-step
    fallback — the fused Pallas kernel when the caller proved it runs
    here, else the dense XLA transport (bitwise with the XLA path)."""

    def __init__(self, shape: tuple[int, int], rate: float, dtype,
                 offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS,
                 origin: tuple[int, int] = (0, 0),
                 global_shape: Optional[tuple[int, int]] = None,
                 tile: Optional[tuple[int, int]] = None,
                 capacity: Optional[int] = None,
                 max_active_frac: float = 0.25,
                 dense_fn: Optional[Callable] = None):
        self.shape = tuple(shape)
        self.rate = float(rate)
        self.dtype = jnp.dtype(dtype)
        self.offsets = tuple((int(dx), int(dy)) for dx, dy in offsets)
        self.origin = (int(origin[0]), int(origin[1]))
        self.global_shape = (tuple(global_shape) if global_shape is not None
                             else self.shape)
        self.plan = plan_for(self.shape, tile=tile, capacity=capacity,
                             max_active_frac=max_active_frac)
        if dense_fn is None:
            def dense_fn(v, _s=self):
                counts = neighbor_counts_traced(
                    _s.shape, _s.offsets, _s.origin, _s.global_shape,
                    _s.dtype)
                return transport(
                    v, jnp.asarray(_s.rate, _s.dtype) * v, counts,
                    _s.offsets)
        self.dense_fn = dense_fn

    def __call__(self, v: jax.Array) -> jax.Array:
        plan = self.plan
        th, tw = plan.tile
        tmap = tile_nonzero_map(v, plan)
        flags = dilate_tile_map(tmap)
        count = jnp.sum(flags, dtype=jnp.int32)
        pred = count > np.int32(plan.fallback_tiles)

        def dense_branch(vv):
            return self.dense_fn(vv)

        def active_branch(vv):
            padded = jnp.pad(vv, 1)
            ids, cnt = compact_tile_ids(flags, plan)
            upd = jnp.zeros((plan.capacity, th, tw), self.dtype)
            padded, _, _ = active_pass(padded, upd, ids, cnt, self.rate,
                                       plan, self.origin,
                                       self.global_shape, self.offsets,
                                       self.dtype)
            return padded[1:-1, 1:-1]

        return lax.cond(pred, dense_branch, active_branch, v)


# -- the amortized whole-run runner (SerialExecutor / ensemble lanes) --------

def build_active_runner(shape: tuple[int, int], rates: dict,
                        offsets: Sequence[tuple[int, int]], dtype,
                        origin: tuple[int, int] = (0, 0),
                        global_shape: Optional[tuple[int, int]] = None,
                        plan: Optional[ActivePlan] = None,
                        dense_fns: Optional[dict] = None,
                        traced_rates: bool = False,
                        track_dirty: bool = False) -> Callable:
    """Whole-run active stepper: ``run(values, n[, rates_vec]) ->
    (values, (fallback_events, active_tiles_total))`` — or, with
    ``track_dirty=True``, ``(values, (fallback_events,
    active_tiles_total, dirty_map))`` where ``dirty_map`` is the bool
    ``[gi, gj]`` UNION over the whole run of every tile the engine
    wrote: the compacted active set on active steps (exactly the tiles
    the scatter touched), the ring-1 dilation of the pre-step nonzero
    map on dense-fallback steps (a dense Diffusion step can only change
    cells within distance 1 of pre-step mass). A guaranteed superset of
    the tiles whose bytes changed — the dirty-tile export the
    incremental checkpoint layer (``io.delta``) keys its delta records
    off, costing one [gi, gj] bool OR per step.

    Pads each flow channel ONCE, then carries ``(padded, tile_map,
    update_buffer)`` per channel across all ``n`` steps (a traced trip
    count — one compile serves every run length): per-step work is the
    tiny activity-map update plus O(active tiles), never O(grid), which
    is where the order-of-magnitude win over the dense path lives.
    Non-flow channels ride through untouched.

    Loop structure (measured, not aesthetic): consecutive ACTIVE steps
    run in an inner ``while_loop`` with no ``lax.cond`` anywhere on
    that path — XLA CPU copies a conditional's carried buffers between
    branch allocations every call (~130 ms/step for the padded grid at
    the 16384² bench geometry, 3x the entire active step), while
    while-loop carries alias in place. The dense fallback sits in the
    OUTER loop and is entered only on actual fallback events, so each
    step still independently takes the dense path iff its dilated
    count exceeds the threshold — same per-step contract, none of the
    per-step cond tax. Channels are independent under plain Diffusion,
    so each runs its own while-nest (bitwise identical to
    interleaving).

    ``rates`` maps attr → uniform rate (a float, or — with
    ``traced_rates=True``, the ensemble's per-lane form — an index list
    into the runner's ``rates_vec`` argument whose entries are summed).
    ``dense_fns`` maps attr → dense stepper for fallback steps (None →
    the bitwise XLA transport). Returned stats: ``fallback_events``
    counts (attr, step) pairs that fell back; ``active_tiles_total``
    sums the dilated active counts (for mean-activity reporting)."""
    shape = tuple(shape)
    gshape = tuple(global_shape) if global_shape is not None else shape
    offsets = tuple((int(dx), int(dy)) for dx, dy in offsets)
    dtype = jnp.dtype(dtype)
    if plan is None:
        plan = plan_for(shape)
    th, tw = plan.tile
    dense_fns = dense_fns or {}
    attrs = list(rates)

    def rate_of(attr, rates_vec):
        r = rates[attr]
        if traced_rates:
            acc = jnp.zeros((), rates_vec.dtype)
            for i in r:
                acc = acc + rates_vec[i]
            return acc
        return r

    thresh = np.int32(plan.fallback_tiles)

    def _dilated_count(tmap):
        flags = dilate_tile_map(tmap)
        return flags, jnp.sum(flags, dtype=jnp.int32)

    def run(values, n, rates_vec=None):
        counts = neighbor_counts_traced(shape, offsets, origin, gshape,
                                        dtype)
        fb = jnp.zeros((), jnp.int32)
        at = jnp.zeros((), jnp.float32)
        # dirty union rides the carries ONLY when tracked, so a
        # track_dirty=False build (the ensemble lanes) stays
        # program-identical to a pre-export build
        dm = (jnp.zeros(plan.grid, bool),) if track_dirty else ()
        out = dict(values)
        for a in attrs:
            rate = rate_of(a, rates_vec)

            # carry: (padded, tile_map, upd, steps_done, fb, at[, dirty])
            def inner_cond(c, _n=n):
                _, cnt = _dilated_count(c[1])
                return (c[3] < _n) & (cnt <= thresh)

            def inner_body(c, _rate=rate):
                p, tm, u, i, fb_, at_, *dm_ = c
                flags, cnt = _dilated_count(tm)
                ids, _ = compact_tile_ids(flags, plan)
                p2, u2, anyf = active_pass(p, u, ids, cnt, _rate, plan,
                                           origin, gshape, offsets, dtype)
                if track_dirty:
                    # the scatter wrote exactly the flagged tiles
                    dm_ = (dm_[0] | flags,)
                return (p2, next_tile_map(anyf, ids, cnt, plan), u2,
                        i + 1, fb_, at_ + cnt.astype(jnp.float32), *dm_)

            def outer_body(c, _a=a, _rate=rate, _n=n):
                c = lax.while_loop(inner_cond, inner_body, c)
                p, tm, u, i, fb_, at_, *dm_ = c

                # the inner loop exited: either the run is done, or this
                # step's dilated count crossed the threshold — run the
                # DENSE step for it (one cond per fallback EVENT, so the
                # buffer-copy tax never lands on the active fast path)
                def dense_step(args):
                    pp, tm_, i_, fb__, at__, *dm__ = args
                    _, cnt = _dilated_count(tm_)
                    fn = dense_fns.get(_a)
                    if fn is not None:
                        p2 = jnp.pad(fn(pp[1:-1, 1:-1]), 1)
                    else:
                        p2 = dense_from_padded(pp, _rate, counts, offsets,
                                               dtype)
                    if track_dirty:
                        # a dense Diffusion step changes cells only
                        # within distance 1 of pre-step mass: the ring-1
                        # tile dilation of the pre-step map bounds them
                        dm__ = (dm__[0] | dilate_tile_map(tm_),)
                    return (p2, tile_nonzero_map(p2[1:-1, 1:-1], plan),
                            i_ + 1, fb__ + 1,
                            at__ + cnt.astype(jnp.float32), *dm__)

                p, tm, i, fb_, at_, *dm_ = lax.cond(
                    i < _n, dense_step, lambda args: args,
                    (p, tm, i, fb_, at_, *dm_))
                return (p, tm, u, i, fb_, at_, *dm_)

            c = lax.while_loop(
                lambda c, _n=n: c[3] < _n, outer_body,
                (jnp.pad(values[a], 1), tile_nonzero_map(values[a], plan),
                 jnp.zeros((plan.capacity, th, tw), dtype),
                 jnp.zeros((), jnp.int32), fb, at, *dm))
            padded, _, _, _, fb, at, *dm = c
            out[a] = padded[1:-1, 1:-1]
            dm = tuple(dm)
        if track_dirty:
            return out, (fb, at, dm[0])
        return out, (fb, at)

    return run
