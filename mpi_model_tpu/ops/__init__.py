from .composed_stencil import (
    ComposedDiffusionStep,
    choose_k,
    composed_dense_step,
    composed_halo_step,
    composed_taps,
)
from .flow import Coupled, Diffusion, Exponencial, Flow, PointFlow, build_outflow
from .pallas_active import (
    FusedActiveStep,
    build_fused_runner,
    choose_fused_k,
    fused_active_pass,
)
from .pallas_stencil import (
    PallasDiffusionStep,
    PallasFieldStep,
    pallas_dense_step,
    pallas_field_halo_step,
    pallas_halo_step,
)
from .stencil import flow_step, point_flow_step, shift2d, transport

__all__ = [
    "Flow",
    "Exponencial",
    "PointFlow",
    "Diffusion",
    "Coupled",
    "build_outflow",
    "shift2d",
    "transport",
    "flow_step",
    "point_flow_step",
    "pallas_dense_step",
    "pallas_halo_step",
    "pallas_field_halo_step",
    "PallasDiffusionStep",
    "PallasFieldStep",
    "ComposedDiffusionStep",
    "composed_dense_step",
    "composed_halo_step",
    "composed_taps",
    "choose_k",
    "FusedActiveStep",
    "build_fused_runner",
    "choose_fused_k",
    "fused_active_pass",
]
