"""Point-subsystem fast path: step only the cells a point flow touches.

The reference's live workload is ONE point flow on a 100×100 grid
(``/root/reference/src/Main.cpp:32-33``): per step, exactly the source
cell and its ≤8 Moore neighbors change (``Model.hpp:176-235``) — yet a
naive compiled loop carries the whole O(grid) array through every
µs-scale step, which is why tiny configs lost to a single-core NumPy
loop (round-3 VERDICT weak #3). This module extracts the *involved
subsystem* — the static union of sources and their in-partition
neighbors, m ≤ 9·k cells — steps an ``[m+1]``-vector in the compiled
loop (the ``+1`` is a dummy slot absorbing dropped shares), and scatters
the result back into the grid ONCE per run.

Faithfulness: the common case — every touched cell receives exactly one
contribution per step (any number of non-overlapping frozen flows; the
reference's exact workload) — collapses each step to one ``[m+1]``
vector add whose entries are the full path's own per-step values, so
results are BITWISE identical to the full-grid path
(``ops.stencil.point_flow_step``). The sequenced branches (overlapping
neighborhoods, dynamic amounts) perform the same logical operations but
XLA may reassociate the small-vector chains differently than the
full-grid scatters: they match to ≤1 ULP per step — the same fidelity
class as the deep-halo general path (``executors._build_deep_runner``),
and well inside the conservation contract. Golden tests pin both tiers.

Eligibility (``build_point_plans`` returns None otherwise):
- every flow is a ``PointFlow`` (any field flow touches O(grid) cells);
- float dtype;
- sharded use additionally requires every flow frozen (a dynamic
  amount reads the owner shard's source value, which other shards do
  not hold — and with frozen amounts NO halo exchange is needed at
  all: each shard updates its owned involved cells locally).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.cellular_space import CellularSpace
from .flow import PointFlow

#: fall back to the full-grid path beyond this many point flows: the
#: subsystem stops being "tiny" and the full path's vectorized scatters
#: amortize better
MAX_FLOWS = 64


@dataclasses.dataclass
class PointPlan:
    """Per-attribute involved-cell subsystem (all coords LOCAL to the
    space's array; validity/topology already folded in at build time)."""

    attr: str
    #: [m] involved-cell local coords (unique, deterministic order)
    xs: np.ndarray
    ys: np.ndarray
    #: all-frozen single-add fast path: sub += delta per step ([m+1])
    delta: Optional[np.ndarray]
    #: all-frozen, per-phase-distinct targets: sequence of [m+1] adds
    phase_deltas: Optional[list[np.ndarray]]
    #: general (dynamic flows): vectorized per-flow spec
    dyn: Optional[dict]

    @property
    def m(self) -> int:
        return len(self.xs)

    @property
    def frozen_only(self) -> bool:
        return self.dyn is None


def _neighbor_count(gx: int, gy: int, gdx: int, gdy: int, offsets) -> int:
    return sum(1 for dx, dy in offsets
               if 0 <= gx + dx < gdx and 0 <= gy + dy < gdy)


def build_point_plans(flows: Sequence, space: CellularSpace,
                      offsets: Sequence[tuple[int, int]],
                      ) -> Optional[dict[str, PointPlan]]:
    """Static subsystem extraction; None when the model is ineligible."""
    if not flows or len(flows) > MAX_FLOWS:
        return None
    if not all(isinstance(f, PointFlow) for f in flows):
        return None
    dtype = np.dtype(jnp.dtype(space.dtype))
    if not jnp.issubdtype(space.dtype, jnp.floating):
        return None

    h, w = space.dim_x, space.dim_y
    gdx, gdy = space.global_shape
    x0, y0 = space.x_init, space.y_init

    by_attr: dict[str, list[PointFlow]] = {}
    for f in flows:
        lx, ly = f.source_xy[0] - x0, f.source_xy[1] - y0
        if 0 <= lx < h and 0 <= ly < w:  # owner test (Model.hpp:176)
            by_attr.setdefault(f.attr, []).append(f)

    plans: dict[str, PointPlan] = {}
    for attr, pflows in by_attr.items():
        # entry table: unique local cells, sources first then neighbors,
        # in flow×offset order (determinism = stable cache keys)
        index: dict[tuple[int, int], int] = {}

        def entry(lx: int, ly: int) -> int:
            return index.setdefault((lx, ly), len(index))

        spec = []  # per flow: (src_entry, amt_or_None, rate, count, tgts)
        for f in pflows:
            lx, ly = f.source_xy[0] - x0, f.source_xy[1] - y0
            src_e = entry(lx, ly)
            count = _neighbor_count(lx + x0, ly + y0, gdx, gdy, offsets)
            # frozen amount with the full path's exact rounding: python
            # f64 product, then one cast to the grid dtype
            amt = (dtype.type(f.flow_rate * f.frozen_source_value)
                   if f.frozen_source_value is not None else None)
            tgts = []
            for dx, dy in offsets:
                nx, ny = lx + dx, ly + dy
                # delivery is LOCAL-bounds (shares leaving the partition
                # drop, reference-worker semantics); counts were GLOBAL
                tgts.append(entry(nx, ny) if 0 <= nx < h and 0 <= ny < w
                            else None)
            spec.append((src_e, amt, f.flow_rate, count, tgts))

        m = len(index)
        xs = np.fromiter((c[0] for c in index), np.int32, m)
        ys = np.fromiter((c[1] for c in index), np.int32, m)

        all_frozen = all(s[1] is not None for s in spec)
        delta = phase_deltas = dyn = None
        if all_frozen:
            # contribution list in full-path op order: one source-phase
            # scatter, then one scatter per offset
            phases: list[list[tuple[int, np.generic]]] = []
            phases.append([(s[0], dtype.type(-s[1])) for s in spec])
            for oi in range(len(offsets)):
                ph = []
                for src_e, amt, _rate, count, tgts in spec:
                    if tgts[oi] is not None:
                        ph.append((tgts[oi], dtype.type(amt
                                                        / dtype.type(count))))
                phases.append(ph)
            flat = [t for ph in phases for t, _ in ph]
            if len(set(flat)) == len(flat):
                # every touched cell gets exactly one add per step →
                # the whole step is ONE vector add (0.0 elsewhere)
                delta = np.zeros(m + 1, dtype)
                for ph in phases:
                    for t, v in ph:
                        delta[t] = v
            elif all(len({t for t, _ in ph}) == len(ph) for ph in phases):
                phase_deltas = []
                for ph in phases:
                    d = np.zeros(m + 1, dtype)
                    for t, v in ph:
                        d[t] = v
                    phase_deltas.append(d)
            # duplicate targets inside one phase: scatter-add combine
            # order is the full path's business — fall through to dyn
        if delta is None and phase_deltas is None:
            dyn = dict(
                src=np.asarray([s[0] for s in spec], np.int32),
                frozen=np.asarray([s[1] is not None for s in spec]),
                const_amt=np.asarray(
                    [s[1] if s[1] is not None else 0 for s in spec], dtype),
                rate=np.asarray([s[2] for s in spec], dtype),
                count=np.asarray([s[3] for s in spec], dtype),
                # [n_offsets, k]: entry index, dummy m when dropped
                tgt=np.asarray([[s[4][oi] if s[4][oi] is not None else m
                                 for s in spec]
                                for oi in range(len(offsets))], np.int32),
                valid=np.asarray([[s[4][oi] is not None for s in spec]
                                  for oi in range(len(offsets))]),
            )
        plans[attr] = PointPlan(attr, xs, ys, delta, phase_deltas, dyn)
    return plans


def subsystem_step(plan: PointPlan, dtype):
    """The per-step function on the ``[m+1]`` subsystem vector —
    bitwise-parallel to ``point_flow_step`` on the full grid."""
    if plan.delta is not None:
        d = jnp.asarray(plan.delta)

        def step(sub):
            return sub + d
        return step
    if plan.phase_deltas is not None:
        ds = [jnp.asarray(d) for d in plan.phase_deltas]

        def step(sub):
            for d in ds:
                sub = sub + d
            return sub
        return step

    dyn = plan.dyn
    src = jnp.asarray(dyn["src"])
    frozen = jnp.asarray(dyn["frozen"])
    const_amt = jnp.asarray(dyn["const_amt"])
    rate = jnp.asarray(dyn["rate"])
    count = jnp.asarray(dyn["count"])
    tgt = jnp.asarray(dyn["tgt"])
    valid = jnp.asarray(dyn["valid"])
    zero = jnp.zeros((), dtype)

    def step(sub):
        # amounts read the PRE-step values (summed-outflow semantics)
        amts = jnp.where(frozen, const_amt, rate * sub[src])
        share = amts / count
        out = sub.at[src].add(-amts)
        for oi in range(tgt.shape[0]):
            out = out.at[tgt[oi]].add(jnp.where(valid[oi], share, zero))
        return out
    return step


def serial_point_runner(plans: dict[str, PointPlan], dtype):
    """(values, n) → values: gather each attr's subsystem, loop n tiny
    steps, scatter back once. Jit-compatible; n is a traced scalar."""
    steps = {a: subsystem_step(p, dtype) for a, p in plans.items()}

    def run(values, n):
        new = dict(values)
        for attr, plan in plans.items():
            xs, ys = jnp.asarray(plan.xs), jnp.asarray(plan.ys)
            sub = jnp.concatenate([values[attr][xs, ys],
                                   jnp.zeros((1,), dtype)])
            step = steps[attr]
            sub = jax.lax.fori_loop(0, n, lambda i, s, f=step: f(s), sub)
            new[attr] = values[attr].at[xs, ys].set(sub[:plan.m])
        return new
    return run


def shard_point_runner(plans: dict[str, PointPlan], dtype,
                       local_h: int, local_w: int):
    """Per-shard subsystem runner (all flows frozen): every shard evolves
    the full entry table (constant deltas — no communication, ever) and
    scatters back only the entries it owns; non-owned gathers are clipped
    garbage that dies in the dummy pad cell. Returns
    ``(values, shard_off_x, shard_off_y, n) → values`` for use inside
    ``shard_map`` (offsets are ``axis_index``-derived traced scalars)."""
    assert all(p.frozen_only for p in plans.values())
    steps = {a: subsystem_step(p, dtype) for a, p in plans.items()}

    def run(values, off_x, off_y, n):
        new = dict(values)
        for attr, plan in plans.items():
            sx = jnp.asarray(plan.xs) - off_x
            sy = jnp.asarray(plan.ys) - off_y
            owned = ((sx >= 0) & (sx < local_h)
                     & (sy >= 0) & (sy < local_w))
            sxc = jnp.clip(sx, 0, local_h - 1)
            syc = jnp.clip(sy, 0, local_w - 1)
            sub = jnp.concatenate([values[attr][sxc, syc],
                                   jnp.zeros((1,), dtype)])
            step = steps[attr]
            sub = jax.lax.fori_loop(0, n, lambda i, s, f=step: f(s), sub)
            padded = jnp.pad(values[attr], ((0, 1), (0, 1)))
            px = jnp.where(owned, sxc, local_h)
            py = jnp.where(owned, syc, local_w)
            padded = padded.at[px, py].set(sub[:plan.m])
            new[attr] = padded[:local_h, :local_w]
        return new
    return run
