"""Composed k-step stencil filters: break the radius-1 ceiling.

BASELINE.md's round-5 roofline investigation measured ~3.2 ms/step as
the architectural bound for any *radius-1* fused stencil on this chip's
VPU — ~76% of the slots go to the ±1 slice shifts, work proportional to
the useful flops — and concluded that beating it "needs a different op
— larger effective radius per pass via higher-order composed filters —
not a better schedule of this one".

This module is that op. For uniform-rate Diffusion — the config-5
workload, and the linear update rule of the reference
(``/root/reference/src/Model.hpp:176-235``) — the flow step on interior
cells is a LINEAR operator:

    S = (1 - rate) * δ + (rate / k') * N        (k' = |offsets|,
                                                 N = neighbor-sum)

so k applications compose into ONE pass of the k-fold filter ``S^k``,
an explicit ``(2k+1) x (2k+1)`` tap table — algebraically exact on
cells at distance >= k from the true grid edge. The near-boundary band
(distance < k, where the per-cell divisor corrections make the operator
spatially varying) is NOT composable; it keeps the exact iterated
radius-1 path via the kernels' existing near/interior split
(``ops.pallas_stencil._stencil_call``'s ``interior_fn`` hook replaces
only the interior branch).

Two lowerings of the composed filter:

- ``variant="vpu"``: the binomial factorization.  δ and N commute, so
  ``S^k = Σ_j C(k,j) (1-rate)^(k-j) (rate/k')^j N^j`` — the
  neighborhood-sum powers ``N^j`` are built iteratively (for Moore-8
  the box-power form ``S = α δ + β B``, ``B`` the separable 3x3 sum,
  is used instead: 4 shift-adds per power instead of 8) and blended
  with precomputed f64 coefficients. Shift-slot count is ~identical to
  k iterated steps — this variant measures whether dropping the
  per-step multiplies and round-trips through the output registers
  buys anything on the VPU (the slot accounting in BASELINE.md predicts
  it cannot, which is half the point: the null must be measured).
- ``variant="mxu"``: the lane-direction banded contraction, retested at
  the tap counts where round 5 predicted it starts to pay. For each of
  the ``2k+1`` sublane offsets, the row's 1-D taps become a banded
  ``(128 + 2k, 128)`` matrix applied per 128-lane output block with an
  f32-accumulating ``dot`` — at 3 taps the 128-wide contraction wastes
  43/45ths of the MXU (round 5 measured 1.08x); at 9-17 taps the waste
  factor drops 3-6x and the flops/cell-step settle near
  ``2·(128+2k)·(2k+1)/k`` ≈ 550-620, constant in k. The ±k sublane
  shifts ride the cheap direction.

Tap tables are composed once per ``(rate, offsets, k)`` in f64 and
cached by fingerprint (mirroring ``ops.flow.Flow.fingerprint``'s
hashable-key design); the interior hooks are cached on the same key so
``jax.jit``'s static ``interior_fn`` argument sees a stable identity
and never retraces a geometry twice.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.cell import MOORE_OFFSETS
from .pallas_stencil import (
    LANE,
    _pallas_halo_step,
    _pallas_step,
    _pick_block,
    _sublane,
    _validate_block,
    check_offsets,
    resolve_interpret,
)

#: tap count from which the MXU banded contraction is preferred by
#: ``variant="auto"`` — the round-5 break-even analysis: below 9 taps
#: the 128-wide contraction's waste factor eats the MXU's flop
#: advantage (measured 0.85-1.08x at 3 taps)
MXU_MIN_TAPS = 9


# -- tap-table composition (cached by fingerprint) ---------------------------

_TAPS_CACHE: dict[tuple, np.ndarray] = {}


def taps_fingerprint(rate: float, offsets: Sequence[tuple[int, int]],
                     k: int) -> tuple:
    """Hashable identity of a composed tap table — the cache key, same
    design as ``Flow.fingerprint`` (hashable tuples of scalars)."""
    return (float(rate), tuple((int(dx), int(dy)) for dx, dy in offsets),
            int(k))


def _conv2_full(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Full 2-D convolution (f64) — table composition needs no scipy."""
    ha, wa = a.shape
    hb, wb = b.shape
    out = np.zeros((ha + hb - 1, wa + wb - 1), np.float64)
    for p in range(ha):
        for q in range(wa):
            if a[p, q] != 0.0:
                out[p:p + hb, q:q + wb] += a[p, q] * b
    return out


def composed_taps(rate: float, offsets: Sequence[tuple[int, int]],
                  k: int) -> np.ndarray:
    """The ``(2k+1, 2k+1)`` f64 tap table of ``S^k``.

    Correlation with table A then table B equals correlation with the
    plain convolution ``A * B`` (shift algebra; holds for asymmetric
    neighborhoods too), so the k-step table is the k-fold
    self-convolution of the one-step table. Taps sum to 1 up to f64
    rounding — each step conserves interior mass, so the composition
    does. Returns a cached array; treat it as read-only."""
    offsets = check_offsets(offsets)
    k = int(k)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    key = taps_fingerprint(rate, offsets, k)
    cached = _TAPS_CACHE.get(key)
    if cached is not None:
        return cached
    w1 = np.zeros((3, 3), np.float64)
    w1[1, 1] = 1.0 - float(rate)
    for dx, dy in offsets:
        w1[1 + dx, 1 + dy] += float(rate) / len(offsets)
    wk = w1
    for _ in range(k - 1):
        wk = _conv2_full(w1, wk)
    wk.setflags(write=False)
    _TAPS_CACHE[key] = wk
    return wk


# -- interior hooks ----------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _interior_hook(rate: float, offsets: tuple, k: int, variant: str,
                   compute_dtype_str: str):
    """Build (and cache — jit staticness needs a stable identity) the
    interior-tile hook for ``_stencil_call``: region ``(bh+2k, bw+2k)``
    in ``compute_dtype`` → output ``(bh, bw)``, one composed pass."""
    cdt = jnp.dtype(compute_dtype_str)
    if variant == "vpu":
        return _make_vpu_hook(rate, offsets, k)
    if variant == "mxu":
        return _make_mxu_hook(rate, offsets, k, cdt)
    raise ValueError(f"unknown composed variant {variant!r}")


def _make_vpu_hook(rate: float, offsets: tuple, k: int):
    kk = len(offsets)
    moore = set(offsets) == set(MOORE_OFFSETS)
    # Moore: S = α δ + β B with B the FULL 3x3 box (separable band
    # trick, centre included), α = 1 - rate - rate/8. Other
    # neighborhoods: S = (1-rate) δ + (rate/k') N with N the plain
    # neighbor sum. Both commute with δ, so the binomial expansion is
    # exact; coefficients are composed in f64 at build time.
    if moore:
        alpha = 1.0 - rate - rate / kk
    else:
        alpha = 1.0 - rate
    beta = rate / kk
    coefs = [math.comb(k, j) * (alpha ** (k - j)) * (beta ** j)
             for j in range(k + 1)]

    def hook(region):
        mh, mw = region.shape
        acc = coefs[0] * region[k:mh - k, k:mw - k]
        cur = region
        for j in range(1, k + 1):
            hs, ws = cur.shape
            if moore:
                band = cur[0:hs - 2, :] + cur[1:hs - 1, :] + cur[2:hs, :]
                cur = (band[:, 0:ws - 2] + band[:, 1:ws - 1]
                       + band[:, 2:ws])
            else:
                nxt = None
                for dx, dy in offsets:
                    t = cur[1 + dx:hs - 1 + dx, 1 + dy:ws - 1 + dy]
                    nxt = t if nxt is None else nxt + t
                cur = nxt
            m = k - j
            hs, ws = cur.shape
            acc = acc + coefs[j] * cur[m:hs - m, m:ws - m]
        return acc

    return hook


def _make_mxu_hook(rate: float, offsets: tuple, k: int, cdt):
    taps = composed_taps(rate, offsets, k)

    def hook(region):
        mh, mw = region.shape
        bh, bw = mh - 2 * k, mw - 2 * k
        if bw % LANE != 0:
            raise ValueError(
                f"the MXU composed variant contracts per {LANE}-lane "
                f"output block; block width {bw} is not a multiple "
                f"of {LANE} (use variant='vpu' or a {LANE}-aligned "
                "block)")
        # banded matrices are built once per tile from iotas — band
        # construction is ~1% of the contraction flops and keeps the
        # taps out of the operand list. d_i = m - c picks the diagonal:
        # out[r, c] = Σ_m slab[r, m] · band[m, c] with
        # band[m, c] = taps[k+dr, m - c] on the 0..2k band.
        m_i = lax.broadcasted_iota(jnp.int32, (LANE + 2 * k, LANE), 0)
        c_i = lax.broadcasted_iota(jnp.int32, (LANE + 2 * k, LANE), 1)
        d_i = m_i - c_i
        acc = None
        for dr in range(-k, k + 1):
            band = jnp.zeros((LANE + 2 * k, LANE), jnp.float32)
            for dc in range(2 * k + 1):
                band = band + jnp.where(d_i == dc,
                                        float(taps[k + dr, dc]), 0.0)
            band = band.astype(cdt)
            rows = region[k + dr:k + dr + bh, :]
            blocks = []
            for b in range(bw // LANE):
                slab = rows[:, b * LANE:b * LANE + LANE + 2 * k]
                # bf16 compute_dtype → native bf16 MXU inputs; the
                # accumulator stays f32 either way
                blocks.append(jnp.dot(
                    slab, band, preferred_element_type=jnp.float32))
            part = (jnp.concatenate(blocks, axis=1) if len(blocks) > 1
                    else blocks[0])
            acc = part if acc is None else acc + part
        return acc

    return hook


def _resolve_variant(variant: str, k: int, bw: int) -> str:
    if variant not in ("auto", "vpu", "mxu"):
        raise ValueError(f"unknown composed variant {variant!r}")
    if variant == "auto":
        return ("mxu" if (2 * k + 1) >= MXU_MIN_TAPS and bw % LANE == 0
                else "vpu")
    return variant


# -- k selection -------------------------------------------------------------

def max_k(shape: tuple[int, int], dtype,
          block: Optional[tuple[int, int]] = None) -> int:
    """Deepest composable k for this geometry: the window's ghost depth
    ``min(hr, hc)`` — 8 rows f32 / 16 bf16 at default blocks (the same
    bound the iterated multi-step kernel obeys)."""
    h, w = shape
    sub = _sublane(dtype)
    if block is None:
        block = (_pick_block(h, 512, sub), _pick_block(w, 512, LANE))
    else:
        block = _validate_block(h, w, block)
    return min(sub, block[0], LANE, block[1])


def choose_k(substeps: int, shape: tuple[int, int], dtype,
             block: Optional[tuple[int, int]] = None) -> int:
    """Largest divisor of ``substeps`` that the window geometry can
    compose — the auto-k rule for ``impl="composed"``: a scan of
    ``substeps`` flow steps then runs as ``substeps/k`` composed passes
    with no remainder step."""
    substeps = int(substeps)
    if substeps < 1:
        raise ValueError(f"substeps must be >= 1, got {substeps}")
    cap = max_k(shape, dtype, block)
    for k in range(min(substeps, cap), 0, -1):
        if substeps % k == 0:
            return k
    return 1


# -- public steps ------------------------------------------------------------

def composed_dense_step(
    values: jax.Array,
    rate: float,
    k: int,
    offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS,
    block: Optional[tuple[int, int]] = None,
    interpret: Optional[bool] = None,
    variant: str = "auto",
    compute_dtype=None,
) -> jax.Array:
    """``k`` uniform-rate flow steps as ONE composed-filter pass.

    Semantics: exactly ``pallas_dense_step(values, rate, nsteps=k)`` —
    interior cells get the single ``(2k+1)²``-tap pass (algebraically
    equal to the k iterated steps; floating-point grouping differs by
    ~k ulp), the near-boundary band gets the exact iterated masked
    radius-1 path, and the conservation contract holds to the same
    tolerances. ``variant`` picks the interior lowering (module
    docstring); ``"auto"`` = MXU at >= 9 taps on 128-aligned blocks,
    VPU otherwise."""
    offsets = check_offsets(offsets)
    k = int(k)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    h, w = values.shape
    if interpret is None:
        interpret = resolve_interpret(values)
    if block is None:
        sub = _sublane(values.dtype)
        block = (_pick_block(h, 512, sub), _pick_block(w, 512, LANE))
    else:
        block = _validate_block(h, w, block)
    var = _resolve_variant(variant, k, block[1])
    cdt = jnp.dtype(compute_dtype or jnp.float32)
    hook = _interior_hook(float(rate), offsets, k, var, str(cdt))
    return _pallas_step(values, rate=float(rate), block=tuple(block),
                        offsets=offsets, interpret=bool(interpret),
                        nsteps=k, compute_dtype=cdt, interior_fn=hook)


def composed_halo_step(
    values: jax.Array,
    ring: dict,
    origin: jax.Array,
    global_shape: tuple[int, int],
    rate: float,
    k: int,
    offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS,
    block: Optional[tuple[int, int]] = None,
    interpret: Optional[bool] = None,
    variant: str = "auto",
    compute_dtype=None,
) -> jax.Array:
    """The sharded form: ``k`` flow steps as one composed pass consuming
    a depth->=k ppermute ghost ring (``parallel.halo.exchange_ring``) —
    one collective round AND one composed pass per k steps. Semantics
    match ``pallas_halo_step(..., nsteps=k)``; near-global-edge tiles
    keep the exact iterated path (origin-aware, like the iterated halo
    kernel)."""
    offsets = check_offsets(offsets)
    k = int(k)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    h, w = values.shape
    d = int(ring["n"].shape[0])
    if interpret is None:
        interpret = resolve_interpret(values)
    if block is None:
        sub = _sublane(values.dtype)
        block = (_pick_block(h, 512, sub), _pick_block(w, 512, LANE))
    else:
        block = _validate_block(h, w, block)
    hr = min(_sublane(values.dtype), block[0])
    hc = min(LANE, block[1])
    if d > min(hr, hc):
        raise ValueError(
            f"ring depth {d} exceeds the slab capacity min(hr={hr}, "
            f"hc={hc}) for block {tuple(block)}")
    if k > d:
        raise ValueError(
            f"k={k} needs a ghost ring at least that deep; got depth {d} "
            f"(exchange_ring(..., depth={k}))")
    var = _resolve_variant(variant, k, block[1])
    cdt = jnp.dtype(compute_dtype or jnp.float32)
    hook = _interior_hook(float(rate), offsets, k, var, str(cdt))
    origin = jnp.asarray(origin, jnp.int32)
    return _pallas_halo_step(
        values, ring["n"], ring["s"], ring["w"], ring["e"],
        ring["nw"], ring["ne"], ring["sw"], ring["se"], origin,
        rate=float(rate), block=tuple(block), offsets=offsets,
        interpret=bool(interpret), global_shape=tuple(global_shape),
        nsteps=k, compute_dtype=cdt, interior_fn=hook)


class ComposedDiffusionStep:
    """Reusable composed stepper bound to one geometry/rate: each call
    advances ``k`` flow steps in one pass (the composed counterpart of
    ``PallasDiffusionStep`` with ``nsteps=k``)."""

    def __init__(self, shape: tuple[int, int], rate: float, k: int,
                 dtype=jnp.float32,
                 offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS,
                 block: Optional[tuple[int, int]] = None,
                 interpret: Optional[bool] = None,
                 variant: str = "auto", compute_dtype=None):
        self.shape = tuple(shape)
        self.rate = float(rate)
        self.k = int(k)
        self.offsets = check_offsets(offsets)
        self.block = block
        self.interpret = interpret
        self.variant = variant
        self.compute_dtype = compute_dtype
        if self.k > max_k(self.shape, dtype, block):
            raise ValueError(
                f"k={self.k} exceeds the window ghost depth "
                f"{max_k(self.shape, dtype, block)} for shape "
                f"{self.shape} dtype {jnp.dtype(dtype)} block {block}")

    def __call__(self, values: jax.Array) -> jax.Array:
        return composed_dense_step(
            values, self.rate, self.k, self.offsets, self.block,
            self.interpret, variant=self.variant,
            compute_dtype=self.compute_dtype)
