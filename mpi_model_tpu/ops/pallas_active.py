"""Fused Pallas active-tile kernel: sparse streaming + composed-k passes.

ROADMAP direction 2 ("roofline round 2"). The XLA active engine
(``ops.active``, PR 3) wins 18.1x at 1% activity, but every step still
pays a full gather/scatter round-trip through HBM — the compacted tile
windows are materialized by ``lax.dynamic_slice`` one at a time, the
updates land in a ``[K, th, tw]`` buffer, and XLA serializes the whole
thing through its fori_loop. This module moves the sparse iteration
INTO the Pallas layer:

- the compacted ``[K]`` active-tile index buffer is **scalar-prefetched**
  (``pltpu.PrefetchScalarGridSpec``) so tile coordinates are available
  to the DMA engine before the kernel body runs;
- each active tile's halo window streams HBM→VMEM with the same
  **double-buffered DMA discipline** as ``_stencil_call`` (lane ``l+1``'s
  window is in flight while lane ``l`` computes);
- the transport update is computed **in VMEM**, and the NEXT step's
  per-tile activity flag (``any(tile_out != 0)``) is computed **inside
  the same kernel pass** on the tile still resident in VMEM — the
  separate per-lane flag reduction of the XLA path (an extra read of
  the update buffer) is gone, which the jaxpr contract auditor asserts
  (``jaxpr-fused-flags``);
- a second tiny **scatter pass** (aliased output,
  ``input_output_aliases``) lands the updates back in the padded state;
  splitting compute from scatter is what makes every window read
  observe PRE-step values — the same all-reads-before-all-writes
  invariant ``ops.active.active_pass`` enforces with its two loops.

**Composed-k active** (``k > 1``): one tile-resident pass advances ``k``
flow steps — the PR 1 composed tap table on interior, self-lit tiles
(``(2k+1)²`` taps, one pass), and the **exact iterated path** on
near-global-edge and frontier (dilated-in, self-zero) tiles, so the
bitwise activation/boundary gates hold. The window carries a ring-k
halo (``k <= min(th, tw)`` keeps ring-1 tile dilation exact: mass moves
k <= tile cells per pass, so a tile still activates one pass before
flux can arrive). This multiplies arithmetic intensity by k exactly
where the dense roofline analysis says the kernel is bandwidth-bound.

Exactness contract (the PR 3 discipline, extended):

- ``k == 1``: the pass is **bitwise equal** to ``ops.active.active_pass``
  — and hence to the dense XLA step — at every dtype (the kernel
  mirrors the transport expression term for term, barrier included,
  with neighbor counts from global coordinates; proven at f64 and f32
  in ``tests/test_active_fused.py``).
- ``k > 1``: frontier and near-edge tiles run the iterated expression
  on the shrinking in-window region, which is bitwise equal to ``k``
  dense steps; interior tap tiles are algebraically equal (the PR 1
  composed-filter contract — ~k-ulp regrouping). Skipped tiles stay
  exactly zero either way.

Tier-1 proves all of this in interpret mode (the kernels trace to the
same XLA ops the oracle runs); the silicon row is a standing
pending-silicon item in ROADMAP.md. On silicon, note the padded-layout
window offsets are not (sublane, lane)-aligned — the Mosaic build will
want the aligned over-fetch treatment ``_stencil_call`` uses (tracked
with the pending-silicon item, not a correctness concern).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..compat import HBM as _HBM, prefetch_scalar_grid_spec
from ..core.cell import MOORE_OFFSETS
from .active import (
    ActivePlan,
    compact_tile_ids,
    dilate_tile_map,
    ghost_flags,
    next_tile_map,
    plan_for,
    tile_nonzero_map,
)
from .stencil import neighbor_counts_traced, transport

#: hard cap on the composed pass depth — the window is (th+2k, tw+2k),
#: so beyond this the VMEM window stops resembling the tile it serves;
#: also bounds the tap table at 33² taps
MAX_FUSED_K = 16


def choose_fused_k(substeps: int, plan: ActivePlan) -> int:
    """Largest divisor of ``substeps`` the tile geometry can compose:
    ``k <= min(th, tw)`` (the ring-1 dilation exactness bound — mass
    moves k cells per pass and must not cross a whole tile) and
    ``k <= MAX_FUSED_K``. Degrades to 1 (every pass = one step) when
    ``substeps`` has no such divisor — the clean-degradation contract
    the auditor's ``k·passes == substeps`` check rides on."""
    substeps = int(substeps)
    if substeps < 1:
        raise ValueError(f"substeps must be >= 1, got {substeps}")
    cap = min(plan.tile[0], plan.tile[1], MAX_FUSED_K)
    for k in range(min(substeps, cap), 0, -1):
        if substeps % k == 0:
            return k
    return 1


def pass_count(steps: int, k: int) -> int:
    """How many passes ``build_fused_runner`` executes PER ATTRIBUTE
    for ``steps`` flow steps at depth ``k``: ``steps // k`` full-depth
    passes plus ``steps % k`` depth-1 remainder passes. THE one copy of
    the split — every report that normalizes the runner's per-pass
    counters (fallback_steps, flags_fused, active-tile sums, which all
    accumulate (attr, pass) pairs across the live attributes) derives
    the denominator here, so the accounting identity
    ``flags_fused + fallback_steps == pass_count(n, k) × live attrs``
    cannot drift from the loop structure."""
    steps, k = int(steps), int(k)
    return steps // k + steps % k


def _fused_taps(rate: float, offsets: tuple, k: int) -> Optional[np.ndarray]:
    """The PR 1 composed tap table for the interior fast path (None at
    k=1: the single-step table is algebraically the explicit expression
    but not bitwise it, and k=1 must stay bitwise everywhere)."""
    if k <= 1:
        return None
    from .composed_stencil import composed_taps
    return composed_taps(rate, offsets, k)


# -- the fused pass (two pallas_calls: compute+flags, aliased scatter) -------

def _fused_compute_call(padded, ids, cnt1, selfnz, origin, *, rate, plan,
                        global_shape, offsets, dtype, k, ring, taps,
                        interpret):
    """Pallas pass 1: stream each active tile's ring-``k`` window from
    the ring-``ring`` padded state (``ring >= k``; remainder passes run
    ``k < ring`` on the same buffer), compute ``k`` transport steps in
    VMEM, and emit ``(upd [K, th, tw], anyf [K])`` — the per-lane
    any-nonzero flags computed on the tile still resident in VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (th, tw), (gi, gj) = plan.tile, plan.grid
    K = plan.capacity
    H, W = global_shape
    wh, ww = th + 2 * k, tw + 2 * k
    off = ring - k  # window offset into the (possibly deeper) padding
    _i32 = np.int32
    tap_list = (None if taps is None else
                [(dr, dc, float(taps[dr, dc]))
                 for dr in range(2 * k + 1) for dc in range(2 * k + 1)])

    def kernel(ids_ref, cnt_ref, self_ref, orig_ref, rate_ref, pad_ref,
               upd_ref, anyf_ref, vwin, sems):
        l = pl.program_id(0)
        cmax = jnp.clip(cnt_ref[0], _i32(1), _i32(K))
        slot = lax.rem(l, _i32(2))
        valid = l < cmax

        def rc_of(lane):
            t = ids_ref[lane]
            return ((t // _i32(gj)) * _i32(th) + _i32(off),
                    lax.rem(t, _i32(gj)) * _i32(tw) + _i32(off))

        def window_copy(lane, sl):
            r, c = rc_of(lane)
            return pltpu.make_async_copy(
                pad_ref.at[pl.ds(r, wh), pl.ds(c, ww)],
                vwin.at[sl], sems.at[sl])

        # double-buffered pipeline (the _stencil_call discipline): lane 0
        # fetches its own window; every lane then prefetches its
        # successor's into the other slot before waiting on its own
        @pl.when(l == 0)
        def _():
            pl.when(valid)(window_copy(l, slot).start)

        nxt = l + _i32(1)
        pl.when(nxt < cmax)(
            window_copy(jnp.minimum(nxt, _i32(K - 1)),
                        lax.rem(nxt, _i32(2))).start)
        pl.when(valid)(window_copy(l, slot).wait)

        @pl.when(valid)
        def _():
            win = vwin[slot]
            r, c = rc_of(l)
            # global coords of the window's [0, 0] (the padded array's
            # [off, off] is global [origin - k, origin - k] of the tile)
            g_r0 = orig_ref[0] + (r - _i32(off)) - _i32(k)
            g_c0 = orig_ref[1] + (c - _i32(off)) - _i32(k)
            row_g = g_r0 + lax.broadcasted_iota(jnp.int32, (wh, ww), 0)
            col_g = g_c0 + lax.broadcasted_iota(jnp.int32, (wh, ww), 1)
            in_grid = ((row_g >= 0) & (row_g < H)
                       & (col_g >= 0) & (col_g < W))
            cnt = jnp.zeros((wh, ww), win.dtype)
            for dx, dy in offsets:
                ok = ((row_g + _i32(dx) >= 0) & (row_g + _i32(dx) < H)
                      & (col_g + _i32(dy) >= 0) & (col_g + _i32(dy) < W))
                cnt = cnt + ok.astype(win.dtype)
            # off-grid window cells can have count 0; their value is 0
            cnt = jnp.maximum(cnt, jnp.asarray(1, win.dtype))
            mask = in_grid.astype(win.dtype)
            rate_v = rate_ref[0]

            def iterated(cur):
                # the exact iterated path, mirroring active_pass (and
                # thus the dense XLA transport) term for term: barrier
                # pins the outflow so LLVM cannot contract v - rate*v
                # into an fma the dense path never emits. Between
                # in-window steps, off-grid cells are re-zeroed (the
                # dense path never computes them, so mass that a gather
                # would park there must not leak back next step —
                # _stencil_call's masked-path invariant); in-grid cells
                # multiply by exactly 1.0, a bitwise no-op. The final
                # step skips the multiply: the output interior is always
                # in-grid, and k=1 must stay the literal active_pass
                # expression (under sharding the ring holds real ghost
                # data and a single step never consumes its own output).
                for s in range(k):
                    hs, ws = cur.shape
                    outflow = lax.optimization_barrier(rate_v * cur)
                    share = outflow / cnt[s:wh - s, s:ww - s]
                    inflow = jnp.zeros((hs - 2, ws - 2), cur.dtype)
                    for dx, dy in offsets:
                        inflow = inflow + share[1 + dx:hs - 1 + dx,
                                                1 + dy:ws - 1 + dy]
                    cur = ((cur[1:hs - 1, 1:ws - 1]
                            - outflow[1:hs - 1, 1:ws - 1]) + inflow)
                    if s < k - 1:
                        cur = cur * mask[s + 1:wh - s - 1,
                                         s + 1:ww - s - 1]
                return cur

            if tap_list is None:
                tile_out = iterated(win)
                upd_ref[0] = tile_out
                anyf_ref[0] = jnp.any(tile_out != 0).astype(jnp.int32)
            else:
                # composed-k: the tap table on interior self-lit tiles,
                # the exact iterated path on near-edge tiles (the
                # spatially-varying boundary divisor does not compose)
                # and frontier tiles (dilated in with a zero self-tile —
                # keeping them iterated keeps the activation-timing
                # gates bitwise). Predicates mirror _stencil_call's
                # near-band form.
                tile_r0 = g_r0 + _i32(k)
                tile_c0 = g_c0 + _i32(k)
                near = ((tile_r0 <= _i32(k))
                        | (tile_r0 + _i32(th) >= _i32(H) - _i32(k))
                        | (tile_c0 <= _i32(k))
                        | (tile_c0 + _i32(tw) >= _i32(W) - _i32(k)))
                exact = near | (self_ref[l] == 0)

                @pl.when(exact)
                def _():
                    tile_out = iterated(win)
                    upd_ref[0] = tile_out
                    anyf_ref[0] = jnp.any(tile_out != 0).astype(jnp.int32)

                @pl.when(jnp.logical_not(exact))
                def _():
                    acc = jnp.zeros((th, tw), win.dtype)
                    for dr, dc, tap in tap_list:
                        acc = acc + jnp.asarray(tap, dtype=win.dtype) * win[
                            dr:dr + th, dc:dc + tw]
                    upd_ref[0] = acc
                    anyf_ref[0] = jnp.any(acc != 0).astype(jnp.int32)

        @pl.when(jnp.logical_not(valid))
        def _():
            # lanes past the active count: a zero update and a False
            # flag (lane 0 is always "valid" — on an all-zero grid it
            # computes tile 0's identically-zero update, so the scatter
            # pass never flushes an unwritten VMEM block)
            upd_ref[0] = jnp.zeros((th, tw), upd_ref.dtype)
            anyf_ref[0] = jnp.zeros((), jnp.int32)

    grid_spec = prefetch_scalar_grid_spec(
        num_scalar_prefetch=5,
        grid=(K,),
        in_specs=[pl.BlockSpec(memory_space=_HBM)],
        out_specs=[
            pl.BlockSpec((1, th, tw),
                         lambda l, i, c, s, o, rt: (l, 0, 0)),
            pl.BlockSpec((1,), lambda l, i, c, s, o, rt: (l,)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, wh, ww), jnp.dtype(dtype)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    rate1 = jnp.reshape(jnp.asarray(rate, dtype=jnp.dtype(dtype)), (1,))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((K, th, tw), jnp.dtype(dtype)),
            jax.ShapeDtypeStruct((K,), jnp.int32),
        ],
        interpret=interpret,
    )(ids, cnt1, selfnz, origin, rate1, padded)


def _fused_scatter_call(padded, upd, ids, cnt1, *, plan, ring, interpret):
    """Pallas pass 2: land each lane's update tile back into the padded
    state. The output ALIASES the state operand
    (``input_output_aliases``), so untouched tiles — exactly the zero
    tiles the engine skipped — keep their bytes; splitting this from the
    compute pass is the all-reads-precede-all-writes invariant."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (th, tw), (gi, gj) = plan.tile, plan.grid
    K = plan.capacity
    _i32 = np.int32

    def kernel(ids_ref, cnt_ref, upd_ref, pad_in_ref, out_ref, sem):
        l = pl.program_id(0)
        cmax = jnp.clip(cnt_ref[0], _i32(1), _i32(K))

        @pl.when(l < cmax)
        def _():
            t = ids_ref[l]
            r = (t // _i32(gj)) * _i32(th) + _i32(ring)
            c = lax.rem(t, _i32(gj)) * _i32(tw) + _i32(ring)
            cp = pltpu.make_async_copy(
                upd_ref.at[0],
                out_ref.at[pl.ds(r, th), pl.ds(c, tw)],
                sem)
            cp.start()
            cp.wait()

    grid_spec = prefetch_scalar_grid_spec(
        num_scalar_prefetch=2,
        grid=(K,),
        in_specs=[
            pl.BlockSpec((1, th, tw), lambda l, i, c: (l, 0, 0)),
            pl.BlockSpec(memory_space=_HBM),
        ],
        out_specs=pl.BlockSpec(memory_space=_HBM),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(padded.shape, padded.dtype),
        # operand order: (ids, cnt1, upd, padded) — index 3 is the state
        input_output_aliases={3: 0},
        interpret=interpret,
    )(ids, cnt1, upd, padded)


def fused_active_pass(padded, ids, count, selfnz, rate, plan: ActivePlan,
                      origin, global_shape: tuple[int, int],
                      offsets: Sequence[tuple[int, int]], dtype,
                      k: int = 1, ring: Optional[int] = None,
                      taps: Optional[np.ndarray] = None,
                      interpret: bool = True):
    """One fused pass over the compacted active set: ``k`` flow steps
    per tile-resident window, flags computed in-kernel. Returns
    ``(padded', anyf)`` where ``anyf`` is the ``[K]`` bool per-lane
    any-nonzero of the written tiles (lanes past ``count`` are False —
    feed it straight to ``ops.active.next_tile_map``).

    ``padded`` is the ring-``ring`` padded state (``ring`` defaults to
    ``k``; a remainder pass may run ``k < ring`` on the same buffer —
    the window fetch offsets shift inward). ``origin`` is the state's
    global (row, col) offset as a traced ``[2]`` int32 (zeros on a full
    grid; the shard offset under sharding). ``selfnz`` is the ``[K]``
    pre-pass self-tile-nonzero gather (``int32``; only consulted when a
    tap table is armed — frontier tiles keep the exact iterated path).
    """
    if ring is None:
        ring = k
    if k < 1 or k > min(plan.tile):
        raise ValueError(
            f"fused pass depth k={k} must be in [1, min(tile)="
            f"{min(plan.tile)}] (ring-1 dilation exactness bound)")
    if ring < k:
        raise ValueError(f"padding ring {ring} shallower than pass depth "
                         f"{k}")
    cnt1 = jnp.reshape(jnp.asarray(count, jnp.int32), (1,))
    origin = jnp.asarray(origin, jnp.int32)
    upd, anyf = _fused_compute_call(
        padded, ids, cnt1, jnp.asarray(selfnz, jnp.int32), origin,
        rate=rate, plan=plan, global_shape=tuple(global_shape),
        offsets=tuple(offsets), dtype=dtype, k=int(k), ring=int(ring),
        taps=taps, interpret=bool(interpret))
    padded = _fused_scatter_call(padded, upd, ids, cnt1, plan=plan,
                                 ring=int(ring), interpret=bool(interpret))
    return padded, anyf != 0


# -- dense fallback at pass depth k ------------------------------------------

def dense_chunk_from_padded(padded, rate, counts, offsets, dtype, k: int,
                            ring: int):
    """``k`` dense XLA transport steps on the interior of a ring-``ring``
    padded state (the fused runner's fallback: bitwise the serial dense
    path, once per fallback EVENT). Returns the re-padded state with the
    ring re-zeroed (the engine invariant)."""
    v = padded[ring:-ring, ring:-ring]
    for _ in range(k):
        v = transport(v, jnp.asarray(rate, dtype) * v, counts, offsets)
    return jnp.pad(v, ring)


# -- the amortized whole-run runner ------------------------------------------

def build_fused_runner(shape: tuple[int, int], rates: dict,
                       offsets: Sequence[tuple[int, int]], dtype,
                       origin: tuple[int, int] = (0, 0),
                       global_shape: Optional[tuple[int, int]] = None,
                       plan: Optional[ActivePlan] = None,
                       k: int = 1,
                       dense_fns: Optional[dict] = None,
                       traced_rates: bool = False,
                       track_dirty: bool = False,
                       interpret: bool = True) -> Callable:
    """Whole-run fused active stepper — ``ops.active.build_active_runner``
    with the gather/compute/flags replaced by the fused Pallas pass:
    ``run(values, n[, rates_vec]) -> (values, stats)`` where ``stats`` is
    ``(fallback_events, active_tiles_total, flags_fused[, dirty_map])``.

    Structure (the measured PR 3 loop shape, per pass instead of per
    step): the state is padded ONCE to ring ``k`` and carried;
    ``q = n // k`` full-depth passes run in an inner while_loop with no
    cond on the fast path, the dense fallback (``k`` transport steps)
    sits in the outer loop and fires per fallback EVENT; the remainder
    ``r = n % k`` steps run the same nest at depth 1 on the same buffer.
    Per-pass flags come from the kernel (``flags_fused`` counts those
    passes); the only per-pass XLA work is the [gi, gj] bool dilation,
    the cumsum compaction and the flag scatter — never a read of the
    grid (the auditor's ``jaxpr-fused-flags`` contract).

    ``rates``/``traced_rates``/``dense_fns``/``track_dirty`` follow
    ``build_active_runner``'s contract; the dirty map unions kernel-
    written tiles (the flagged set) per fused pass and the ring-1
    dilation of the pre-chunk map per dense event (a k-step dense chunk
    moves mass k <= min(tile) cells — within one tile ring)."""
    shape = tuple(shape)
    gshape = tuple(global_shape) if global_shape is not None else shape
    offsets = tuple((int(dx), int(dy)) for dx, dy in offsets)
    dtype = jnp.dtype(dtype)
    if plan is None:
        plan = plan_for(shape)
    k = int(k)
    if k < 1 or k > min(min(plan.tile), MAX_FUSED_K):
        raise ValueError(
            f"fused runner depth k={k} must divide into "
            f"[1, min(min(tile), {MAX_FUSED_K})] for tile {plan.tile}")
    th, tw = plan.tile
    dense_fns = dense_fns or {}
    attrs = list(rates)
    thresh = np.int32(plan.fallback_tiles)
    taps_by_attr = {}

    def rate_of(attr, rates_vec):
        r = rates[attr]
        if traced_rates:
            acc = jnp.zeros((), rates_vec.dtype)
            for i in r:
                acc = acc + rates_vec[i]
            return acc
        return r

    if not traced_rates:
        # tap tables need a CONCRETE rate; per-lane traced rates run the
        # iterated path at every depth (still k steps per window)
        for a in attrs:
            taps_by_attr[a] = _fused_taps(float(rates[a]), offsets, k)

    def _dilated(tmap):
        flags = dilate_tile_map(tmap)
        return flags, jnp.sum(flags, dtype=jnp.int32)

    def run(values, n, rates_vec=None):
        counts = neighbor_counts_traced(shape, offsets, origin, gshape,
                                        dtype)
        orig_vec = jnp.asarray(origin, jnp.int32)
        fb = jnp.zeros((), jnp.int32)
        at = jnp.zeros((), jnp.float32)
        ff = jnp.zeros((), jnp.int32)
        dm = (jnp.zeros(plan.grid, bool),) if track_dirty else ()
        q = n // np.int32(k)
        r = n - q * np.int32(k)
        out = dict(values)
        for a in attrs:
            rate = rate_of(a, rates_vec)
            taps = taps_by_attr.get(a)

            def phase(carry, npasses, depth, _rate=rate, _a=a,
                      _taps=None):
                """One while-nest: ``npasses`` passes of ``depth`` steps
                — fused on the fast path, dense per fallback event."""

                def inner_cond(c, _np=npasses):
                    _, cnt = _dilated(c[1])
                    return (c[2] < _np) & (cnt <= thresh)

                def inner_body(c):
                    p, tm, i, fb_, at_, ff_, *dm_ = c
                    flags, cnt = _dilated(tm)
                    ids, _ = compact_tile_ids(flags, plan)
                    selfnz = tm.reshape(-1)[ids].astype(jnp.int32)
                    p2, anyf = fused_active_pass(
                        p, ids, cnt, selfnz, _rate, plan, orig_vec,
                        gshape, offsets, dtype, k=depth, ring=k,
                        taps=_taps, interpret=interpret)
                    if track_dirty:
                        dm_ = (dm_[0] | flags,)
                    return (p2, next_tile_map(anyf, ids, cnt, plan),
                            i + 1, fb_, at_ + cnt.astype(jnp.float32),
                            ff_ + 1, *dm_)

                def outer_body(c, _np=npasses):
                    c = lax.while_loop(inner_cond, inner_body, c)
                    p, tm, i, fb_, at_, ff_, *dm_ = c

                    def dense_pass(args):
                        pp, tm_, i_, fb__, at__, ff__, *dm__ = args
                        _, cnt = _dilated(tm_)
                        fn = dense_fns.get(_a)
                        if fn is not None:
                            v = pp[k:-k, k:-k]
                            for _s in range(depth):
                                v = fn(v)
                            p2 = jnp.pad(v, k)
                        else:
                            p2 = dense_chunk_from_padded(
                                pp, _rate, counts, offsets, dtype,
                                depth, k)
                        if track_dirty:
                            dm__ = (dm__[0] | dilate_tile_map(tm_),)
                        return (p2,
                                tile_nonzero_map(p2[k:-k, k:-k], plan),
                                i_ + 1, fb__ + 1,
                                at__ + cnt.astype(jnp.float32), ff__,
                                *dm__)

                    p, tm, i, fb_, at_, ff_, *dm_ = lax.cond(
                        i < _np, dense_pass, lambda args: args,
                        (p, tm, i, fb_, at_, ff_, *dm_))
                    return (p, tm, i, fb_, at_, ff_, *dm_)

                return lax.while_loop(
                    lambda c, _np=npasses: c[2] < _np, outer_body, carry)

            c0 = (jnp.pad(values[a], k),
                  tile_nonzero_map(values[a], plan),
                  jnp.zeros((), jnp.int32), fb, at, ff, *dm)
            c1 = phase(c0, q, k, _taps=taps)
            # remainder steps at depth 1 on the same ring-k buffer
            # (taps never apply at depth 1 — the k=1 bitwise contract)
            c2 = phase((c1[0], c1[1], jnp.zeros((), jnp.int32),
                        *c1[3:]), r, 1, _taps=None)
            padded, _, _, fb, at, ff, *dm = c2
            out[a] = padded[k:-k, k:-k]
            dm = tuple(dm)
        if track_dirty:
            return out, (fb, at, ff, dm[0])
        return out, (fb, at, ff)

    return run


# -- stateless per-step form (Model.make_step impl="active_fused") -----------

class FusedActiveStep:
    """Stateless fused active step for one channel: pad → activity →
    compact → fused kernel pass(es) (or the dense fallback, same call)
    → unpad. One ``__call__`` advances ``k * passes`` flow steps (the
    ``make_step(impl='active_fused', substeps=...)`` contract:
    ``k`` auto-chosen dividing ``substeps``, ``passes = substeps / k``).
    Activity is recomputed from the values each call, so interleaved
    point-flow deposits and restores are seen next call — the
    ``ActiveDiffusionStep`` composition contract. ``SerialExecutor``'s
    amortized runner (``build_fused_runner``) is the whole-run fast
    path."""

    def __init__(self, shape: tuple[int, int], rate: float, dtype,
                 offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS,
                 origin: tuple[int, int] = (0, 0),
                 global_shape: Optional[tuple[int, int]] = None,
                 tile: Optional[tuple[int, int]] = None,
                 capacity: Optional[int] = None,
                 max_active_frac: float = 0.25,
                 k: int = 1, passes: int = 1,
                 dense_fn: Optional[Callable] = None,
                 interpret: bool = True):
        self.shape = tuple(shape)
        self.rate = float(rate)
        self.dtype = jnp.dtype(dtype)
        self.offsets = tuple((int(dx), int(dy)) for dx, dy in offsets)
        self.origin = (int(origin[0]), int(origin[1]))
        self.global_shape = (tuple(global_shape)
                             if global_shape is not None else self.shape)
        self.plan = plan_for(self.shape, tile=tile, capacity=capacity,
                             max_active_frac=max_active_frac)
        self.k = int(k)
        self.passes = int(passes)
        self.interpret = bool(interpret)
        if self.k < 1 or self.k > min(min(self.plan.tile), MAX_FUSED_K):
            raise ValueError(
                f"k={k} outside [1, min(min(tile), {MAX_FUSED_K})] for "
                f"tile {self.plan.tile}")
        self.taps = _fused_taps(self.rate, self.offsets, self.k)
        if dense_fn is None:
            def dense_fn(v, _s=self):
                counts = neighbor_counts_traced(
                    _s.shape, _s.offsets, _s.origin, _s.global_shape,
                    _s.dtype)
                return transport(
                    v, jnp.asarray(_s.rate, _s.dtype) * v, counts,
                    _s.offsets)
        self.dense_fn = dense_fn

    def __call__(self, v: jax.Array) -> jax.Array:
        plan, k = self.plan, self.k
        orig_vec = jnp.asarray(self.origin, jnp.int32)
        for _ in range(self.passes):
            tmap = tile_nonzero_map(v, plan)
            flags = dilate_tile_map(tmap)
            count = jnp.sum(flags, dtype=jnp.int32)
            pred = count > np.int32(plan.fallback_tiles)

            def dense_branch(vv):
                out = vv
                for _s in range(k):
                    out = self.dense_fn(out)
                return out

            def active_branch(vv, _tmap=tmap, _flags=flags,
                              _count=count):
                padded = jnp.pad(vv, k)
                ids, _ = compact_tile_ids(_flags, plan)
                selfnz = _tmap.reshape(-1)[ids].astype(jnp.int32)
                padded, _anyf = fused_active_pass(
                    padded, ids, _count, selfnz, self.rate, plan,
                    orig_vec, self.global_shape, self.offsets,
                    self.dtype, k=k, ring=k, taps=self.taps,
                    interpret=self.interpret)
                return padded[k:-k, k:-k]

            v = lax.cond(pred, dense_branch, active_branch, v)
        return v
