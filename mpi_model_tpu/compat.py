"""Version bridges for the jax API surface this framework sits on.

The framework targets current jax, but several names it leans on moved
across the 0.4.x → 0.7.x window and the images this code runs under pin
different points of that line:

- ``jax.shard_map`` (top-level since 0.6) vs
  ``jax.experimental.shard_map.shard_map`` — whose replication-check
  kwarg is ``check_vma`` new-style and ``check_rep`` old-style;
- ``pltpu.HBM`` (explicit HBM memory space) vs the older
  ``pltpu.ANY``/``TPUMemorySpace.ANY`` (compiler-chosen, which in
  practice is HBM for the grid-sized operands these kernels pin there);
- ``pltpu.CompilerParams`` vs the older ``pltpu.TPUCompilerParams``.

Every bridge prefers the NEW name when present, so on a current jax this
module is a plain passthrough; on the 0.4.x line it degrades to the
nearest equivalent instead of an ``AttributeError`` at trace time.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.experimental.pallas import tpu as pltpu

#: memory space that pins a pallas operand out of VMEM. On jax without
#: ``pltpu.HBM`` this is ``ANY`` — the compiler may then place SMALL
#: operands in VMEM (re-imposing (sublane, lane) slice alignment), but
#: every silicon path in this repo runs on images whose jax has the
#: explicit HBM space; the ANY fallback serves interpret-mode rigs.
HBM: Any = getattr(pltpu, "HBM", None)
if HBM is None:
    HBM = getattr(pltpu, "ANY", None)
if HBM is None:  # pragma: no cover - very old jax
    HBM = pltpu.TPUMemorySpace.ANY

_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def jaxpr_type():
    """The ``Jaxpr`` class under whichever module this jax exports it:
    ``jax.extend.core`` (the supported home since 0.5; ``jax.core``'s
    alias is deprecated and removed in 0.6+) with the 0.4.x
    ``jax.core`` fallback. Used by the jaxpr contract auditor's
    recursive eqn walk."""
    try:
        from jax.extend.core import Jaxpr
    except ImportError:
        from jax.core import Jaxpr
    return Jaxpr


def tpu_compiler_params(*, vmem_limit_bytes: Optional[int] = None):
    """``pltpu.CompilerParams`` under whichever name this jax spells it."""
    return _COMPILER_PARAMS(vmem_limit_bytes=vmem_limit_bytes)


def literal_type():
    """The ``Literal`` class (jaxpr invars that are inline constants)
    under whichever module this jax exports it — the fused-kernel
    auditor uses it to prove the scalar-prefetched index buffer reaches
    ``pallas_call`` as a traced argument, never a baked literal."""
    try:
        from jax.extend.core import Literal
    except ImportError:
        from jax.core import Literal
    return Literal


def prefetch_scalar_grid_spec(*, num_scalar_prefetch, grid, in_specs,
                              out_specs, scratch_shapes):
    """``pltpu.PrefetchScalarGridSpec`` — the TPU grid spec whose
    leading operands are scalar-prefetched (available to index maps and
    to the kernel before the body runs; the sparse-streaming shape the
    fused active kernel is built on). Stable across the 0.4.x → current
    window under this one name; bridged here so a future rename has one
    place to land, and so a jax WITHOUT it fails with a clear message
    at build time instead of an AttributeError mid-trace."""
    spec_cls = getattr(pltpu, "PrefetchScalarGridSpec", None)
    if spec_cls is None:  # pragma: no cover - jax without scalar prefetch
        raise NotImplementedError(
            "this jax exposes no pltpu.PrefetchScalarGridSpec; the fused "
            "active kernel (impl='active_fused') needs it — use "
            "impl='active' (the XLA engine) on this rig")
    return spec_cls(num_scalar_prefetch=num_scalar_prefetch, grid=grid,
                    in_specs=in_specs, out_specs=out_specs,
                    scratch_shapes=scratch_shapes)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """``jax.shard_map`` when available, else the experimental spelling
    with ``check_vma`` translated to its old name ``check_rep``."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    # the legacy replication checker has no rule for while/fori loops,
    # which every runner here is built around — disable it unless the
    # caller explicitly asked for a check (the new-style checker, when
    # this branch isn't taken, handles loops fine)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=bool(check_vma) if check_vma is not None else False)


def optimization_barrier(x):
    """``lax.optimization_barrier`` that also works under ``vmap``: jax
    0.4.x never registered a batching rule for the primitive (it landed
    upstream later), and the IR lowering's pointwise amounts run both
    serially and inside the ensemble's vmapped parametric step. The
    rule is the identity passthrough (a barrier commutes with
    batching); registered once, lazily, and only when missing — on a
    jax that already has the rule this is exactly ``lax
    .optimization_barrier``."""
    from jax import lax
    from jax.interpreters import batching

    p = getattr(lax, "optimization_barrier_p", None)
    if p is None:  # pragma: no cover - very old jax spelling
        from jax._src.lax import lax as _ll
        p = _ll.optimization_barrier_p
    if p not in batching.primitive_batchers:
        def _batch_rule(args, dims, **params):
            return p.bind(*args, **params), list(dims)

        batching.primitive_batchers[p] = _batch_rule
    return lax.optimization_barrier(x)
