"""Post-mortem timeline reconstruction (ISSUE 15 tentpole, part 3).

"What happened to ticket X during the kill?" is the question every
production incident starts with, and before this PR the answer was
spread over four artifacts in three formats: the fleet ticket journal,
the tiering lifecycle journal, the tracer's span ring (or an exported
Chrome trace) and the flight-recorder dumps. :func:`reconstruct` joins
them into ONE ordered per-ticket timeline:

- **fleet journal** (``ensemble.journal``): submit / served /
  quarantined / expired / readmit / migrate / wake records for the
  ticket, in verified-record order (each stamped ``t_wall`` since this
  PR; older journals order by record index alone and say so);
- **tiering journal** (``<vault>/hibernation.journal``): hibernate /
  hibernated / wake / requeue / reclaim lifecycle records;
- **spans**: dicts from ``Tracer.spans``/``ingest`` or a Chrome trace
  file (``export_chrome``) — matched by the ticket's ``trace_id``
  (carried in its journal submit record) or by ticket membership in a
  dispatch span's ``tickets``/``trace_ids`` meta;
- **explicit uncertainty**: a submitted-but-unresolved ticket gets a
  synthesized ``uncertainty`` event ("in flight on m2g1 at end of
  journal — process killed?"), and a readmit after a fence closes the
  gap with the handoff visible. A timeline NEVER has a silent hole:
  what is not known is a record saying it is not known.

``Timeline.complete`` is the acceptance predicate the chaos kill legs
assert: submit + exactly one terminal, with any submit→terminal gap
either covered by records or explicitly annotated.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

__all__ = ["Timeline", "TimelineEvent", "reconstruct", "spans_from_chrome"]

def _fleet_machine():
    """The declared fleet lifecycle machine (ISSUE 19) — imported
    lazily and cached so obs stays import-light: ``ensemble.lifecycle``
    is stdlib-only, but naming it at module load would execute
    ``ensemble/__init__`` and pull the jax-laden serving stack."""
    global _FLEET
    if _FLEET is None:
        from ..ensemble.lifecycle import FLEET

        _FLEET = FLEET
    return _FLEET


_FLEET = None


@dataclasses.dataclass
class TimelineEvent:
    """One timeline entry. ``t_wall`` is None for records from sources
    without a wall stamp (pre-ISSUE-15 journals) — such events keep
    their source order and the timeline says the ordering is by index,
    not by clock."""

    t_wall: Optional[float]
    source: str  # "journal" | "tiering" | "span" | "reconstruction"
    kind: str
    detail: str
    service_id: Optional[str] = None
    #: source-local ordering key (journal record index / span start)
    order: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Timeline:
    """One ticket's reconstructed lifecycle."""

    ticket: int
    events: list
    #: submit seen + exactly one terminal record seen
    complete: bool
    #: the explicit uncertainty/gap annotations (also present in
    #: ``events`` — listed separately so "no silent gaps" is checkable)
    gaps: list
    #: the trace id the ticket's spans were matched by (None when the
    #: submit record carried no trace context)
    trace_id: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "ticket": self.ticket,
            "complete": self.complete,
            "trace_id": self.trace_id,
            "events": [e.to_dict() for e in self.events],
            "gaps": [e.to_dict() for e in self.gaps],
        }


def spans_from_chrome(path: str) -> list:
    """Span dicts out of an ``export_chrome`` artifact — the offline
    counterpart of ``Tracer.spans`` for post-mortem joins."""
    with open(path) as fh:
        doc = json.load(fh)
    out = []
    for e in doc.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        out.append({
            "name": e.get("name"),
            "start_wall_s": e.get("ts", 0.0) / 1e6,
            "duration_s": e.get("dur", 0.0) / 1e6,
            "pid": e.get("pid"), "thread": e.get("tid"),
            "meta": {k: v for k, v in args.items()
                     if k not in ("trace_id", "span_id", "parent_id")},
            "trace_id": args.get("trace_id"),
            "span_id": args.get("span_id"),
            "parent_id": args.get("parent_id"),
        })
    return out


def spans_from_jsonl(path: str) -> list:
    """Span dicts out of an ``export_stream`` JSONL sink (ISSUE 20).
    The file may end in a torn line (the writer was killed mid-append
    — the sink's whole point is surviving exactly that); the torn
    tail is skipped, everything before it loads."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue  # the torn tail of a killed writer
            if isinstance(d, dict):
                out.append(d)
    return out


def _span_dicts(spans) -> list:
    """Normalize ``Tracer.spans`` (Span objects) / dict lists / a span
    FILE path — chrome trace, or an ``export_stream`` ``.jsonl``
    stream — into plain span dicts."""
    if spans is None:
        return []
    if isinstance(spans, str):
        if spans.endswith(".jsonl"):
            return spans_from_jsonl(spans)
        return spans_from_chrome(spans)
    out = []
    for s in spans:
        out.append(s if isinstance(s, dict) else s.to_dict())
    return out


#: stat-signature read cache (the tiering journal-fallback pattern):
#: reconstructing N tickets' timelines over the same pair of journal
#: files must scan + CRC each file once, not once per ticket. Bounded
#: at a few entries (fleet journal + tiering journal alternate within
#: one reconstruct() call — a single slot would thrash).
_READ_CACHE: dict = {}
_READ_CACHE_MAX = 4


def _read_records_cached(path: str):
    from ..ensemble.journal import read_records

    st = os.stat(path)
    sig = (st.st_mtime_ns, st.st_size)
    hit = _READ_CACHE.get(path)
    if hit is not None and hit[0] == sig:
        return hit[1], hit[2]
    records, torn = read_records(path)
    while len(_READ_CACHE) >= _READ_CACHE_MAX:
        _READ_CACHE.pop(next(iter(_READ_CACHE)))
    _READ_CACHE[path] = (sig, records, torn)
    return records, torn


def _journal_events(ticket: int, path: str, source: str) -> tuple:
    """(events, submit_meta, terminal_kinds) for ``ticket`` from one
    TJ1 journal file."""
    from ..ensemble.lifecycle import SUBMIT

    machine = _fleet_machine()
    events: list = []
    submit_meta: Optional[dict] = None
    terminals: list = []
    if not os.path.exists(path):
        return events, submit_meta, terminals
    records, torn = _read_records_cached(path)
    for rec in records:
        if rec.meta.get("ticket") != ticket:
            continue
        sid = rec.meta.get("service_id")
        bits = []
        for k in ("seq", "source", "from", "to", "reason", "error",
                  "detail", "steps"):
            v = rec.meta.get(k)
            if v is not None:
                bits.append(f"{k}={v}")
        events.append(TimelineEvent(
            t_wall=rec.meta.get("t_wall"), source=source, kind=rec.kind,
            detail="; ".join(bits), service_id=sid, order=rec.index))
        if rec.kind == SUBMIT and submit_meta is None:
            submit_meta = rec.meta
        if machine.is_terminal(rec.kind):
            terminals.append(rec.kind)
    if torn:
        events.append(TimelineEvent(
            t_wall=None, source=source, kind="journal-torn-tail",
            detail=f"{path} had an unverifiable suffix — events after "
                   "the verified prefix are unknown",
            order=len(records) + 0.5))
    return events, submit_meta, terminals


def reconstruct(ticket: int, *, journal_dir: Optional[str] = None,
                vault_dir: Optional[str] = None,
                spans=None) -> Timeline:
    """Join every available source into one ordered timeline for
    ``ticket`` (module docstring has the semantics). ``spans`` accepts
    ``Tracer.spans``, a list of span dicts, or a Chrome-trace path."""
    from ..ensemble.journal import journal_path
    from ..ensemble.tiering import HIBERNATE_JOURNAL

    events: list = []
    gaps: list = []
    submit_meta = None
    terminals: list = []
    if journal_dir is not None:
        ev, submit_meta, terminals = _journal_events(
            ticket, journal_path(journal_dir), "journal")
        events.extend(ev)
    if vault_dir is not None:
        ev, _, _ = _journal_events(
            ticket, os.path.join(vault_dir, HIBERNATE_JOURNAL), "tiering")
        events.extend(ev)

    # span join: by the submit record's trace id, or by ticket
    # membership in a dispatch span's meta
    trace_id = None
    if submit_meta is not None:
        tmeta = submit_meta.get("trace")
        if isinstance(tmeta, dict):
            trace_id = tmeta.get("trace_id")
    for d in _span_dicts(spans):
        meta = d.get("meta") or {}
        tid = d.get("trace_id")
        if trace_id is not None:
            # the journaled trace id is authoritative: dispatch-span
            # `tickets` are MEMBER-LOCAL scheduler ids in a fleet (a
            # fleet ticket 5 and some member's ticket 5 are unrelated
            # scenarios), so raw ticket-membership must not join here
            match = (tid == trace_id
                     or trace_id in (meta.get("trace_ids") or ()))
        else:
            # no journaled trace (pre-ISSUE-15 journal, or no journal
            # at all): fall back to ticket membership — correct only
            # for a SINGLE-scheduler namespace, which is exactly the
            # no-fleet case this branch serves
            match = (ticket in (meta.get("tickets") or ())
                     or meta.get("ticket") == ticket)
        if not match:
            continue
        t0 = d.get("start_wall_s")
        events.append(TimelineEvent(
            t_wall=t0, source="span", kind=d.get("name", "span"),
            detail=f"{d.get('duration_s', 0.0):.6f}s "
                   f"pid={d.get('pid')}",
            service_id=meta.get("service_id"),
            order=t0 if t0 is not None else 0.0))

    # explicit uncertainty: submitted, never resolved → say so, naming
    # where it was last known to be (the last attribution record wins)
    if submit_meta is not None and not terminals:
        last_sid = submit_meta.get("service_id")
        for e in events:
            if (e.source == "journal"
                    and e.kind in _fleet_machine().attribution_kinds()):
                last_sid = e.service_id or last_sid
                # readmit/migrate/wake meta carries to= in the detail;
                # the service_id field is what we surface
        where = (f"on {last_sid}" if last_sid else "unattributed")
        gap = TimelineEvent(
            t_wall=None, source="reconstruction", kind="uncertainty",
            detail=f"submitted but never resolved in the journal — in "
                   f"flight {where} at end of journal (process killed "
                   "before a terminal record, or the journal's tail "
                   "was lost)",
            service_id=last_sid, order=float("inf"))
        events.append(gap)
        gaps.append(gap)
    if submit_meta is None and (journal_dir is not None or events):
        gap = TimelineEvent(
            t_wall=None, source="reconstruction", kind="uncertainty",
            detail="no verified submit record for this ticket — the "
                   "journal predates it, lost its tail, or the ticket "
                   "id is from another fleet",
            order=float("-inf"))
        events.append(gap)
        gaps.append(gap)
    if any(e.t_wall is None and e.source in ("journal", "tiering")
           for e in events):
        note = TimelineEvent(
            t_wall=None, source="reconstruction", kind="ordering-note",
            detail="some records carry no t_wall stamp (pre-ISSUE-15 "
                   "journal) — their order is record-index order, not "
                   "clock order",
            order=float("-inf"))
        events.append(note)

    # merge order: wall time when present; unstamped events keep their
    # source-local order interleaved after the last stamped event
    # before them (stable sort on (t_wall or +inf bucket, order))
    def sort_key(e: TimelineEvent):
        return (e.t_wall if e.t_wall is not None else float("inf"),
                e.order)

    stamped = sorted((e for e in events if e.t_wall is not None),
                     key=sort_key)
    unstamped = sorted((e for e in events if e.t_wall is None),
                       key=lambda e: e.order)
    return Timeline(
        ticket=ticket,
        events=stamped + unstamped,
        complete=(submit_meta is not None and len(terminals) == 1),
        gaps=gaps,
        trace_id=trace_id)
