"""``python -m mpi_model_tpu.obs`` — the operator CLI over the
telemetry plane (ISSUE 15):

- ``validate <snapshot.json>`` — schema-gate a dumped snapshot (exit 1
  with the failing field named when it does not validate);
- ``prom <snapshot.json>`` — render the snapshot's stats as the
  Prometheus text exposition (scrape the dumped file without teaching
  a collector our JSON);
- ``timeline <ticket> --journal DIR [--vault DIR] [--trace FILE]`` —
  reconstruct one ticket's lifecycle from the journals and an exported
  span file (Chrome trace or streaming JSONL); ``--json`` emits the
  timeline document, otherwise a human-ordered listing. Exit 1 when
  the timeline is INCOMPLETE (no submit, or no/duplicate terminal) —
  the post-mortem acceptance predicate, scriptable.
- ``--serve PORT --snapshot FILE`` (ISSUE 20) — stand up the live
  scrape endpoint over a snapshot file a soak keeps rewriting
  (``run_soak(snapshot_path=...)``): ``GET /metrics`` is the
  Prometheus exposition, ``GET /`` the snapshot JSON, each re-reading
  the file per request. Blocks until interrupted.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from . import prometheus_text, serve_status, validate_snapshot
from .postmortem import reconstruct


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpi_model_tpu.obs",
        description="Telemetry-plane CLI: snapshot validation, "
                    "Prometheus exposition, per-ticket timeline "
                    "reconstruction, live scrape serving.")
    p.add_argument("--serve", type=int, default=None, metavar="PORT",
                   help="serve the live scrape endpoint on PORT "
                        "(requires --snapshot; no subcommand)")
    p.add_argument("--snapshot", default=None, metavar="FILE",
                   help="snapshot file to serve (re-read per request)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address for --serve (default loopback)")
    sub = p.add_subparsers(dest="cmd", required=False)

    v = sub.add_parser("validate", help="schema-gate a snapshot file")
    v.add_argument("snapshot")

    pr = sub.add_parser("prom", help="Prometheus text exposition of a "
                                     "snapshot's stats")
    pr.add_argument("snapshot")

    t = sub.add_parser("timeline", help="reconstruct one ticket's "
                                        "lifecycle")
    t.add_argument("ticket", type=int)
    t.add_argument("--journal", required=True,
                   help="fleet journal directory")
    t.add_argument("--vault", default=None,
                   help="tiering vault directory (hibernation journal)")
    t.add_argument("--trace", default=None,
                   help="exported span file: a Chrome trace "
                        "(export_chrome) or a streaming .jsonl sink "
                        "(export_stream)")
    t.add_argument("--json", action="store_true")

    args = p.parse_args(argv)
    if args.serve is not None:
        if args.cmd is not None:
            p.error("--serve takes no subcommand")
        if args.snapshot is None:
            p.error("--serve needs --snapshot FILE (the document a "
                    "soak keeps rewriting via run_soak snapshot_path=)")
        snap_path = args.snapshot

        def _read_snapshot() -> dict:
            with open(snap_path) as fh:
                return json.load(fh)

        server = serve_status(args.serve, _read_snapshot,
                              host=args.host)
        host, port = server.server_address[:2]
        print(f"serving {snap_path} on http://{host}:{port} "
              "(/metrics for Prometheus, / for the snapshot JSON); "
              "Ctrl-C to stop", file=sys.stderr)
        try:
            import threading

            threading.Event().wait()  # the server threads do the work
        except KeyboardInterrupt:
            pass
        finally:
            server.shutdown()
            server.server_close()
        return 0
    if args.cmd is None:
        p.error("a subcommand (validate/prom/timeline) or --serve is "
                "required")
    if args.cmd == "validate":
        with open(args.snapshot) as fh:
            doc = json.load(fh)
        try:
            validate_snapshot(doc)
        except ValueError as e:
            print(f"INVALID: {e}", file=sys.stderr)
            return 1
        print(f"ok: {args.snapshot} validates against {doc['schema']}")
        return 0
    if args.cmd == "prom":
        with open(args.snapshot) as fh:
            doc = json.load(fh)
        sys.stdout.write(prometheus_text(doc.get("stats", {})))
        return 0
    # timeline
    tl = reconstruct(args.ticket, journal_dir=args.journal,
                     vault_dir=args.vault, spans=args.trace)
    if args.json:
        print(json.dumps(tl.to_dict(), sort_keys=True))
    else:
        for e in tl.events:
            ts = "              " if e.t_wall is None \
                else f"{e.t_wall:14.3f}"
            sid = "" if e.service_id is None else f" [{e.service_id}]"
            print(f"{ts} {e.source:<14} {e.kind:<18}{sid} {e.detail}")
        print(f"-- ticket {tl.ticket}: "
              + ("COMPLETE" if tl.complete else "INCOMPLETE")
              + (f", {len(tl.gaps)} explicit gap/uncertainty record(s)"
                 if tl.gaps else ", gap-free"))
    return 0 if tl.complete else 1


if __name__ == "__main__":
    raise SystemExit(main())
