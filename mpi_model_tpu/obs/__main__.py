"""``python -m mpi_model_tpu.obs`` — the operator CLI over the
telemetry plane (ISSUE 15):

- ``validate <snapshot.json>`` — schema-gate a dumped snapshot (exit 1
  with the failing field named when it does not validate);
- ``prom <snapshot.json>`` — render the snapshot's stats as the
  Prometheus text exposition (scrape the dumped file without teaching
  a collector our JSON);
- ``timeline <ticket> --journal DIR [--vault DIR] [--trace FILE]`` —
  reconstruct one ticket's lifecycle from the journals and an exported
  Chrome trace; ``--json`` emits the timeline document, otherwise a
  human-ordered listing. Exit 1 when the timeline is INCOMPLETE
  (no submit, or no/duplicate terminal) — the post-mortem acceptance
  predicate, scriptable.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from . import prometheus_text, validate_snapshot
from .postmortem import reconstruct


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpi_model_tpu.obs",
        description="Telemetry-plane CLI: snapshot validation, "
                    "Prometheus exposition, per-ticket timeline "
                    "reconstruction.")
    sub = p.add_subparsers(dest="cmd", required=True)

    v = sub.add_parser("validate", help="schema-gate a snapshot file")
    v.add_argument("snapshot")

    pr = sub.add_parser("prom", help="Prometheus text exposition of a "
                                     "snapshot's stats")
    pr.add_argument("snapshot")

    t = sub.add_parser("timeline", help="reconstruct one ticket's "
                                        "lifecycle")
    t.add_argument("ticket", type=int)
    t.add_argument("--journal", required=True,
                   help="fleet journal directory")
    t.add_argument("--vault", default=None,
                   help="tiering vault directory (hibernation journal)")
    t.add_argument("--trace", default=None,
                   help="exported Chrome trace (export_chrome output)")
    t.add_argument("--json", action="store_true")

    args = p.parse_args(argv)
    if args.cmd == "validate":
        with open(args.snapshot) as fh:
            doc = json.load(fh)
        try:
            validate_snapshot(doc)
        except ValueError as e:
            print(f"INVALID: {e}", file=sys.stderr)
            return 1
        print(f"ok: {args.snapshot} validates against {doc['schema']}")
        return 0
    if args.cmd == "prom":
        with open(args.snapshot) as fh:
            doc = json.load(fh)
        sys.stdout.write(prometheus_text(doc.get("stats", {})))
        return 0
    # timeline
    tl = reconstruct(args.ticket, journal_dir=args.journal,
                     vault_dir=args.vault, spans=args.trace)
    if args.json:
        print(json.dumps(tl.to_dict(), sort_keys=True))
    else:
        for e in tl.events:
            ts = "              " if e.t_wall is None \
                else f"{e.t_wall:14.3f}"
            sid = "" if e.service_id is None else f" [{e.service_id}]"
            print(f"{ts} {e.source:<14} {e.kind:<18}{sid} {e.detail}")
        print(f"-- ticket {tl.ticket}: "
              + ("COMPLETE" if tl.complete else "INCOMPLETE")
              + (f", {len(tl.gaps)} explicit gap/uncertainty record(s)"
                 if tl.gaps else ", gap-free"))
    return 0 if tl.complete else 1


if __name__ == "__main__":
    raise SystemExit(main())
