"""Unified telemetry plane (ISSUE 15 tentpole, part 2).

Until this PR the stack's observability was scattered: supervisor
counters in ``FleetSupervisor.stats()``, per-member cuts in heartbeat
telemetry, tiering residency in ``ScenarioTiering.stats()``, tracer
summaries on the process tracer, flight-recorder rings in
``obs.flight`` — five surfaces, five shapes, and the bench/chaos tests
each picked their own subset. This package merges them into ONE
versioned JSON document:

- :func:`fleet_snapshot` — the merged, schema-versioned snapshot
  (``schema: "mpi-model-tpu.obs/1"``): serving stats (fleet- or
  service-level, per-member breakdown included), tiering residency,
  tracer per-stage rollups (with the explicit ``dropped`` count), and
  the flight recorder's dump ledger. Humans, bench rows, the chaos
  harness and the CLI ``--status`` flag all consume THIS document —
  one plane, not per-consumer scrapes.
- :func:`validate_snapshot` — the schema gate (the verify skill's
  obs-smoke step and the tests call it; a field that silently vanishes
  from the plane fails loudly here).
- :func:`write_snapshot` — atomic dump-to-file (tmp + rename), the
  shape ``run_soak(snapshot_path=...)`` emits on an interval during
  soaks.
- :func:`prometheus_text` — a Prometheus-style text exposition of
  every ``ThroughputCounter`` counter (plus the latency/occupancy
  gauges), per-member labeled ``{service_id="m<slot>g<gen>"}`` — for
  scrape-based collection without teaching a collector our JSON.
- :func:`serve_status` — a LIVE scrape endpoint over both shapes
  (ISSUE 20 satellite): a stdlib HTTP server on a daemon thread
  answering ``GET /metrics`` with the Prometheus text and ``GET /``
  with the snapshot JSON, each computed fresh per request.
  ``run_soak(status_port=...)`` and the CLI ``--status-port`` flag
  stand one up beside a live soak.
- :func:`timeline` (``obs.timeline``) — post-mortem per-ticket
  timeline reconstruction joining the fleet journal, the tiering
  lifecycle journal and exported span files, with EXPLICIT
  gap/uncertainty records (never a silent hole); see
  ``obs/postmortem.py``.
- :mod:`obs.flight` — the flight recorder (bounded lifecycle-event
  rings dumped beside every ``FailureEvent``).

``python -m mpi_model_tpu.obs`` is the operator CLI over all of it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from .flight import FlightRecorder, get_recorder, set_recorder

__all__ = [
    "SCHEMA",
    "FlightRecorder",
    "fleet_snapshot",
    "get_recorder",
    "jsonable",
    "prometheus_text",
    "serve_status",
    "set_recorder",
    "timeline",
    "validate_snapshot",
    "write_snapshot",
]

#: the telemetry-plane schema id; bump the suffix on any breaking
#: field change so a consumer can dispatch on it
SCHEMA = "mpi-model-tpu.obs/1"

#: top-level fields every snapshot must carry (validate_snapshot)
_REQUIRED = ("schema", "generated_unix_s", "stats", "tracer",
             "flight_recorder")
#: stats fields every serving snapshot must carry — the shared core of
#: ThroughputCounter.snapshot() and FleetSupervisor.stats()
_REQUIRED_STATS = ("dispatches", "scenarios", "busy_s", "inflight_s",
                   "shed", "expired", "quarantined", "loop_faults",
                   "latency_n", "latency_p50_s", "latency_p99_s")


def fleet_snapshot(service=None, *, stats: Optional[dict] = None,
                   tracer=None, recorder=None) -> dict:
    """The unified telemetry plane as one versioned JSON document.

    ``service`` is anything with a ``stats()`` method (an
    ``AsyncEnsembleService``, a ``FleetSupervisor``, the sync
    ``EnsembleService``); pass ``stats=`` instead when you already hold
    a cut (the bench does — its cut and the snapshot's must be the
    same one). Tiering residency and the per-member breakdown ride
    inside ``stats`` already; the tracer contributes the per-stage
    rollups and its ``dropped`` count; the flight recorder contributes
    its dump ledger (reasons + counts, not the full rings — the rings
    live in the dump files)."""
    from ..utils.tracing import get_tracer

    if stats is None:
        if service is None:
            raise ValueError(
                "fleet_snapshot needs a service (anything with "
                ".stats()) or an explicit stats= cut")
        stats = service.stats()
    tr = tracer if tracer is not None else get_tracer()
    rec = recorder if recorder is not None else get_recorder()
    dump_ledger = rec.dump_ledger()
    summary = tr.summary()
    meta = summary.pop("__tracer__", {"dropped": tr.dropped,
                                      "recorded": len(tr.spans)})
    return {
        "schema": SCHEMA,
        "generated_unix_s": time.time(),
        "pid": os.getpid(),
        "stats": _jsonable(stats),
        "tracer": {
            "dropped": meta.get("dropped", 0),
            "recorded": meta.get("recorded", 0),
            "stages": _jsonable(summary),
        },
        "flight_recorder": {
            # copied under the recorder lock: the interval-dump thread
            # snapshots while fence/quarantine threads append dumps
            "dumps": len(dump_ledger),
            "dump_reasons": sorted({d["reason"] for d in dump_ledger}),
            "dump_paths": [d["path"] for d in dump_ledger
                           if d.get("path")],
        },
    }


def validate_snapshot(doc: dict) -> None:
    """Raise ``ValueError`` naming the first missing/malformed field —
    the schema gate of the plane (tests + the verify obs-smoke step).
    Accepts any ``mpi-model-tpu.obs/1`` document."""
    if not isinstance(doc, dict):
        raise ValueError(f"snapshot is {type(doc).__name__}, not a dict")
    for k in _REQUIRED:
        if k not in doc:
            raise ValueError(f"snapshot missing required field {k!r}")
    if doc["schema"] != SCHEMA:
        raise ValueError(
            f"snapshot schema {doc['schema']!r} != expected {SCHEMA!r}")
    stats = doc["stats"]
    if not isinstance(stats, dict):
        raise ValueError("snapshot stats is not a dict")
    for k in _REQUIRED_STATS:
        if k not in stats:
            raise ValueError(f"snapshot stats missing field {k!r}")
    tr = doc["tracer"]
    if not isinstance(tr, dict) or "dropped" not in tr \
            or "stages" not in tr:
        raise ValueError(
            "snapshot tracer block must carry dropped + stages — a "
            "truncated trace must be explicit in the artifact")
    json.dumps(doc)  # the plane is a JSON document, enforced


def write_snapshot(path: str, service=None, **kw) -> dict:
    """Snapshot to file, atomically (tmp + rename — a scraper reading
    mid-write must never see a torn document). Returns the document."""
    doc = fleet_snapshot(service, **kw)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return doc


# -- Prometheus-style exposition ----------------------------------------------

def _prom_name(key: str) -> str:
    return "mpi_model_tpu_" + key.replace("-", "_")


def _prom_lines(stats: dict, label: str = "") -> list:
    out = []
    for k in sorted(stats):
        v = stats[k]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out.append((k, label, float(v)))
    return out


def prometheus_text(stats: dict) -> str:
    """Prometheus text exposition of a serving stats cut: every numeric
    counter/gauge as ``mpi_model_tpu_<name>``, with the per-member
    breakdown (a fleet cut's ``services`` list) labeled by
    ``service_id`` — counters whose names are in
    ``ThroughputCounter.COUNTERS`` (plus dispatch/latency derivatives)
    are typed ``counter``, everything else ``gauge``."""
    from ..utils.metrics import ThroughputCounter

    counterish = set(ThroughputCounter.COUNTERS) | {
        "busy_s", "inflight_s", "compile_cache_hits"}
    samples = _prom_lines(stats)
    for m in stats.get("services") or ():
        sid = m.get("service_id")
        if sid is None:
            continue
        samples.extend(_prom_lines(
            {k: v for k, v in m.items() if k != "service_id"},
            label=f'{{service_id="{sid}"}}'))
    by_name: dict = {}
    for k, label, v in samples:
        by_name.setdefault(k, []).append((label, v))
    lines = []
    for k in sorted(by_name):
        kind = "counter" if k in counterish else "gauge"
        name = _prom_name(k)
        lines.append(f"# TYPE {name} {kind}")
        for label, v in by_name[k]:
            lines.append(f"{name}{label} {v}")
    return "\n".join(lines) + "\n"


def serve_status(port: int, snapshot_fn, host: str = "127.0.0.1"):
    """Stand up a LIVE scrape endpoint (ISSUE 20 satellite): a stdlib
    ``ThreadingHTTPServer`` on a daemon thread answering

    - ``GET /metrics`` — :func:`prometheus_text` of the CURRENT stats
      cut (``snapshot_fn()`` runs per request, so a scraper always
      sees live counters, not a stale dump);
    - ``GET /`` (or ``/snapshot``) — the full snapshot JSON document.

    ``snapshot_fn`` is any zero-arg callable returning a snapshot-
    shaped dict (usually ``lambda: fleet_snapshot(service)``; the
    operator CLI's ``--serve`` passes a file re-reader instead). A
    failing ``snapshot_fn`` answers 500 with the error named — a
    scrape must see the failure, not a hang. Pass ``port=0`` for an
    ephemeral port; the bound one is ``server.server_address[1]``.
    Returns the started server; call ``.shutdown()`` then
    ``.server_close()`` to stop it."""
    import http.server
    import threading

    class _Handler(http.server.BaseHTTPRequestHandler):
        def _send(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if path not in ("/", "/snapshot", "/metrics"):
                self._send(404, "text/plain; charset=utf-8",
                           b"unknown path (try / or /metrics)\n")
                return
            try:
                doc = snapshot_fn()
                if path == "/metrics":
                    body = prometheus_text(
                        doc.get("stats", {})).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    body = json.dumps(doc).encode()
                    ctype = "application/json"
            # analysis: ignore[broad-except] — scrape isolation: a
            # failing snapshot_fn (a stopped fleet, a torn file) must
            # answer 500, not kill the serving thread
            except Exception as e:
                self._send(500, "text/plain; charset=utf-8",
                           f"snapshot failed: {e!r}\n".encode())
                return
            self._send(200, ctype, body)

        def log_message(self, *a):  # scrapes are not operator events
            pass

    server = http.server.ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="obs-status-server")
    t.start()
    return server


def jsonable(x):
    """THE telemetry JSON projection (one implementation — the
    heartbeat telemetry cuts in ``ensemble.member_proc`` and the
    snapshot plane here must not drift): numpy scalars become numbers,
    tuples become lists, anything else becomes its repr — telemetry
    must never fail to serialize."""
    if isinstance(x, dict):
        return {str(k): jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [jsonable(v) for v in x]
    if isinstance(x, (str, int, float, bool, type(None))):
        return x
    try:
        import numpy as np

        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.ndarray):
            return x.tolist()
    except ImportError:  # pragma: no cover - numpy is a hard dep
        pass
    return repr(x)


_jsonable = jsonable  # the package-internal spelling


def timeline(ticket: int, **kw):
    """Post-mortem per-ticket timeline (``obs/postmortem.py`` has the
    join semantics); re-exported here so ``obs.timeline(ticket,
    journal_dir=...)`` is the one-call post-mortem entry point."""
    from .postmortem import reconstruct

    return reconstruct(ticket, **kw)
