"""Flight recorder — a bounded ring of recent ticket-lifecycle events
per service (ISSUE 15 tentpole, part 3).

The journals are the durable ledger, but they are append-only files
tuned for replay, and the tracer ring is duration-shaped. When a fence,
a quarantine or a ``HibernationError`` fires, the question an operator
asks first is "what was this service doing in the seconds before?" —
the flight recorder answers it: every lifecycle seam (submit, dispatch,
served, quarantined, expired, shed, hibernate, wake, fence, respawn)
drops one tiny event into a per-service ring, and any failure worth a
``FailureEvent`` DUMPS the ring alongside it (``dumps`` in memory,
JSON files when the recorder was built with ``dump_dir=`` — the CLI's
``--status PATH`` installs one dumping under ``PATH.flight.d/``), so
the post-mortem starts with the recent history already cut out.

Design constraints, in order:

- **cheap enough to leave on**: one dict, one deque append, one leaf
  lock — no I/O on the record path (I/O happens only at dump time,
  which is already a failure path);
- **bounded everywhere**: per-service rings hold ``capacity`` events,
  the in-memory dump list holds ``max_dumps`` dumps, dump files are
  ring-sized;
- **process-wide default** (``get_recorder``/``set_recorder``, the
  ``get_tracer`` pattern): the serving stack records into it without
  plumbing a handle through every constructor; tests swap a fresh one.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

__all__ = [
    "FlightRecorder",
    "get_recorder",
    "set_recorder",
]

#: events kept per service ring
DEFAULT_CAPACITY = 256
#: in-memory dumps kept (oldest discarded — a failure storm must not
#: grow memory without bound either)
DEFAULT_MAX_DUMPS = 32


class FlightRecorder:
    """Bounded per-service ring of lifecycle events + failure dumps
    (module docstring). Thread-safe behind one leaf lock (nothing is
    ever acquired under it — the serving stack records from under its
    own locks, so this one must stay a leaf)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 max_dumps: int = DEFAULT_MAX_DUMPS,
                 dump_dir: Optional[str] = None):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._rings: dict[str, collections.deque] = {}
        #: most recent failure dumps: {reason, service_id, t_wall,
        #: events, path} — newest last
        self.dumps: collections.deque = collections.deque(
            maxlen=int(max_dumps))
        self.dump_dir = dump_dir
        self._dump_seq = 0

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, *, service_id: Optional[str] = None,
               ticket: Optional[int] = None, **detail) -> None:
        """One lifecycle event into ``service_id``'s ring (None lands
        in the ``"fleet"`` ring). ``t_wall`` is stamped here so dumped
        rings order against journal records and spans."""
        ev = {"t_wall": time.time(), "kind": kind,
              "service_id": service_id, "ticket": ticket}
        if detail:
            ev.update(detail)
        key = service_id or "fleet"
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = collections.deque(maxlen=self.capacity)
                self._rings[key] = ring
            ring.append(ev)

    # -- dumping -------------------------------------------------------------

    def snapshot(self, service_id: Optional[str] = None) -> list:
        """The ring's current events (all rings merged by time when
        ``service_id`` is None) — newest last."""
        with self._lock:
            if service_id is not None:
                return list(self._rings.get(service_id, ()))
            merged: list = []
            for ring in self._rings.values():
                merged.extend(ring)
        merged.sort(key=lambda e: e["t_wall"])
        return merged

    def dump_ledger(self) -> list:
        """The current dump records, copied under the lock — iterating
        ``dumps`` directly races a concurrent failure's append (deque
        mutation during iteration raises)."""
        with self._lock:
            return list(self.dumps)

    def dump(self, reason: str, *, service_id: Optional[str] = None,
             ticket: Optional[int] = None) -> dict:
        """Cut the recent history out NOW (a fence, a quarantine, a
        ``HibernationError`` — anything that also lands a
        ``FailureEvent``): the affected service's ring plus the fleet
        ring, kept in ``dumps`` and written to ``dump_dir`` when
        configured. Returns the dump record."""
        events = self.snapshot(service_id)
        if service_id is not None:
            fleet = self.snapshot("fleet")
            events = sorted(events + fleet, key=lambda e: e["t_wall"])
        rec = {"reason": reason, "service_id": service_id,
               "ticket": ticket, "t_wall": time.time(),
               "events": events, "path": None}
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        if self.dump_dir is not None:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(
                    self.dump_dir, f"flight-{seq:04d}-{reason}.json")
                with open(path, "w") as fh:
                    json.dump(rec, fh)
                rec["path"] = path
            except OSError:
                # the dump is best-effort observability on a path that
                # is ALREADY failing — never let it cascade
                rec["path"] = None
        with self._lock:
            self.dumps.append(rec)
        return rec


# -- process-wide default recorder -------------------------------------------

_default = FlightRecorder()
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    return _default


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process-wide recorder (tests install a fresh one;
    ``--status`` serve runs install one with a dump dir); returns the
    previous."""
    global _default
    with _default_lock:
        prev, _default = _default, recorder
    return prev
