"""Serial NumPy oracle: bit-exact golden reference for every backend.

The reference repo has no tests (SURVEY §4); its one correctness contract is
mass conservation under the sharded stencil update (``Model.hpp:88-95``).
This module is the framework's independent ground truth: a deliberately
naive, loop-free-but-unfused NumPy implementation of the exact same
semantics as ``ops.stencil`` — used to golden-compare the JAX serial path,
the sharded paths (1-D/2-D), the Pallas kernel, and the native C++ runtime.

Kept free of any jax import so it cannot share bugs with the code under test.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .core.cell import MOORE_OFFSETS, moore_neighbors, neighbor_count_grid


def shift2d_np(x: np.ndarray, dx: int, dy: int) -> np.ndarray:
    out = np.zeros_like(x)
    h, w = x.shape
    xs, xe = max(0, -dx), min(h, h - dx)
    ys, ye = max(0, -dy), min(w, w - dy)
    out[xs:xe, ys:ye] = x[xs + dx:xe + dx, ys + dy:ye + dy]
    return out


def transport_np(values: np.ndarray, outflow: np.ndarray,
                 counts: Optional[np.ndarray] = None,
                 offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS) -> np.ndarray:
    if counts is None:
        counts = neighbor_count_grid(*values.shape, offsets=offsets,
                                     dtype=values.dtype)
    share = outflow / counts
    inflow = np.zeros_like(values)
    for dx, dy in offsets:
        inflow += shift2d_np(share, dx, dy)
    return values - outflow + inflow


def dense_flow_step_np(values: np.ndarray, rate: float | np.ndarray,
                       offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS) -> np.ndarray:
    return transport_np(values, np.asarray(rate, dtype=values.dtype) * values,
                        offsets=offsets)


def point_flow_step_np(values: np.ndarray, x: int, y: int, amount: float,
                       offsets: Sequence[tuple[int, int]] = MOORE_OFFSETS) -> np.ndarray:
    """Scalar-loop oracle of the reference's live step (``Model.hpp:176-235``):
    source sheds ``amount``; each in-bounds neighbor gains ``amount/k`` where
    k is the source's neighbor count."""
    h, w = values.shape
    neigh = moore_neighbors(x, y, h, w, offsets)
    out = values.copy()
    out[x, y] -= amount
    for nx, ny in neigh:
        out[nx, ny] += amount / len(neigh)
    return out


def cut_np(G: np.ndarray, rs: int, re: int, cs: int, ce: int) -> np.ndarray:
    """``G[rs:re, cs:ce]`` with zero-fill outside the grid — exactly what
    a ppermute halo exchange delivers to a shard at a true grid edge."""
    H, W = G.shape
    out = np.zeros((re - rs, ce - cs), G.dtype)
    rs_c, re_c = max(rs, 0), min(re, H)
    cs_c, ce_c = max(cs, 0), min(ce, W)
    if rs_c < re_c and cs_c < ce_c:
        out[rs_c - rs:re_c - rs, cs_c - cs:ce_c - cs] = G[rs_c:re_c,
                                                          cs_c:ce_c]
    return out


def ring_from_global_np(G: np.ndarray, r0: int, c0: int, h: int, w: int,
                        d: int) -> dict:
    """The depth-``d`` ghost ring a shard at global offset (r0, c0) would
    receive from the two-stage ppermute exchange, cut directly from the
    global grid (``parallel.halo.exchange_ring``'s layout: n/s [d, w],
    w/e [h, d], corners [d, d]; zeros at true grid edges). Ground truth
    for the halo-mode Pallas kernels' silicon gates and tests."""
    return {
        "n": cut_np(G, r0 - d, r0, c0, c0 + w),
        "s": cut_np(G, r0 + h, r0 + h + d, c0, c0 + w),
        "w": cut_np(G, r0, r0 + h, c0 - d, c0),
        "e": cut_np(G, r0, r0 + h, c0 + w, c0 + w + d),
        "nw": cut_np(G, r0 - d, r0, c0 - d, c0),
        "ne": cut_np(G, r0 - d, r0, c0 + w, c0 + w + d),
        "sw": cut_np(G, r0 + h, r0 + h + d, c0 - d, c0),
        "se": cut_np(G, r0 + h, r0 + h + d, c0 + w, c0 + w + d),
    }


def reference_run_np(dim_x: int = 100, dim_y: int = 100,
                     src: tuple[int, int] = (19, 3),
                     snapshot_value: float = 2.2, rate: float = 0.1,
                     init: float = 1.0, steps: int = 1,
                     dtype=np.float64) -> np.ndarray:
    """The reference's exact live run (``Main.cpp:25-35``): 100×100 grid of
    1.0, one Exponencial step moving ``0.1 * 2.2`` out of (19, 3). The
    snapshot value never updates (``Flow.hpp:22-28``), so every step moves
    the same amount."""
    values = np.full((dim_x, dim_y), init, dtype=dtype)
    for _ in range(steps):
        values = point_flow_step_np(values, *src, rate * snapshot_value)
    return values
