"""Protocol audit (ISSUE 19 layer 4): journal/wire vocabulary
conformance against the DECLARED protocol surfaces.

The first three layers check code shape: layer 1 lints one module's
AST, layer 2 audits one traced step's jaxpr, layer 3 models the
whole program's locks. None of them can see the failure mode ISSUE 19
is about: a protocol whose writer and reader drift apart. A journal
record kind appended that no replay fold dispatches on, a meta key a
timeline reads that no append site stamps, an RPC the server handles
that no client ever sends — each is invisible module-locally, type
checks fine, and silently corrupts recovery the day a crash crosses
it.

This layer extracts both sides of every protocol conversation from the
package AST and checks them against the single sources of truth:

- ``ensemble.lifecycle`` — the declared ticket-lifecycle machines
  (record kinds, transitions, per-kind meta keys, terminal set, the
  FailureEvent kind set). Loaded standalone (stdlib-only by contract),
  never through the jax-laden ensemble package init.
- ``ensemble.wire`` — the declared RPC vocabulary
  (``REQUEST_KINDS``/``REPLY_KINDS``), read off the module AST.

Rules (registered in the shared registry; same CLI, pragmas and repo
gate as every other layer):

``journal-kind-drift`` (ERROR)
    a journal append site writes a record kind no machine declares, a
    reader fold dispatches on one, or (whole-package runs only) a
    declared kind is never written anywhere — the declaration and the
    code disagree about the stream vocabulary.
``journal-meta-drift`` (WARNING)
    a reader pulls a meta key (``rec.meta.get(...)``) no transition
    declares and no universal stamp provides, or a literal append meta
    stamps a key its kind's transition does not declare — the key will
    be silently None (reader side) or silently unread (writer side).
``rpc-asymmetry`` (ERROR)
    the member wire protocol's two halves disagree: a request kind the
    server dispatches that no client call site sends (dead handler), a
    kind a client sends that the server never dispatches (runtime
    ``err`` reply), a reply kind outside the declared vocabulary
    (``wire.send`` raises at runtime), or a reply meta field a client
    reads that no server code path stamps.
``rpc-no-deadline`` (ERROR)
    a raw wire ``.send(...)``/``.recv(...)`` on a conn-ish receiver
    with no ``deadline_s=`` decision — a dead peer turns the call into
    an unbounded stall. Passing an explicit ``deadline_s=None`` is a
    recorded decision and passes; saying nothing is not.
``terminal-coverage`` (ERROR)
    in a journaling class, a method removes a ticket from a ledger
    (``_route``/``_resolved``/``_hibernated``/…) without emitting any
    declared terminal or re-admission transition, calling a sanctioned
    resolution helper (``*_finalize*``/``*_resolve*``/``*_reclaim*``/
    ``*_readmit*``), or being a ``poll``-style result handoff — the
    ticket leaves the ledger with no journal evidence, so replay
    reconstructs a state the process never had.
``event-kind-coverage`` (ERROR)
    a ``FailureEvent(kind=...)`` constructed with a kind outside the
    declared :data:`lifecycle.EVENT_KINDS` — the supervisor taxonomy,
    the obs timeline and the analysis all dispatch on that set.

Extraction is resolution-based, never guessed: record kinds resolve
through string literals, lifecycle constants (``SERVED``,
``lifecycle.SERVED``), module-level constant assignments, single-
function local assignments and ``IfExp`` branches; an unresolvable
kind contributes nothing (the astlint ``journal-kind-literal`` rule
separately forbids raw literals at append sites, so the two rules
squeeze from both ends). Reader dispatch is anchored on the package's
journal-record convention (``rec.kind`` / ``record.kind``), so
``FailureEvent.kind`` and fault-plan dispatches never alias in.

The whole-package entry point is :func:`run_protocol_audit`;
:func:`lint_protocol_source` is the single-module fixture surface
(package-completeness directions — declared-but-never-written,
declared-but-unused request kinds — stay quiet there).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Optional

from .registry import (RULES, Finding, Rule, Severity, apply_pragmas,
                       collect_pragmas)

#: registry scope tag for the protocol rules (run by THIS engine over
#: writer/reader pairs, never by the per-module AST engine)
SCOPE_PROTOCOL = "protocol"


def _register(name: str, severity: Severity, doc: str,
              fix_hint: str = "") -> None:
    if name not in RULES:
        RULES[name] = Rule(name, severity, doc,
                           check=lambda ctx: (), scope=SCOPE_PROTOCOL,
                           fix_hint=fix_hint)


_register("journal-kind-drift", Severity.ERROR,
          "a journal record kind written or dispatched on that the "
          "declared lifecycle machines do not know (or, package-wide, "
          "a declared kind nothing writes) — writers, readers and the "
          "declaration must share one vocabulary",
          fix_hint="declare the kind as a lifecycle.Transition on its "
                   "machine (and write the site through the constant), "
                   "or fix the drifted literal")
_register("journal-meta-drift", Severity.WARNING,
          "a journal meta key read that no transition declares and no "
          "universal stamp provides (silently None forever), or a "
          "literal append meta stamping a key its kind does not "
          "declare (silently unread forever)",
          fix_hint="add the key to the owning Transition's meta tuple "
                   "in ensemble/lifecycle.py, or stop reading/stamping "
                   "it")
_register("rpc-asymmetry", Severity.ERROR,
          "the member RPC protocol's halves disagree: a handled "
          "request kind no client sends, a sent kind no server "
          "handles, an undeclared reply kind, or a reply field read "
          "that no server stamps",
          fix_hint="make the server dispatch, the client call sites "
                   "and wire.REQUEST_KINDS/REPLY_KINDS agree — delete "
                   "the dead half or add the missing one")
_register("rpc-no-deadline", Severity.ERROR,
          "a raw wire .send()/.recv() with no deadline_s decision "
          "turns a dead peer into an unbounded stall; an explicit "
          "deadline_s=None records the decision to block",
          fix_hint="pass deadline_s=<seconds> (or an explicit "
                   "deadline_s=None with the blocking rationale in a "
                   "comment)")
_register("terminal-coverage", Severity.ERROR,
          "a journaling class removes a ticket from a ledger on a "
          "path that journals no terminal or re-admission transition "
          "— replay would reconstruct a ticket state the process "
          "never had",
          fix_hint="journal a declared terminal/re-admission kind on "
                   "that path, or route the removal through a "
                   "*_finalize/*_resolve/*_reclaim/*_readmit helper "
                   "that does")
_register("event-kind-coverage", Severity.ERROR,
          "a FailureEvent constructed with a kind outside the "
          "declared lifecycle.EVENT_KINDS set — the supervisor, the "
          "timeline and the failure taxonomy all dispatch on it",
          fix_hint="use a declared EVENT_KINDS member, or extend the "
                   "set in ensemble/lifecycle.py if the taxonomy "
                   "genuinely grew")


# -- declared-vocabulary loaders ----------------------------------------------

_LIFECYCLE = None


def _lifecycle():
    """The declared machines, loaded STANDALONE from
    ``ensemble/lifecycle.py`` (stdlib-only by contract) — importing it
    through the package would execute ``ensemble/__init__`` and pull
    jax into a lint run."""
    global _LIFECYCLE
    if _LIFECYCLE is None:
        import importlib.util
        import sys

        path = (Path(__file__).resolve().parent.parent
                / "ensemble" / "lifecycle.py")
        spec = importlib.util.spec_from_file_location(
            "_mpi_model_lifecycle_decl", path)
        mod = importlib.util.module_from_spec(spec)
        # dataclass construction resolves the defining module through
        # sys.modules — register before exec, like importlib itself
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _LIFECYCLE = mod
    return _LIFECYCLE


_WIRE_VOCAB = None


def _wire_vocab() -> tuple[tuple, tuple]:
    """``(REQUEST_KINDS, REPLY_KINDS)`` read off ``ensemble/wire.py``'s
    AST — the declaration is a pair of literal tuples, and parsing
    keeps the audit import-free."""
    global _WIRE_VOCAB
    if _WIRE_VOCAB is None:
        path = (Path(__file__).resolve().parent.parent
                / "ensemble" / "wire.py")
        found = {"REQUEST_KINDS": (), "REPLY_KINDS": ()}
        for node in ast.parse(path.read_text()).body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name) and tgt.id in found
                        and isinstance(node.value, ast.Tuple)):
                    found[tgt.id] = tuple(
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
        _WIRE_VOCAB = (found["REQUEST_KINDS"], found["REPLY_KINDS"])
    return _WIRE_VOCAB


def _declared_kinds() -> frozenset:
    lc = _lifecycle()
    out = set()
    for m in lc.MACHINES.values():
        out.update(m.kinds())
    return frozenset(out)


def _declared_meta_keys() -> frozenset:
    lc = _lifecycle()
    out = set()
    for m in lc.MACHINES.values():
        out |= m.meta_keys()
    return frozenset(out)


def _kind_meta(kind: str) -> Optional[frozenset]:
    """Declared meta keys for ``kind`` (union over machines declaring
    it) plus the universal stamps; None when no machine declares it."""
    lc = _lifecycle()
    out: Optional[set] = None
    for m in lc.MACHINES.values():
        t = m.transition(kind)
        if t is not None:
            out = (out or set(lc.STAMPED_META)) | set(t.meta)
    return frozenset(out) if out is not None else None


def _resolution_kinds() -> frozenset:
    """Kinds whose journal record accounts for a ticket leaving a
    ledger: every terminal plus every declared re-admission/attribution
    transition (non-initial sources — migrate/readmit/wake/requeue)."""
    lc = _lifecycle()
    out = set()
    for m in lc.MACHINES.values():
        out.update(m.terminal_kinds())
        out.update(m.attribution_kinds())
    return frozenset(out)


# -- expression → record-kind resolution --------------------------------------

#: same-class helpers that append a journal record (the package's two
#: naming conventions; ``.append`` on a journal-ish receiver also
#: counts — see ``_append_call_kind``)
_APPEND_HELPERS = ("_journal_append_locked", "_append_locked")

_JOURNALISH = ("journal",)


def _terminal_name(node: ast.AST) -> str:
    """The last name segment of a receiver chain (``self.a.journal`` →
    ``journal``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_append_call(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in _APPEND_HELPERS:
            return True
        if fn.attr == "append":
            recv = _terminal_name(fn.value).lower()
            return any(tok in recv for tok in _JOURNALISH)
        return False
    if isinstance(fn, ast.Name):
        return fn.id in _APPEND_HELPERS
    return False


def _module_const_map(tree: ast.Module) -> dict:
    """Module-level ``NAME = "literal"`` assignments (how a module may
    alias a kind without importing the constant)."""
    out: dict = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = {node.value.value}
    return out


def _local_str_map(fn: ast.AST, module_map: dict) -> dict:
    """name → set of possible string values for single-name locals
    assigned from resolvable expressions inside ``fn`` (multiple
    assignments union — the if/elif kind-classifier shape); a name with
    ANY unresolvable assignment maps to None."""
    out: dict = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        vals = _const_strs(node.value, module_map, {})
        if name in out and out[name] is not None and vals is not None:
            out[name] = out[name] | vals
        else:
            out[name] = vals if name not in out else None
    return out


def _const_strs(node: ast.AST, module_map: dict,
                local_map: dict) -> Optional[set]:
    """All string values ``node`` can take, resolved through literals,
    IfExp branches, module constants, function locals and lifecycle
    declarations — None when any path is unresolvable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, ast.IfExp):
        a = _const_strs(node.body, module_map, local_map)
        b = _const_strs(node.orelse, module_map, local_map)
        return a | b if a is not None and b is not None else None
    if isinstance(node, ast.Name):
        if node.id in local_map:
            return local_map[node.id]
        if node.id in module_map:
            return module_map[node.id]
        if node.id.isupper():
            v = getattr(_lifecycle(), node.id, None)
            if isinstance(v, str):
                return {v}
        return None
    if isinstance(node, ast.Attribute) and node.attr.isupper():
        v = getattr(_lifecycle(), node.attr, None)
        if isinstance(v, str):
            return {v}
    return None


# -- per-module fact extraction -----------------------------------------------

#: ticket ledgers whose removals must leave journal evidence
_LEDGERS = frozenset({
    "_route", "_resolved", "_results", "_pending",
    "_hib_meta", "_hib_resolved", "_hibernated",
})

#: same-class helpers sanctioned to own the journal evidence for a
#: removal routed through them
_RESOLUTION_HELPER = ("finalize", "resolve", "reclaim", "readmit")

#: method names that hand an ALREADY-journaled resolution to the caller
#: (the terminal record landed before the result entered the ledger)
_HANDOFF_METHODS = ("poll",)


class _ModuleFacts:
    """Everything the six rules need from one module, in one walk."""

    def __init__(self, source: str, path: str):
        self.path = path
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.pragmas = collect_pragmas(self.lines)
        self.module_map = _module_const_map(self.tree)
        #: (kinds | None, line, literal_meta_keys | None)
        self.appends: list = []
        #: (literal, line) — ``rec.kind == "x"`` reader dispatches
        self.dispatches: list = []
        #: (key, line) — ``rec.meta.get("k")`` / ``rec.meta["k"]``
        self.meta_reads: list = []
        #: (kinds | None, line) — FailureEvent(kind=...) sites
        self.event_kinds: list = []
        #: (kind, line) — request kinds a *Server class dispatches on
        self.server_kinds: list = []
        #: (kind, line) — request kinds client call sites send
        self.client_kinds: list = []
        #: (kind, line) — reply kinds *Server classes send
        self.reply_kinds: list = []
        #: literal meta keys any server code path could stamp in a reply
        self.reply_sent_keys: set = set()
        #: (key, line) — reply meta fields read at client call sites
        self.reply_reads: list = []
        #: (line, attr) — .send/.recv on conn-ish receiver, no deadline
        self.no_deadline: list = []
        #: (ledger, line, method) — uncovered ledger removals
        self.uncovered_removals: list = []
        self._walk()

    # -- walking --------------------------------------------------------------

    def _walk(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._walk_class(node)
        # module-level / free-function facts (fold helpers live outside
        # classes in journal.py)
        for fn in self._functions(self.tree, top_only=True):
            self._walk_function(fn, in_server=False)

    def _functions(self, root, top_only=False):
        out = []
        body = root.body if top_only else [root]
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(node)
        return out

    def _walk_class(self, cls: ast.ClassDef) -> None:
        is_server = cls.name.endswith("Server")
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        journaling = any(
            _is_append_call(c) for m in methods
            for c in ast.walk(m) if isinstance(c, ast.Call))
        for m in methods:
            self._walk_function(m, in_server=is_server)
            if journaling:
                self._check_removals(m)
        if is_server:
            self._collect_server_facts(cls, methods)

    def _walk_function(self, fn, in_server: bool) -> None:
        local_map = _local_str_map(fn, self.module_map)
        reply_vars = self._rpc_result_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._visit_call(node, local_map, in_server, reply_vars)
            elif isinstance(node, ast.Compare):
                self._visit_compare(node, in_server)
            elif isinstance(node, ast.Subscript):
                self._visit_subscript(node, reply_vars)
        # meta reads via literal for-loops: for k in ("a", "b"): m.get(k)
        self._visit_meta_loops(fn)

    # -- call/compare/subscript visitors --------------------------------------

    def _visit_call(self, node: ast.Call, local_map: dict,
                    in_server: bool, reply_vars: set) -> None:
        fn = node.func
        if _is_append_call(node) and node.args:
            kinds = _const_strs(node.args[0], self.module_map, local_map)
            meta_keys = None
            if len(node.args) > 1 and isinstance(node.args[1], ast.Dict):
                meta_keys = {k.value for k in node.args[1].keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str)}
            self.appends.append((kinds, node.lineno, meta_keys))
            return
        if (isinstance(fn, ast.Name) and fn.id == "FailureEvent") or (
                isinstance(fn, ast.Attribute)
                and fn.attr == "FailureEvent"):
            for kw in node.keywords:
                if kw.arg == "kind":
                    kinds = _const_strs(kw.value, self.module_map,
                                        local_map)
                    if kinds is not None:  # unresolvable: never guessed
                        self.event_kinds.append((kinds, node.lineno))
            return
        if isinstance(fn, ast.Attribute) and fn.attr == "_rpc":
            if node.args and isinstance(node.args[0], ast.Constant):
                self.client_kinds.append(
                    (node.args[0].value, node.lineno))
            return
        if isinstance(fn, ast.Attribute) and fn.attr in ("send", "recv"):
            recv = _terminal_name(fn.value).lower()
            if "conn" not in recv:
                return
            if not any(kw.arg == "deadline_s" for kw in node.keywords):
                self.no_deadline.append((node.lineno, fn.attr))
            requests, replies = _wire_vocab()
            if (fn.attr == "send" and node.args
                    and isinstance(node.args[0], ast.Constant)):
                kind = node.args[0].value
                if in_server:
                    self.reply_kinds.append((kind, node.lineno))
                elif kind in requests:
                    self.client_kinds.append((kind, node.lineno))

    def _visit_compare(self, node: ast.Compare, in_server: bool) -> None:
        left = node.left
        lits = [c.value for c in node.comparators
                if isinstance(c, ast.Constant)
                and isinstance(c.value, str)]
        # tuple membership: kind in ("a", "b")
        for c in node.comparators:
            if isinstance(c, (ast.Tuple, ast.List, ast.Set)):
                lits.extend(e.value for e in c.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str))
        if not lits:
            return
        if (isinstance(left, ast.Attribute) and left.attr == "kind"
                and isinstance(left.value, ast.Name)
                and left.value.id in ("rec", "record")):
            for lit in lits:
                self.dispatches.append((lit, node.lineno))
        elif (in_server and isinstance(left, ast.Name)
                and left.id == "kind"):
            for lit in lits:
                self.server_kinds.append((lit, node.lineno))

    def _visit_subscript(self, node: ast.Subscript,
                         reply_vars: set) -> None:
        if not (isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            return
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "meta":
            self.meta_reads.append((node.slice.value, node.lineno))
        elif isinstance(v, ast.Name) and v.id in reply_vars:
            self.reply_reads.append((node.slice.value, node.lineno))

    def _visit_meta_loops(self, fn) -> None:
        """``rec.meta.get("k")`` calls, plus key-Name resolution
        through literal for-loop tuples (the postmortem detail loop)."""
        loop_keys: dict = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.For)
                    and isinstance(node.target, ast.Name)
                    and isinstance(node.iter, (ast.Tuple, ast.List))):
                vals = {e.value for e in node.iter.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
                if vals:
                    loop_keys[node.target.id] = vals
        reply_vars = self._rpc_result_names(fn)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get" and node.args):
            # .get on something other than a meta/reply mapping is not
            # this layer's business
                continue
            recv = node.func.value
            keys: set = set()
            if isinstance(node.args[0], ast.Constant) and isinstance(
                    node.args[0].value, str):
                keys = {node.args[0].value}
            elif (isinstance(node.args[0], ast.Name)
                    and node.args[0].id in loop_keys):
                keys = loop_keys[node.args[0].id]
            if not keys:
                continue
            if isinstance(recv, ast.Attribute) and recv.attr == "meta":
                for k in keys:
                    self.meta_reads.append((k, node.lineno))
            elif isinstance(recv, ast.Name) and recv.id in reply_vars:
                for k in keys:
                    self.reply_reads.append((k, node.lineno))

    # -- RPC plumbing ---------------------------------------------------------

    def _rpc_result_names(self, fn) -> set:
        """Local names bound to the meta slot of an RPC result
        (``kind, meta, arrays = self._rpc(...)`` /
        ``... = self._conn.recv(...)``)."""
        out: set = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Tuple)
                    and len(node.targets[0].elts) == 3
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)):
                continue
            attr = node.value.func.attr
            recv = _terminal_name(node.value.func.value).lower()
            if attr == "_rpc" or (attr == "recv" and "conn" in recv):
                meta_t = node.targets[0].elts[1]
                if isinstance(meta_t, ast.Name) and meta_t.id != "_":
                    out.add(meta_t.id)
        return out

    def _collect_server_facts(self, cls: ast.ClassDef,
                              methods: list) -> None:
        """Every literal meta key any server path could stamp into a
        reply: dict-literal keys plus ``body["k"] = ...`` augmentations
        (a conservative superset — the asymmetry rule flags only reads
        OUTSIDE it, never a read it cannot prove missing)."""
        for node in ast.walk(cls):
            if isinstance(node, ast.Dict):
                self.reply_sent_keys.update(
                    k.value for k in node.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str))
            elif (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].slice, ast.Constant)
                    and isinstance(node.targets[0].slice.value, str)):
                self.reply_sent_keys.add(node.targets[0].slice.value)

    # -- terminal-coverage ----------------------------------------------------

    def _check_removals(self, fn) -> None:
        removals = []
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "pop"
                    and isinstance(node.func.value, ast.Attribute)
                    and node.func.value.attr in _LEDGERS):
                removals.append((node.func.value.attr, node.lineno))
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Subscript)
                            and isinstance(tgt.value, ast.Attribute)
                            and tgt.value.attr in _LEDGERS):
                        removals.append((tgt.value.attr, node.lineno))
        if not removals:
            return
        if any(fn.name == h or fn.name.startswith(h + "_")
               for h in _HANDOFF_METHODS):
            return
        local_map = _local_str_map(fn, self.module_map)
        resolution = _resolution_kinds()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_append_call(node) and node.args:
                kinds = _const_strs(node.args[0], self.module_map,
                                    local_map)
                # an unresolvable kind still counts as evidence — the
                # rule flags silence, not ambiguity
                if kinds is None or kinds & resolution:
                    return
            if (isinstance(node.func, ast.Attribute)
                    and any(tok in node.func.attr
                            for tok in _RESOLUTION_HELPER)):
                return
        for ledger, line in removals:
            self.uncovered_removals.append((ledger, line, fn.name))


# -- the audit ----------------------------------------------------------------


def _audit(facts: list, rules: Optional[Iterable[str]],
           complete: bool) -> list:
    lc = _lifecycle()
    requests, replies = _wire_vocab()
    declared = _declared_kinds()
    declared_meta = _declared_meta_keys()
    raw: list[Finding] = []

    def emit(rule_id, path, line, msg):
        raw.append(Finding(rule_id, RULES[rule_id].severity, path,
                           line, msg))

    written: set = set()
    server_seen = any(m.server_kinds for m in facts)
    client_seen = any(m.client_kinds for m in facts)
    for m in facts:
        for kinds, line, meta_keys in m.appends:
            for k in sorted(kinds or ()):
                written.add(k)
                if k not in declared:
                    emit("journal-kind-drift", m.path, line,
                         f"append site writes record kind {k!r} that "
                         "no declared lifecycle machine knows")
                elif meta_keys is not None:
                    allowed = _kind_meta(k) or frozenset()
                    for key in sorted(meta_keys - allowed):
                        emit("journal-meta-drift", m.path, line,
                             f"append meta stamps key {key!r} that the "
                             f"{k!r} transition does not declare — no "
                             "reader can rely on it")
        for lit, line in m.dispatches:
            if lit not in declared:
                emit("journal-kind-drift", m.path, line,
                     f"reader dispatches on record kind {lit!r} that "
                     "no declared lifecycle machine knows")
        for key, line in m.meta_reads:
            if key not in declared_meta:
                emit("journal-meta-drift", m.path, line,
                     f"reader pulls meta key {key!r} that no declared "
                     "transition stamps — it will be None forever")
        for kinds, line in m.event_kinds:
            for k in sorted(kinds):
                if k not in lc.EVENT_KINDS:
                    emit("event-kind-coverage", m.path, line,
                         f"FailureEvent kind {k!r} is outside the "
                         "declared EVENT_KINDS set")
        for kind, line in m.client_kinds:
            if kind not in requests:
                emit("rpc-asymmetry", m.path, line,
                     f"client sends request kind {kind!r} outside "
                     "wire.REQUEST_KINDS — wire.send raises at "
                     "runtime")
        for kind, line in m.reply_kinds:
            if kind not in replies:
                emit("rpc-asymmetry", m.path, line,
                     f"server sends reply kind {kind!r} outside "
                     "wire.REPLY_KINDS — wire.send raises at runtime")
        for line, attr in m.no_deadline:
            emit("rpc-no-deadline", m.path, line,
                 f"wire .{attr}() with no deadline_s decision — a "
                 "dead peer stalls this call forever")
        for ledger, line, fname in m.uncovered_removals:
            emit("terminal-coverage", m.path, line,
                 f"{fname}() removes a ticket from {ledger} without "
                 "journaling any terminal/re-admission transition or "
                 "routing through a resolution helper")

    if server_seen and client_seen:
        handled = {k for m in facts for k, _ in m.server_kinds}
        called = {k for m in facts for k, _ in m.client_kinds}
        sent_keys = set()
        for m in facts:
            sent_keys |= m.reply_sent_keys
        for m in facts:
            for kind, line in m.server_kinds:
                if kind not in called:
                    emit("rpc-asymmetry", m.path, line,
                         f"server handles request kind {kind!r} that "
                         "no client call site ever sends (dead "
                         "handler)")
            for kind, line in m.client_kinds:
                if kind not in handled:
                    emit("rpc-asymmetry", m.path, line,
                         f"client sends request kind {kind!r} the "
                         "server never dispatches on — every call "
                         "gets the unknown-RPC err reply")
            for key, line in m.reply_reads:
                if key not in sent_keys:
                    emit("rpc-asymmetry", m.path, line,
                         f"client reads reply field {key!r} that no "
                         "server code path stamps — it is never "
                         "present")
        if complete:
            anchor = next((m for m in facts if m.server_kinds), None)
            for kind in requests:
                if kind not in handled and kind not in called:
                    emit("rpc-asymmetry", anchor.path, 1,
                         f"wire.REQUEST_KINDS declares {kind!r} but "
                         "nothing handles or sends it")

    if complete and written:
        lc_path = None
        for m in facts:
            if m.path.replace("\\", "/").endswith(
                    "ensemble/lifecycle.py"):
                lc_path = m.path
        for kind in sorted(declared - written):
            emit("journal-kind-drift",
                 lc_path or "mpi_model_tpu/ensemble/lifecycle.py", 1,
                 f"lifecycle declares record kind {kind!r} but no "
                 "append site ever writes it")

    if rules is not None:
        want = set(rules)
        raw = [f for f in raw if f.rule in want]
    raw.sort(key=lambda f: (f.path, f.line, f.rule))
    by_mod = {m.path: m for m in facts}
    out: list[Finding] = []
    for path in sorted({f.path for f in raw}):
        mod = by_mod.get(path)
        group = [f for f in raw if f.path == path]
        if mod is None:
            out.extend(group)
        else:
            out.extend(apply_pragmas(group, mod.pragmas, mod.lines))
    return out


# -- entry points -------------------------------------------------------------


def lint_protocol_source(source: str,
                         path: str = "mpi_model_tpu/fake.py",
                         rules: Optional[Iterable[str]] = None
                         ) -> list[Finding]:
    """Single-module fixture surface for the tests
    (package-completeness directions stay quiet here)."""
    return _audit([_ModuleFacts(source, path)], rules, complete=False)


def _default_roots() -> list[Path]:
    pkg = Path(__file__).resolve().parent.parent
    return [pkg]


def run_protocol_audit(roots=None, rules=None,
                       rel_to=None) -> list[Finding]:
    """The layer-4 entry point: extract writer/reader facts from every
    package module and audit them against the declared vocabularies."""
    from .concurrency import _package_sources

    roots = list(roots) if roots else _default_roots()
    facts = [_ModuleFacts(source, shown)
             for source, shown in _package_sources(roots, rel_to)]
    if not facts:
        return []
    return _audit(facts, rules, complete=True)
