"""Concurrency audit (ISSUE 12 layer 3): a whole-program lock model and
inter-procedural lock-acquisition graph over the threaded serving stack.

The AST lint (layer 1) checks one module at a time; the jaxpr audit
(layer 2) checks one traced step at a time. Neither can see the shape
that actually deadlocks a fleet: method A of one class acquiring lock L1
and then CALLING into another class whose method acquires L2, while some
other path nests them the opposite way. This layer builds that view:

**Lock model** — per class: which attributes are synchronization
primitives (the ``LOCKISH`` name test, shared with layer 1's
``unguarded-shared-mutation`` rule), whether each is re-entrant
(``RLock``/``Condition`` vs plain ``Lock`` — read off the constructor,
``threading.*`` or the ``resilience.lockdep`` factories), which methods
acquire them (``with self.<lock>:`` or the ``*_locked``
caller-holds-the-lock naming convention), and — for classes that spawn a
``threading.Thread(target=self.X)`` — which methods run on the
pump/supervisor thread vs the client surface.

**Call resolution** — receiver types are inferred from the code the repo
already writes: ``self.x = ClassName(...)`` bindings, ``self.x: T``
annotations (dataclass fields included, ``Optional[T]``/``dict[K, V]``/
``list[T]`` unwrapped), parameter and return annotations, and simple
local aliases (``sched = self.scheduler``). An attribute call whose
receiver does not resolve to a modeled class is treated as UNKNOWN —
never guessed by bare method name, so ``self.member_log.append`` can not
alias into ``TicketJournal.append``.

**Acquisition graph** — an edge ``A → B`` means some code path acquires
lock key ``B`` (directly or through any resolvable call chain) while
holding ``A``. Lock keys are the strings the runtime witness uses
(``"EnsembleScheduler._lock"`` — taken from the ``lockdep`` factory
argument when present, else ``Class.attr``), so the static graph and
``resilience.lockdep``'s recorded runtime orders are directly
comparable: ``static_lock_graph()`` is what the armed witness asserts
observed orders against.

Rules on top of the model (registered in the shared registry, reported
through the same CLI/pragma machinery as every other rule):

``lock-order`` (ERROR)
    a cycle in the acquisition graph — two paths nesting the same locks
    in opposite orders is a potential deadlock the instant both run
    concurrently. Same-key nesting is allowed only for re-entrant locks
    (the sync scheduler's submit→dispatch RLock re-entry); a plain Lock
    nested under itself is a self-deadlock and flags.
``blocking-under-lock`` (WARNING)
    device work (``jnp.*`` dispatch, ``device_get``,
    ``block_until_ready``, ``np.asarray``), file I/O (``open``/
    ``write``/``flush``), ``Thread.join`` or sleeps while a lock is
    held — directly, or through any resolvable call chain. Every thread
    that wants the lock stalls behind the blocked holder; the finding
    names the chain. ``Condition.wait`` is exempt (it releases the
    lock), and calls to a same-class ``*_locked`` helper are reported
    inside the helper, not at every caller.
``lock-leak`` (ERROR)
    a bare ``.acquire()`` on a lock outside ``with``/``try‥finally`` —
    any exception between acquire and release leaves the lock held
    forever.
``thread-shared-without-lock`` (WARNING)
    an attribute written on the pump/supervisor thread and read from the
    client surface (or vice versa) with NO lock discipline at any
    access site — the torn-read twin of layer 1's
    ``unguarded-shared-mutation`` (which only sees writes).

The analysis is conservative where it must be (a resolvable call's
transitive acquisitions all count) and silent where it cannot know (an
unresolvable receiver contributes nothing) — the escape hatch is the
same reasoned pragma every other rule uses.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable, Optional

from .registry import (RULES, Finding, Rule, Severity, apply_pragmas,
                       collect_pragmas)

#: registry scope tag for the concurrency rules (run by THIS engine over
#: the whole program, never by the per-module AST engine)
SCOPE_CONCURRENCY = "concurrency"


def _register(name: str, severity: Severity, doc: str,
              fix_hint: str = "") -> None:
    if name not in RULES:
        RULES[name] = Rule(name, severity, doc,
                           check=lambda ctx: (), scope=SCOPE_CONCURRENCY,
                           fix_hint=fix_hint)


_register("lock-order", Severity.ERROR,
          "a cycle in the inter-procedural lock-acquisition graph (or a "
          "non-reentrant lock nested under itself) is a potential "
          "deadlock — keep every path acquiring locks in one global "
          "order",
          fix_hint="acquire the locks in the documented global order "
                   "(or release the outer lock before taking the "
                   "inner one)")
_register("blocking-under-lock", Severity.WARNING,
          "device work (jnp dispatch/device_get/block_until_ready), "
          "file I/O, Thread.join or sleeps while holding a lock stall "
          "every thread contending for it — move the work outside the "
          "lock or pragma the reasoned exception",
          fix_hint="snapshot state under the lock, release it, then do "
                   "the blocking work on the snapshot")
_register("lock-leak", Severity.ERROR,
          "bare .acquire() outside with/try-finally leaks the lock on "
          "any exception between acquire and release",
          fix_hint="use `with lock:` (or wrap acquire/release in "
                   "try/finally)")
_register("thread-shared-without-lock", Severity.WARNING,
          "an attribute written on the pump/supervisor thread and read "
          "from the client surface with no common lock is a torn-read "
          "race (the read-side twin of unguarded-shared-mutation)",
          fix_hint="read the attribute under the same lock the writer "
                   "holds (a *_locked accessor keeps it explicit)")


# -- the shared lock model (layer 1's unguarded-shared-mutation re-fronts
# -- these — one definition of "what is a lock" for the whole subsystem)

#: attribute names that read as a synchronization primitive. The tokens
#: are anchored at name-segment boundaries: `_lock`, `lock_cv`,
#: `_condition` qualify; `_clock`, `block_size`, `seconds` must NOT — a
#: bare substring match would classify a scheduler's injectable
#: `self._clock` as a lock and emit `with self._clock:` guidance.
LOCKISH = re.compile(
    r"(?:^|_)(?:lock|mutex|condition|cond|cv)(?:$|_)", re.IGNORECASE)

#: constructor names that build a NON-re-entrant primitive; everything
#: else lockish (RLock, Condition, the lockdep condition/rlock
#: factories, unknowns) is treated as re-entrant — the permissive
#: default, so an unrecognized constructor can't fabricate a same-key
#: deadlock finding
_NONREENTRANT_CTORS = {"Lock", "lock"}


def target_root(node: ast.AST) -> Optional[ast.AST]:
    """The root expression of an assignment-target chain
    (``self.a.b[k]`` → the ``self`` Name), descending Attribute/
    Subscript/Starred wrappers."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        node = node.value
    return node


def self_write_targets(node: ast.AST) -> list[ast.AST]:
    """Assignment-target expressions rooted at ``self`` for a write
    statement (tuple targets unpacked), else []."""
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = list(node.targets)
    else:
        return []
    flat: list[ast.AST] = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    out = []
    for t in flat:
        if isinstance(t, ast.Name):
            continue  # plain local — never shared state
        root = target_root(t)
        if isinstance(root, ast.Name) and root.id == "self":
            out.append(t)
    return out


def module_is_threaded(tree: ast.Module) -> bool:
    """True when the module imports ``threading`` OR the runtime lock
    witness (``resilience.lockdep``) — a module whose locks come from
    the lockdep factories is exactly as threaded as one calling
    ``threading.RLock()`` directly, and the shared-mutation/concurrency
    rules must treat them identically."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                parts = a.name.split(".")
                if parts[0] == "threading" or parts[-1] == "lockdep":
                    return True
        elif isinstance(node, ast.ImportFrom):
            mod = (node.module or "").split(".")
            if mod[0] == "threading" or mod[-1] == "lockdep":
                return True
            if any(a.name == "lockdep" for a in node.names):
                return True
    return False


def lock_attrs_bound_in_class(cls: ast.ClassDef) -> set[str]:
    """Names of self.<attr> bound ANYWHERE in the class whose attr reads
    as a lock (``self._lock = threading.RLock()``, ``self._lock_cv =
    ...``). Scanning every method (not just __init__) is deliberate: a
    supervisor that creates or replaces a synchronization primitive
    outside construction is still lock-owning — a lock bound late
    protects state exactly as much as one bound in __init__."""
    out: set[str] = set()
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for node in ast.walk(stmt):
                for t in self_write_targets(node):
                    if (isinstance(t, ast.Attribute)
                            and LOCKISH.search(t.attr)):
                        out.add(t.attr)
    return out


def under_lock_with(parents: dict, node: ast.AST, method: ast.AST) -> bool:
    """True when ``node`` sits inside a ``with self.<lockish>:`` (or
    Condition) block within ``method`` — layer 1's write-guard test."""
    cur = parents.get(node)
    while cur is not None and cur is not method:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                for n in ast.walk(item.context_expr):
                    if (isinstance(n, ast.Attribute)
                            and LOCKISH.search(n.attr)):
                        root = target_root(n)
                        if isinstance(root, ast.Name) and root.id == "self":
                            return True
        cur = parents.get(cur)
    return False


# -- blocking-primitive classification ----------------------------------------

#: call last-names that block wherever they appear: host syncs, sleeps,
#: the raw file open
_BLOCKING_NAMES = {"block_until_ready", "device_get", "device_put",
                   "sleep", "open"}
#: attribute calls that are file/host I/O on their receiver
_IO_ATTRS = {"write", "flush", "fsync", "tobytes"}
#: numpy module aliases whose asarray/save materialize on host
_NP_ALIASES = {"np", "numpy", "onp"}
_NP_BLOCKING_ATTRS = {"asarray", "ascontiguousarray", "save", "savez",
                      "load"}
#: receivers whose .join is path assembly, not thread synchronization
_JOIN_SAFE_RECEIVERS = {"path", "os", "sep"}


def _dotted_last(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call blocks (host sync / I/O / join / sleep / device
    dispatch), or None. ``Condition.wait`` is NOT blocking-under-lock:
    waiting releases the lock — that is its whole point."""
    fn = call.func
    name = _dotted_last(fn)
    if name is None:
        return None
    if name in _BLOCKING_NAMES:
        return f"`{name}` blocks the calling thread"
    if not isinstance(fn, ast.Attribute):
        return None
    recv = fn.value
    recv_name = _dotted_last(recv)
    if recv_name in _NP_ALIASES and name in _NP_BLOCKING_ATTRS:
        return (f"`{recv_name}.{name}` materializes device state on "
                "host (a device_get)")
    if recv_name == "jnp":
        return f"`jnp.{name}` dispatches device work eagerly"
    if name in _IO_ATTRS and not isinstance(recv, ast.Constant):
        return f"`.{name}()` is file/host I/O"
    if name == "item" and not isinstance(recv, ast.Constant):
        return "`.item()` is a host sync"
    if (name == "join" and not isinstance(recv, ast.Constant)
            and recv_name not in _JOIN_SAFE_RECEIVERS):
        return "`.join()` waits for another thread"
    return None


# -- type references and annotation parsing -----------------------------------

# A TypeRef is ("cls", name) | ("list", TypeRef) | ("dict", value TypeRef)
# | None — just enough typing to resolve the receiver chains the serving
# stack actually writes.


def _ann_to_type(ann, classes: dict):
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, (ast.Name, ast.Attribute)):
        name = _dotted_last(ann)
        return ("cls", name) if name in classes else None
    if isinstance(ann, ast.Subscript):
        base = _dotted_last(ann.value)
        sl = ann.slice
        if base in ("Optional",):
            return _ann_to_type(sl, classes)
        if base in ("list", "List", "Sequence", "Iterable", "Iterator"):
            return _wrap("list", _ann_to_type(sl, classes))
        if base in ("dict", "Dict", "OrderedDict", "defaultdict"):
            if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
                return _wrap("dict", _ann_to_type(sl.elts[1], classes))
            return None
    return None


def _wrap(kind, inner):
    return (kind, inner) if inner is not None else None


# -- program model ------------------------------------------------------------


@dataclasses.dataclass
class ModuleInfo:
    path: str
    stem: str
    tree: ast.Module
    lines: list[str]
    pragmas: dict
    threaded: bool
    parents: dict
    #: module-level lock Name → (key, reentrant)
    module_locks: dict


@dataclasses.dataclass
class FuncInfo:
    qual: str
    node: ast.AST
    module: ModuleInfo
    cls: Optional["ClassInfo"] = None
    #: direct lock keys acquired by `with` in this body
    direct_acquires: set = dataclasses.field(default_factory=set)
    #: resolved callee quals (for the fixpoints)
    callees: set = dataclasses.field(default_factory=set)
    #: (line, reason) of directly blocking calls in this body
    direct_blocking: list = dataclasses.field(default_factory=list)
    #: transitive results (filled by the fixpoints)
    may_acquire: set = dataclasses.field(default_factory=set)
    blocking_chain: Optional[str] = None

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]

    @property
    def caller_holds(self) -> bool:
        return (self.cls is not None and bool(self.cls.locks)
                and self.name.endswith("_locked"))


@dataclasses.dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    module: ModuleInfo
    #: lock attr → (key, reentrant)
    locks: dict = dataclasses.field(default_factory=dict)
    attr_types: dict = dataclasses.field(default_factory=dict)
    methods: dict = dataclasses.field(default_factory=dict)
    #: methods named as a Thread target= (the pump/supervisor entries)
    thread_targets: set = dataclasses.field(default_factory=set)


class Program:
    """Every modeled module, class and function, plus the resolved
    acquisition graph — built once per audit run."""

    def __init__(self):
        self.modules: list[ModuleInfo] = []
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}  # module-level, by name
        self.funcs_by_qual: dict[str, FuncInfo] = {}
        #: lock key → re-entrant? (so a transitive same-key acquisition
        #: of a plain Lock still reads as the self-deadlock it is)
        self.lock_reentrant: dict = {}
        #: (from_key, to_key) → (path, line, description) first witness
        self.edges: dict = {}
        self.findings: list[Finding] = []

    # -- construction --------------------------------------------------------

    def add_module(self, source: str, path: str) -> None:
        tree = ast.parse(source, filename=path)
        parents: dict = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        mod = ModuleInfo(
            path=path, stem=Path(path).stem, tree=tree,
            lines=source.splitlines(),
            pragmas=collect_pragmas(source.splitlines()),
            threaded=module_is_threaded(tree), parents=parents,
            module_locks={})
        self.modules.append(mod)
        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._add_class(stmt, mod)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{mod.stem}.{stmt.name}"
                fi = FuncInfo(qual, stmt, mod)
                self.functions.setdefault(stmt.name, fi)
                self.funcs_by_qual[qual] = fi
            elif isinstance(stmt, ast.Assign):
                # module-level lock: `_default_lock = threading.Lock()`
                for t in stmt.targets:
                    if (isinstance(t, ast.Name) and LOCKISH.search(t.id)
                            and isinstance(stmt.value, ast.Call)):
                        ctor = _dotted_last(stmt.value.func)
                        info = (f"{mod.stem}.{t.id}",
                                ctor not in _NONREENTRANT_CTORS)
                        mod.module_locks[t.id] = info
                        self.lock_reentrant[info[0]] = info[1]

    def _add_class(self, node: ast.ClassDef, mod: ModuleInfo) -> None:
        ci = ClassInfo(node.name, node, mod)
        # name-based resolution is first-wins; a SHADOWED duplicate
        # class still gets analyzed (its methods carry a module-
        # qualified qual so the tables never disagree), it just can't
        # be resolved INTO by name from other code
        primary = node.name not in self.classes
        if primary:
            self.classes[node.name] = ci
        # dataclass-field annotations type the attrs directly
        for stmt in node.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                ci.attr_types[stmt.target.id] = stmt.annotation
        for stmt in node.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = (f"{node.name}.{stmt.name}" if primary
                    else f"{mod.stem}:{node.name}.{stmt.name}")
            fi = FuncInfo(qual, stmt, mod, cls=ci)
            ci.methods[stmt.name] = fi
            self.funcs_by_qual[qual] = fi
            for n in ast.walk(stmt):
                # self.x = Ctor(...) / self.x: T = ... bindings + locks
                if isinstance(n, ast.AnnAssign):
                    for t in self_write_targets(n):
                        if isinstance(t, ast.Attribute):
                            ci.attr_types.setdefault(t.attr, n.annotation)
                if isinstance(n, (ast.Assign, ast.AnnAssign)):
                    val = n.value
                    for t in self_write_targets(n):
                        if not isinstance(t, ast.Attribute):
                            continue
                        if LOCKISH.search(t.attr) and isinstance(
                                val, ast.Call):
                            info = self._lock_info(node.name, t.attr, val)
                            ci.locks[t.attr] = info
                            self.lock_reentrant[info[0]] = info[1]
                        ctor = self._ctor_name(val)
                        if ctor is not None:
                            ci.attr_types.setdefault(t.attr, ctor)
                # Thread(target=self.X) → X is a pump/supervisor entry
                if (isinstance(n, ast.Call)
                        and _dotted_last(n.func) == "Thread"):
                    for kw in n.keywords:
                        if kw.arg == "target" and isinstance(
                                kw.value, ast.Attribute):
                            root = target_root(kw.value)
                            if (isinstance(root, ast.Name)
                                    and root.id == "self"):
                                ci.thread_targets.add(kw.value.attr)

    @staticmethod
    def _ctor_name(val) -> Optional[ast.Name]:
        """The Name of a top-level constructor call in an attr binding,
        looking through the ``x if x is not None else Ctor()`` default
        idiom — NOT a deep walk, so ``self.x = foo(Bar())`` can never
        bind x to Bar."""
        if isinstance(val, ast.Call) and isinstance(val.func, ast.Name):
            if val.func.id != "Thread":
                return val.func
            return None
        if isinstance(val, ast.IfExp):
            return (Program._ctor_name(val.body)
                    or Program._ctor_name(val.orelse))
        return None

    @staticmethod
    def _lock_info(cls_name: str, attr: str, ctor: ast.Call):
        """(key, reentrant) for a lock binding. The lockdep factories
        carry the runtime key as their first argument — prefer it, so
        the static graph speaks the witness's language."""
        key = f"{cls_name}.{attr}"
        if (ctor.args and isinstance(ctor.args[0], ast.Constant)
                and isinstance(ctor.args[0].value, str)):
            key = ctor.args[0].value
        name = _dotted_last(ctor.func)
        return (key, name not in _NONREENTRANT_CTORS)

    # -- type inference ------------------------------------------------------

    def _infer_locals(self, fi: FuncInfo) -> dict:
        """name → TypeRef for a function's parameters and simple local
        bindings (flow-insensitive, last binding wins — enough for the
        ``sched = self.scheduler`` aliases the stack writes)."""
        classes = self.classes
        out: dict = {}
        args = fi.node.args
        for a in (args.args + args.posonlyargs + args.kwonlyargs):
            t = _ann_to_type(a.annotation, classes)
            if t is not None:
                out[a.arg] = t
        # nested defs are a different frame: their bindings must not
        # overwrite this frame's aliases (_walk_skip_nested prunes the
        # whole nested body, not just the def node)
        for n in _walk_skip_nested(fi.node, skip_root=True):
            if isinstance(n, ast.AnnAssign) and isinstance(
                    n.target, ast.Name):
                t = _ann_to_type(n.annotation, classes)
                if t is not None:
                    out[n.target.id] = t
            elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                t = self._infer_expr(n.value, out, fi)
                if t is not None:
                    out[n.targets[0].id] = t
            elif isinstance(n, ast.For):
                it = self._infer_iter_elem(n.iter, out, fi)
                if isinstance(n.target, ast.Name) and it is not None:
                    out[n.target.id] = it
                elif (isinstance(n.target, ast.Tuple) and it is not None
                      and isinstance(it, tuple) and it[0] == "pair"
                      and len(n.target.elts) == 2
                      and isinstance(n.target.elts[1], ast.Name)):
                    out[n.target.elts[1].id] = it[1]
        return out

    def _infer_iter_elem(self, expr, locals_, fi):
        """Element type of an iterated expression: list[T] → T,
        dict.values() → V, dict.items() → ("pair", V)."""
        if isinstance(expr, ast.Call):
            fn = expr.func
            if isinstance(fn, ast.Name) and fn.id in ("list", "sorted") \
                    and expr.args:
                return self._infer_iter_elem(expr.args[0], locals_, fi)
            if isinstance(fn, ast.Attribute):
                base = self._infer_expr(fn.value, locals_, fi)
                if base is not None and base[0] == "dict":
                    if fn.attr == "values":
                        return base[1]
                    if fn.attr == "items":
                        return ("pair", base[1])
        t = self._infer_expr(expr, locals_, fi)
        if t is not None and t[0] == "list":
            return t[1]
        return None

    def _infer_expr(self, expr, locals_, fi: FuncInfo):
        classes = self.classes
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fi.cls is not None:
                return ("cls", fi.cls.name)
            if expr.id == "cls" and fi.cls is not None:
                return ("cls", fi.cls.name)
            return locals_.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._infer_expr(expr.value, locals_, fi)
            if base is not None and base[0] == "cls":
                ci = self._class_for(base[1], fi)
                if ci is not None and expr.attr in ci.attr_types:
                    ann = ci.attr_types[expr.attr]
                    if isinstance(ann, ast.Name) and ann.id in classes:
                        return ("cls", ann.id)
                    return _ann_to_type(ann, classes)
            return None
        if isinstance(expr, ast.Subscript):
            base = self._infer_expr(expr.value, locals_, fi)
            if base is not None and base[0] in ("list", "dict"):
                return base[1]
            return None
        if isinstance(expr, ast.Call):
            fn = expr.func
            if isinstance(fn, ast.Name) and fn.id in classes:
                return ("cls", fn.id)
            if isinstance(fn, ast.Attribute):
                if fn.attr in ("get", "pop"):
                    base = self._infer_expr(fn.value, locals_, fi)
                    if base is not None and base[0] == "dict":
                        return base[1]
                target = self._resolve_method(fn, locals_, fi)
                if target is not None:
                    return _ann_to_type(
                        getattr(target.node, "returns", None), classes)
            return None
        return None

    # -- call resolution -----------------------------------------------------

    def _class_for(self, name: str, fi: FuncInfo) -> Optional[ClassInfo]:
        """Resolve a class NAME, preferring the function's own class —
        so `self.` calls inside a shadowed duplicate class resolve to
        that class, not its primary namesake."""
        if fi.cls is not None and fi.cls.name == name:
            return fi.cls
        return self.classes.get(name)

    def _resolve_method(self, fn: ast.Attribute, locals_,
                        fi: FuncInfo) -> Optional[FuncInfo]:
        base = self._infer_expr(fn.value, locals_, fi)
        if base is None or base[0] != "cls":
            return None
        ci = self._class_for(base[1], fi)
        if ci is None:
            return None
        return ci.methods.get(fn.attr)

    def resolve_call(self, call: ast.Call, locals_,
                     fi: FuncInfo) -> list[FuncInfo]:
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.classes:
                init = self.classes[fn.id].methods.get("__init__")
                return [init] if init is not None else []
            f = self.functions.get(fn.id)
            return [f] if f is not None else []
        if isinstance(fn, ast.Attribute):
            target = self._resolve_method(fn, locals_, fi)
            return [target] if target is not None else []
        return []

    # -- per-function facts + fixpoints --------------------------------------

    def analyze(self) -> None:
        for fi in self.funcs_by_qual.values():
            self._collect_facts(fi)
        self._fix_acquires()
        self._fix_blocking()

    def _resolve_lock_item(self, expr, locals_, fi: FuncInfo):
        """(key, reentrant) for a with-item context expression that is a
        lock acquisition, (None, True) for an unresolvable lockish
        receiver (region still counts, no graph edge), or None when the
        with-item is not a lock at all."""
        if isinstance(expr, ast.Name):
            if LOCKISH.search(expr.id):
                return fi.module.module_locks.get(expr.id, (None, True))
            return None
        if not (isinstance(expr, ast.Attribute)
                and LOCKISH.search(expr.attr)):
            return None
        base = self._infer_expr(expr.value, locals_, fi)
        if base is not None and base[0] == "cls":
            ci = self.classes.get(base[1])
            if ci is not None and expr.attr in ci.locks:
                return ci.locks[expr.attr]
        # fall back to the attr name iff exactly one modeled class owns
        # a lock under it — `_cv` is unique, `_lock` is not
        owners = [ci.locks[expr.attr] for ci in self.classes.values()
                  if expr.attr in ci.locks]
        if len(owners) == 1:
            return owners[0]
        return (None, True)

    def _collect_facts(self, fi: FuncInfo) -> None:
        locals_ = self._infer_locals(fi)
        fi._locals = locals_
        for n in _walk_skip_nested(fi.node, skip_root=True):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    lk = self._resolve_lock_item(
                        item.context_expr, locals_, fi)
                    if lk is not None and lk[0] is not None:
                        fi.direct_acquires.add(lk[0])
            elif isinstance(n, ast.Call):
                reason = _blocking_reason(n)
                if reason is not None and _dotted_last(n.func) not in (
                        "wait", "wait_for"):
                    fi.direct_blocking.append((n.lineno, reason))
                for callee in self.resolve_call(n, locals_, fi):
                    fi.callees.add(callee.qual)

    def _fix_acquires(self) -> None:
        for fi in self.funcs_by_qual.values():
            fi.may_acquire = set(fi.direct_acquires)
        changed = True
        while changed:
            changed = False
            for fi in self.funcs_by_qual.values():
                for c in fi.callees:
                    extra = self.funcs_by_qual[c].may_acquire - \
                        fi.may_acquire
                    if extra:
                        fi.may_acquire |= extra
                        changed = True

    def _fix_blocking(self) -> None:
        for fi in self.funcs_by_qual.values():
            if fi.direct_blocking:
                fi.blocking_chain = fi.direct_blocking[0][1]
        changed = True
        while changed:
            changed = False
            for fi in self.funcs_by_qual.values():
                if fi.blocking_chain is not None:
                    continue
                for c in fi.callees:
                    chain = self.funcs_by_qual[c].blocking_chain
                    if chain is not None:
                        fi.blocking_chain = f"{c} → {chain}"
                        changed = True
                        break


def _walk_skip_nested(root: ast.AST, skip_root: bool = False):
    """ast.walk that does not descend into nested function/lambda/class
    bodies — what lexically executes in THIS frame."""
    stack = [root]
    first = True
    while stack:
        n = stack.pop()
        if not (first and skip_root):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            yield n
        first = False
        stack.extend(ast.iter_child_nodes(n))


# -- the audit engine ---------------------------------------------------------


class _Auditor:
    """Walks every threaded-module function with the lock-held region
    state, emitting acquisition-graph edges and the per-site findings."""

    def __init__(self, prog: Program):
        self.prog = prog
        self.raw: list[Finding] = []

    def run(self) -> None:
        for fi in self.prog.funcs_by_qual.values():
            if not fi.module.threaded:
                continue
            held: list = []
            if fi.caller_holds:
                locks = list(fi.cls.locks.values())
                if len(locks) == 1:
                    # *_locked: the caller holds THE class lock
                    held = [(locks[0][0], locks[0][1], fi.node.lineno)]
                else:
                    # multi-lock class: WHICH lock the caller holds is
                    # unknowable from the name — keep the lock-held
                    # region (blocking findings still fire) but
                    # fabricate no graph edges for it
                    held = [(None, True, fi.node.lineno)]
            self._walk(fi, list(fi.node.body), held)
        self._lock_order_findings()
        for mod in self.prog.modules:
            if mod.threaded:
                self._lock_leak(mod)
        self._shared_without_lock()

    # -- region walking ------------------------------------------------------

    def _walk(self, fi: FuncInfo, stmts: list, held: list) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, (ast.With, ast.AsyncWith)):
                new = []
                for item in s.items:
                    self._exprs(fi, item.context_expr, held)
                    lk = self.prog._resolve_lock_item(
                        item.context_expr, fi._locals, fi)
                    if lk is not None:
                        key, reentrant = lk
                        if key is not None:
                            self._acquire_edges(fi, key, reentrant,
                                                held, s.lineno)
                        new.append((key, reentrant, s.lineno))
                self._walk(fi, s.body, held + new)
                continue
            for _, value in ast.iter_fields(s):
                if isinstance(value, list) and value and isinstance(
                        value[0], ast.stmt):
                    self._walk(fi, value, held)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.excepthandler):
                            self._walk(fi, v.body, held)
                        elif isinstance(v, ast.AST):
                            self._exprs(fi, v, held)
                elif isinstance(value, ast.AST):
                    self._exprs(fi, value, held)

    def _acquire_edges(self, fi: FuncInfo, key: str, reentrant: bool,
                       held: list, line: int) -> None:
        for hkey, hre, _ in held:
            if hkey is None:
                continue
            if hkey == key:
                if not reentrant:
                    self.raw.append(Finding(
                        "lock-order", Severity.ERROR, fi.module.path,
                        line,
                        f"`{fi.qual}` acquires non-reentrant lock "
                        f"`{key}` while already holding it — a "
                        "self-deadlock (use an RLock, or restructure)"))
                continue  # re-entrant same-key: the sanctioned re-entry
            self.prog.edges.setdefault(
                (hkey, key),
                (fi.module.path, line, f"`{fi.qual}` acquires `{key}` "
                 f"while holding `{hkey}`"))

    def _exprs(self, fi: FuncInfo, node: ast.AST, held: list) -> None:
        if not held:
            return
        for n in _walk_skip_nested(node):
            if not isinstance(n, ast.Call):
                continue
            callees = self.prog.resolve_call(n, fi._locals, fi)
            # graph edges: everything the callee may transitively acquire
            for c in callees:
                for key in c.may_acquire:
                    self._acquire_edges(
                        fi, key,
                        self.prog.lock_reentrant.get(key, True),
                        held, n.lineno)
            # blocking findings: direct primitive, or a resolved callee
            # that (transitively) blocks — same-class *_locked callees
            # report inside their own body, not at every caller
            reason = _blocking_reason(n)
            name = _dotted_last(n.func)
            if name in ("wait", "wait_for", "notify", "notify_all"):
                continue
            hkeys = sorted({k for k, _, _ in held if k is not None}) or \
                ["<unresolved lock>"]
            if reason is not None:
                self.raw.append(Finding(
                    "blocking-under-lock", Severity.WARNING,
                    fi.module.path, n.lineno,
                    f"`{fi.qual}` holds {', '.join(hkeys)} while "
                    f"{reason} — every contending thread stalls behind "
                    "it (move the work outside the lock, or pragma the "
                    "reasoned exception)"))
                continue
            for c in callees:
                if c.blocking_chain is None:
                    continue
                if (fi.cls is not None and c.cls is fi.cls
                        and c.name.endswith("_locked")):
                    continue  # reported inside the helper's own region
                self.raw.append(Finding(
                    "blocking-under-lock", Severity.WARNING,
                    fi.module.path, n.lineno,
                    f"`{fi.qual}` holds {', '.join(hkeys)} while "
                    f"calling `{c.qual}`, which blocks "
                    f"({c.blocking_chain}) — every contending thread "
                    "stalls behind it (move the call outside the lock, "
                    "or pragma the reasoned exception)"))
                break

    # -- lock-order (cycles) -------------------------------------------------

    def _lock_order_findings(self) -> None:
        graph: dict[str, set] = {}
        for (a, b) in self.prog.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            cyc = " → ".join(sorted(scc)) + " → …"
            for (a, b), (path, line, desc) in sorted(
                    self.prog.edges.items(),
                    key=lambda kv: (kv[1][0], kv[1][1])):
                if a in scc and b in scc:
                    self.raw.append(Finding(
                        "lock-order", Severity.ERROR, path, line,
                        f"lock-order cycle [{cyc}]: {desc}, but another "
                        "path nests them the opposite way — a potential "
                        "deadlock; pick ONE global order"))

    # -- lock-leak -----------------------------------------------------------

    def _lock_leak(self, mod: ModuleInfo) -> None:
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "acquire"):
                continue
            recv = n.func.value
            recv_name = _dotted_last(recv)
            if recv_name is None or not LOCKISH.search(recv_name):
                continue
            if self._released_in_finally(mod, n, recv):
                continue
            self.raw.append(Finding(
                "lock-leak", Severity.ERROR, mod.path, n.lineno,
                f"bare `.acquire()` on `{ast.unparse(recv)}` without a "
                "`with` block or try/finally release — any exception "
                "before the release leaves the lock held forever"))

    @staticmethod
    def _released_in_finally(mod: ModuleInfo, call: ast.Call,
                             recv: ast.AST) -> bool:
        """True when the enclosing function has SOME ``try`` whose
        ``finally`` releases this receiver — covers both the
        acquire-inside-try and the idiomatic acquire-then-try shapes."""
        want = ast.unparse(recv)
        cur = mod.parents.get(call)
        fn = None
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = cur
                break
            cur = mod.parents.get(cur)
        scope = fn if fn is not None else mod.tree
        for t in ast.walk(scope):
            if not (isinstance(t, ast.Try) and t.finalbody):
                continue
            for n in ast.walk(ast.Module(body=t.finalbody,
                                         type_ignores=[])):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "release"
                        and ast.unparse(n.func.value) == want):
                    return True
        return False

    # -- thread-shared-without-lock ------------------------------------------

    def _shared_without_lock(self) -> None:
        for ci in self.prog.classes.values():
            if not ci.module.threaded or not ci.thread_targets:
                continue
            pump = self._role_closure(ci, ci.thread_targets)
            client = self._role_closure(
                ci, {m for m in ci.methods
                     if not m.startswith("_")} - pump)
            pump_only = pump - client
            client_only = client - pump
            # attr → {"w": [(method, line, locked)], "r": [...]}
            acc: dict[str, dict] = {}
            for mname, fi in ci.methods.items():
                locked_default = fi.caller_holds
                for n in _walk_skip_nested(fi.node, skip_root=True):
                    locked = locked_default or under_lock_with(
                        ci.module.parents, n, fi.node)
                    for t in self_write_targets(n):
                        if isinstance(t, ast.Attribute):
                            acc.setdefault(t.attr, {"w": [], "r": []})[
                                "w"].append((mname, n.lineno, locked))
                    if (isinstance(n, ast.Attribute)
                            and isinstance(n.ctx, ast.Load)
                            and isinstance(n.value, ast.Name)
                            and n.value.id == "self"):
                        acc.setdefault(n.attr, {"w": [], "r": []})[
                            "r"].append((mname, n.lineno, locked))
            for attr, sites in sorted(acc.items()):
                if attr in ci.locks or LOCKISH.search(attr):
                    continue
                writes = [s for s in sites["w"] if s[0] != "__init__"]
                if not writes:
                    continue  # construction happens-before thread start
                if any(locked for _, _, locked in
                       sites["w"] + sites["r"]):
                    continue  # some lock discipline exists → layer 1's
                w_roles = {self._role(m, pump_only, client_only)
                           for m, _, _ in writes}
                r_roles = {self._role(m, pump_only, client_only)
                           for m, _, _ in sites["r"]}
                if ("pump" in w_roles and "client" in r_roles) or \
                        ("client" in w_roles and "pump" in r_roles):
                    m, line, _ = writes[0]
                    self.raw.append(Finding(
                        "thread-shared-without-lock", Severity.WARNING,
                        ci.module.path, line,
                        f"`{ci.name}.{attr}` is written in "
                        f"`{m}` and read across the pump/client thread "
                        "boundary with no lock at ANY access site — a "
                        "torn read is a matter of scheduling (guard "
                        "both sides with the class lock)"))

    def _role_closure(self, ci: ClassInfo, seeds: set) -> set:
        out = set(seeds)
        changed = True
        while changed:
            changed = False
            for m in list(out):
                fi = ci.methods.get(m)
                if fi is None:
                    continue
                for c in fi.callees:
                    cfi = self.prog.funcs_by_qual.get(c)
                    if (cfi is not None and cfi.cls is ci
                            and cfi.name not in out):
                        out.add(cfi.name)
                        changed = True
        return out

    @staticmethod
    def _role(method: str, pump_only: set, client_only: set) -> str:
        if method in pump_only:
            return "pump"
        if method in client_only:
            return "client"
        return "shared"


def _sccs(graph: dict) -> list[set]:
    """Tarjan's strongly connected components, iterative."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list[set] = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)
    return out


# -- entry points -------------------------------------------------------------


def build_program(sources: Iterable[tuple[str, str]]) -> Program:
    """Parse ``(source, path)`` pairs into one analyzable Program."""
    prog = Program()
    for source, path in sources:
        prog.add_module(source, path)
    prog.analyze()
    return prog


def audit_program(prog: Program,
                  rules: Optional[Iterable[str]] = None) -> list[Finding]:
    """Run the four concurrency rules over a built program; findings
    carry pragma suppression exactly like the AST lint's."""
    auditor = _Auditor(prog)
    auditor.run()
    raw = auditor.raw
    if rules is not None:
        want = set(rules)
        raw = [f for f in raw if f.rule in want]
    raw.sort(key=lambda f: (f.path, f.line, f.rule))
    by_mod = {m.path: m for m in prog.modules}
    out: list[Finding] = []
    for path in sorted({f.path for f in raw}):
        mod = by_mod.get(path)
        group = [f for f in raw if f.path == path]
        if mod is None:
            out.extend(group)
        else:
            out.extend(apply_pragmas(group, mod.pragmas, mod.lines))
    return out


def lint_concurrency_source(source: str,
                            path: str = "mpi_model_tpu/fake.py",
                            rules: Optional[Iterable[str]] = None
                            ) -> list[Finding]:
    """Single-module fixture surface for the tests."""
    return audit_program(build_program([(source, path)]), rules)


def _package_sources(roots, rel_to=None) -> list[tuple[str, str]]:
    from .astlint import iter_py_files

    out = []
    for root in roots:
        for p in iter_py_files(root):
            parts = p.resolve().parts
            if "mpi_model_tpu" not in parts:
                continue
            name = p.name
            if name.startswith("test_"):
                continue
            shown = str(p.relative_to(rel_to)) if rel_to else str(p)
            try:
                source = p.read_text()
                ast.parse(source, filename=shown)
            except (OSError, UnicodeDecodeError, SyntaxError):
                continue  # astlint's parse-error rule owns broken files
            out.append((source, shown))
    return out


def _default_roots() -> list[Path]:
    pkg = Path(__file__).resolve().parent.parent
    return [pkg]


def run_concurrency_audit(roots=None, rules=None,
                          rel_to=None) -> list[Finding]:
    """The layer-3 entry point: model every package module (cross-module
    call resolution needs the callees too), audit the threaded ones."""
    roots = list(roots) if roots else _default_roots()
    sources = _package_sources(roots, rel_to)
    if not sources:
        return []
    return audit_program(build_program(sources), rules)


def static_lock_graph(roots=None) -> set:
    """The acquisition-order edge set ``{(held_key, acquired_key), …}``
    over the package — what ``resilience.lockdep``'s armed witness
    asserts runtime acquisition orders against. Same-key re-entries are
    not edges here, so the witness still flags real cross-instance
    same-key nesting."""
    roots = list(roots) if roots else _default_roots()
    prog = build_program(_package_sources(roots))
    auditor = _Auditor(prog)
    auditor.run()
    return set(prog.edges)
