"""Rule registry, findings, severities and pragma suppressions — the
shared spine of the static-analysis subsystem (ISSUE 4).

Every check in the subsystem — AST lint rules (``astlint``) and jaxpr
contract audits (``jaxpr_audit``) — registers here with a stable rule
id, a severity, and a one-line contract statement. The registry is what
makes the analyzer extensible: a new invariant is a ``@rule(...)``
function, and the CLI, the pragma machinery, the repo-gate test and the
docs rule table all pick it up without further wiring.

Suppressions are explicit and carry their justification in the source::

    except Exception as e:  # analysis: ignore[broad-except] — supervisor boundary

A pragma with no reason still suppresses its target (so a stale finding
cannot block an emergency fix) but raises a ``bare-pragma`` finding of
its own: the acceptance bar is *zero unsuppressed findings AND every
suppression carries a reason*.
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Callable, Optional


class Severity(enum.Enum):
    """``ERROR`` gates every run; ``WARNING`` gates ``--strict`` runs
    (the tier-1 repo gate runs strict, so both block a PR — the split
    exists so ad-hoc non-strict runs surface the hard invariants
    first)."""

    ERROR = "error"
    WARNING = "warning"

    def __str__(self) -> str:  # "error", not "Severity.ERROR", in reports
        return self.value


#: which files a rule runs over: the whole tree, only package sources,
#: or only test modules (``tests/test_*.py``)
SCOPE_ALL = "all"
SCOPE_PACKAGE = "package"
SCOPE_TESTS = "tests"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One concrete violation, anchored to a file line (AST rules) or a
    pseudo-path like ``jaxpr:<impl>`` (contract audits)."""

    rule: str
    severity: Severity
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    def format(self) -> str:
        sup = "  [suppressed]" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.severity}"
                f" [{self.rule}] {self.message}{sup}")

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": str(self.severity),
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }


@dataclasses.dataclass(frozen=True)
class Rule:
    """A registered check. ``check`` receives a ``ModuleCtx`` (see
    ``astlint``) and yields raw findings; the engine applies pragma
    suppression afterwards, so rules never reason about pragmas."""

    name: str
    severity: Severity
    doc: str
    check: Callable
    scope: str = SCOPE_ALL
    #: one actionable sentence — what to change (or which pragma to
    #: write) when the rule fires; rides every ``--json`` finding as
    #: ``fix_hint`` so a CI consumer can surface the remedy inline
    fix_hint: str = ""


#: rule-id → Rule, in registration order (reports keep this order)
RULES: dict[str, Rule] = {}

#: registry scope tag for findings the ENGINE synthesizes (never run as
#: checks themselves, but registered so --list-rules/--rule know them)
SCOPE_ENGINE = "engine"

RULES["bare-pragma"] = Rule(
    "bare-pragma", Severity.ERROR,
    "a suppression pragma with no reason (synthesized by the engine "
    "whenever a reasonless pragma actually fires)",
    check=lambda ctx: (), scope=SCOPE_ENGINE,
    fix_hint="append the justification: `# analysis: ignore[rule] — "
             "why this is safe`")
RULES["parse-error"] = Rule(
    "parse-error", Severity.ERROR,
    "a scanned file failed to parse or read (synthesized by the "
    "engine; a broken file cannot be linted and must not pass silently)",
    check=lambda ctx: (), scope=SCOPE_ENGINE,
    fix_hint="fix the syntax error (or delete the file) — a broken "
             "module can neither run nor be audited")


def rule(name: str, severity: Severity, doc: str,
         scope: str = SCOPE_ALL, fix_hint: str = "") -> Callable:
    """Register an AST rule::

        @rule("broad-except", Severity.ERROR, "…contract…")
        def check_broad_except(ctx): ...
    """
    if scope not in (SCOPE_ALL, SCOPE_PACKAGE, SCOPE_TESTS):
        raise ValueError(f"unknown rule scope {scope!r}")

    def deco(fn: Callable) -> Callable:
        if name in RULES:
            raise ValueError(f"duplicate rule id {name!r}")
        RULES[name] = Rule(name, severity, doc, fn, scope,
                           fix_hint=fix_hint)
        return fn

    return deco


# -- pragma suppressions ------------------------------------------------------

#: ``# analysis: ignore[rule-a, rule-b] — reason`` (reason separator may
#: be an em/en dash, a hyphen run, or a colon; the reason is REQUIRED
#: for a clean strict run — see ``bare-pragma``)
PRAGMA_RE = re.compile(
    r"#\s*analysis:\s*ignore\[([A-Za-z0-9_\-, ]+)\]"
    r"(?:\s*(?:[—–]|--+|-|:)\s*(\S.*))?")


@dataclasses.dataclass(frozen=True)
class Pragma:
    line: int
    rules: tuple[str, ...]
    reason: Optional[str]
    own_line: bool  # a comment-only line also covers the NEXT code line


def _comment_lines(lines: list[str]) -> Optional[dict[int, int]]:
    """1-indexed line → column of the REAL comment token on it, via the
    tokenizer — so pragma text inside a string/docstring (e.g. pasted
    documentation of the pragma syntax) can never suppress a finding.
    None when tokenization fails (caller falls back to the line scan)."""
    import io
    import tokenize
    out: dict[int, int] = {}
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO("\n".join(lines)).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.start[1]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    return out


def collect_pragmas(lines: list[str]) -> dict[int, Pragma]:
    """1-indexed line → Pragma for every suppression comment in the
    module source."""
    comments = _comment_lines(lines)
    out: dict[int, Pragma] = {}
    for i, text in enumerate(lines, start=1):
        if comments is None:  # tokenizer fallback: line heuristic
            comment, own = text, text.lstrip().startswith("#")
        else:
            col = comments.get(i)
            if col is None:
                continue  # no real comment token on this line
            comment, own = text[col:], text[:col].strip() == ""
        m = PRAGMA_RE.search(comment)
        if not m:
            continue
        names = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = m.group(2).strip() if m.group(2) else None
        out[i] = Pragma(i, names, reason, own)
    return out


def pragma_for(pragmas: dict[int, Pragma], rule_name: str, line: int,
               lines: Optional[list[str]] = None) -> Optional[Pragma]:
    """The pragma suppressing ``rule_name`` at ``line``: a trailing
    pragma on the line itself, or a comment-only pragma in the
    contiguous comment block directly above it."""
    p = pragmas.get(line)
    if p is not None and rule_name in p.rules:
        return p
    # scan upward through the comment block above the construct
    cand = line - 1
    while cand >= 1:
        text = lines[cand - 1] if lines and cand <= len(lines) else ""
        if not text.lstrip().startswith("#"):
            break
        p = pragmas.get(cand)
        if p is not None and p.own_line and rule_name in p.rules:
            return p
        cand -= 1
    return None


def apply_pragmas(findings: list[Finding], pragmas: dict[int, Pragma],
                  lines: Optional[list[str]] = None) -> list[Finding]:
    """Mark suppressed findings and append a ``bare-pragma`` finding for
    every suppression that actually fired without carrying a reason."""
    out: list[Finding] = []
    bare_seen: set[int] = set()
    for f in findings:
        p = pragma_for(pragmas, f.rule, f.line, lines)
        if p is None:
            out.append(f)
            continue
        out.append(dataclasses.replace(
            f, suppressed=True, suppress_reason=p.reason))
        if p.reason is None and p.line not in bare_seen:
            bare_seen.add(p.line)
            out.append(Finding(
                "bare-pragma", Severity.ERROR, f.path, p.line,
                f"suppression of [{', '.join(p.rules)}] carries no reason "
                "— write `# analysis: ignore[rule] — why this is safe`"))
    return out
