"""Static analysis for the step stack (ISSUE 4): an AST lint layer and
a jaxpr contract auditor over a shared rule registry.

Quick use::

    python -m mpi_model_tpu.analysis --strict        # the PR gate
    python -m mpi_model_tpu.analysis --json          # machine-readable
    mpi-model-analyze --strict                       # console script

Library surface: ``run_astlint`` / ``lint_source`` (layer 1, pure AST,
no jax import), ``run_jaxpr_audit`` (layer 2, abstract traces of the
four registered step impls), ``run_concurrency_audit`` (layer 3,
whole-program lock/phase audit), ``run_protocol_audit`` /
``lint_protocol_source`` (layer 4, journal/wire vocabulary conformance
against the declared lifecycle machines),
``RULES``/``Severity``/``Finding`` from the registry. Suppress a
finding in source with ``# analysis: ignore[rule-id] — reason``.
"""

from .registry import (RULES, Finding, Pragma, Rule,  # noqa: F401
                       Severity, collect_pragmas, rule)
from .astlint import (audit_test_module, iter_py_files,  # noqa: F401
                      lint_file, lint_source, parse_module, run_astlint)
from .concurrency import (SCOPE_CONCURRENCY,  # noqa: F401
                          lint_concurrency_source, run_concurrency_audit,
                          static_lock_graph)
from .protocol import (SCOPE_PROTOCOL,  # noqa: F401
                       lint_protocol_source, run_protocol_audit)

__all__ = [
    "RULES", "Finding", "Pragma", "Rule", "Severity", "collect_pragmas",
    "rule", "audit_test_module", "iter_py_files", "lint_file",
    "lint_source", "parse_module", "run_astlint", "run_jaxpr_audit",
    "SCOPE_CONCURRENCY", "lint_concurrency_source",
    "run_concurrency_audit", "static_lock_graph",
    "SCOPE_PROTOCOL", "lint_protocol_source", "run_protocol_audit",
    "main",
]


def run_jaxpr_audit(impls=None):
    """Layer 2 entry point (imports jax lazily — layer 1 stays
    millisecond-fast without it)."""
    from .jaxpr_audit import run_jaxpr_audit as _run
    return _run(impls)


def main(argv=None) -> int:
    from .__main__ import main as _main
    return _main(argv)
