"""AST lint layer (ISSUE 4 layer 1): JAX-specific structural rules over
the repo's Python sources.

The engine parses each module once into a ``ModuleCtx`` (tree + parent
links + pragma map) and runs every registered rule whose scope matches.
Rules are pure AST walks — no imports of the analyzed code, so linting
never executes repo code and runs in milliseconds.

What "traced" means here
------------------------
Several rules only fire *inside traced scopes* — functions whose bodies
become jaxprs rather than running per call. Statically we treat a
function as traced when it

- is decorated with a trace entry point (``jit``/``vmap``/``pmap``/
  ``shard_map``/``remat``/``checkpoint``, bare or via ``partial``), or
- is passed by name (or as an inline ``lambda``) to a trace entry call:
  ``jit``/``vmap``/``pmap``/``shard_map`` or a ``lax`` combinator
  (``scan``/``while_loop``/``fori_loop``/``cond``/``switch``/``map``), or
- is a nested ``def`` inside a *step builder* — a function named
  ``make_*``/``build_*``/``_build*`` (the repo's convention for
  functions that RETURN the pure step: ``Model.make_step``'s ``single``,
  ``ensemble.batch.make_scenario_step``'s ``single``, the executors'
  ``_build_*`` runner bodies). The builder body itself runs eagerly at
  build time and is NOT traced — probing compiles with
  ``block_until_ready`` there is exactly right.

This is a heuristic with an escape hatch (the pragma), not a proof; the
jaxpr audit (layer 2) is the ground-truth check for what actually ends
up in the traced hot path.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable, Optional

from .registry import (RULES, SCOPE_ALL, SCOPE_PACKAGE, SCOPE_TESTS,
                       Finding, Severity, apply_pragmas, collect_pragmas,
                       rule)

# -- module context -----------------------------------------------------------


@dataclasses.dataclass
class ModuleCtx:
    """One parsed module, shared by every rule."""

    path: str
    tree: ast.Module
    lines: list[str]
    pragmas: dict
    is_test: bool
    #: resolved absolute path components, for package-scope matching
    #: (a bare relative path like ``ops/stencil.py`` passed from inside
    #: the package directory must still count as package code)
    resolved_parts: tuple
    #: node → enclosing node, for upward walks
    parents: dict[ast.AST, ast.AST]
    #: FunctionDef/AsyncFunctionDef/Lambda nodes considered traced
    traced_scopes: set[ast.AST]

    def enclosing_functions(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                yield cur
            cur = self.parents.get(cur)

    def in_traced_scope(self, node: ast.AST) -> bool:
        return any(fn in self.traced_scopes
                   for fn in self.enclosing_functions(node))


#: decorators / call targets that enter a trace
TRACE_ENTRY_NAMES = {"jit", "vmap", "pmap", "shard_map", "remat",
                     "checkpoint"}
#: lax combinators whose function arguments are traced
TRACE_COMBINATORS = {"scan", "while_loop", "fori_loop", "cond", "switch",
                     "map"} | TRACE_ENTRY_NAMES
#: step-builder naming convention: nested defs inside these are traced
BUILDER_PREFIXES = ("make_", "build_", "_build")


def _dotted_last(node: ast.AST) -> Optional[str]:
    """Last identifier of a Name/Attribute chain (``jax.lax.scan`` →
    ``scan``), else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _decorated_as_trace(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        for n in ast.walk(dec):
            if _dotted_last(n) in TRACE_ENTRY_NAMES:
                return True
    return False


def _find_traced_scopes(tree: ast.Module,
                        parents: dict) -> set[ast.AST]:
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda))]
    by_name: dict[str, list[ast.AST]] = {}
    for f in funcs:
        if not isinstance(f, ast.Lambda):
            by_name.setdefault(f.name, []).append(f)

    traced: set[ast.AST] = set()
    for f in funcs:
        if _decorated_as_trace(f):
            traced.add(f)
            continue
        # nested def inside a step builder (but not the builder itself)
        cur = parents.get(f)
        while cur is not None:
            if (isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and cur.name.startswith(BUILDER_PREFIXES)):
                traced.add(f)
                break
            cur = parents.get(cur)

    # functions handed to a trace-entry call by name or inline lambda
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted_last(node.func) not in TRACE_COMBINATORS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                traced.add(arg)
            elif isinstance(arg, ast.Name):
                traced.update(by_name.get(arg.id, []))
    return traced


def parse_module(source: str, path: str = "<string>") -> ModuleCtx:
    tree = ast.parse(source, filename=path)
    parents: dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    name = Path(path).name
    try:
        resolved = Path(path).resolve().parts
    except OSError:
        resolved = Path(path).parts
    return ModuleCtx(
        path=path,
        tree=tree,
        lines=source.splitlines(),
        pragmas=collect_pragmas(source.splitlines()),
        is_test=name.startswith("test_") and name.endswith(".py"),
        resolved_parts=resolved,
        parents=parents,
        traced_scopes=_find_traced_scopes(tree, parents),
    )


# -- rules --------------------------------------------------------------------

@rule("broad-except", Severity.ERROR,
      "`except Exception`/bare `except` hides tracer leaks and dtype "
      "bugs; only pragma'd supervisor boundaries may catch broadly "
      "(cleanup handlers ending in a bare `raise` are exempt)",
      fix_hint="narrow to the exceptions the handler can actually "
      "recover from, or pragma the supervisor boundary with its "
      "reason")
def check_broad_except(ctx: ModuleCtx):
    def is_broad(t) -> bool:
        if t is None:
            return True  # bare except:
        if isinstance(t, ast.Tuple):
            return any(is_broad(e) for e in t.elts)
        return _dotted_last(t) in ("Exception", "BaseException")

    def reraises(handler: ast.ExceptHandler) -> bool:
        # `except BaseException: <cleanup>; raise` supervises nothing —
        # it is the atomic-write/unwind idiom, and exempt
        last = handler.body[-1]
        return isinstance(last, ast.Raise) and last.exc is None

    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.ExceptHandler) and is_broad(node.type)
                and not reraises(node)):
            yield Finding(
                "broad-except", Severity.ERROR, ctx.path, node.lineno,
                "broad `except` — narrow to the exceptions this boundary "
                "actually supervises, or pragma a genuine supervisor "
                "boundary with its reason")


@rule("mutable-default", Severity.ERROR,
      "mutable default arguments ([] / {} / set()) alias across calls",
      fix_hint="default to None and create the container inside the "
      "function body")
def check_mutable_default(ctx: ModuleCtx):
    def is_mutable(d) -> bool:
        if isinstance(d, (ast.List, ast.Dict, ast.Set)):
            return True
        return (isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set") and not d.args)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        a = node.args
        for d in list(a.defaults) + [d for d in a.kw_defaults if d]:
            if is_mutable(d):
                yield Finding(
                    "mutable-default", Severity.ERROR, ctx.path, d.lineno,
                    "mutable default argument — use None and construct "
                    "inside the function")


#: host-sync call shapes: a name/attr called as these forces device→host
HOST_SYNC_CALLEES = {"block_until_ready", "item"}
#: module aliases whose ``.asarray`` materializes on host (jnp.asarray
#: stays on device and is fine)
NUMPY_ALIASES = {"np", "numpy", "onp"}


@rule("host-sync", Severity.ERROR,
      "host syncs (`block_until_ready`, `np.asarray`, `.item()`) inside "
      "a traced/step-builder function stall the device pipeline or leak "
      "tracers at trace time",
      fix_hint="return the traced value and sync at the caller (outside "
      "jit), or move the call out of the traced scope")
def check_host_sync(ctx: ModuleCtx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not ctx.in_traced_scope(node):
            continue
        callee = _dotted_last(node.func)
        msg = None
        if callee in HOST_SYNC_CALLEES:
            msg = f"`{callee}` call inside a traced scope"
        elif (callee == "asarray" and isinstance(node.func, ast.Attribute)
              and isinstance(node.func.value, ast.Name)
              and node.func.value.id in NUMPY_ALIASES):
            msg = ("`np.asarray` inside a traced scope materializes the "
                   "operand on host (use `jnp.asarray`)")
        if msg:
            yield Finding(
                "host-sync", Severity.ERROR, ctx.path, node.lineno,
                msg + " — this either fails on tracers or silently "
                "serializes the hot path")


#: jnp constructors where an un-dtyped float literal inherits the
#: AMBIENT x64 config instead of the space dtype
DTYPE_DRIFT_CTORS = {"array", "asarray", "full", "linspace", "arange"}


def _has_float_literal(node: ast.AST) -> Optional[ast.Constant]:
    """First float literal in an arg expression, not descending into
    nested calls (their args are that call's concern)."""
    if isinstance(node, ast.Constant):
        return node if isinstance(node.value, float) else None
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            hit = _has_float_literal(e)
            if hit:
                return hit
        return None
    if isinstance(node, (ast.UnaryOp, ast.BinOp)):
        for child in ast.iter_child_nodes(node):
            hit = _has_float_literal(child)
            if hit:
                return hit
    return None


@rule("dtype-drift", Severity.WARNING,
      "a bare float literal in a jnp constructor takes the ambient-x64 "
      "default dtype, not the space dtype — pin `dtype=`",
      scope=SCOPE_PACKAGE,
      fix_hint="pass dtype= explicitly (the space dtype, usually from "
      "the config)")
def check_dtype_drift(ctx: ModuleCtx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in DTYPE_DRIFT_CTORS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "jnp"):
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        lit = None
        for a in list(node.args) + [kw.value for kw in node.keywords
                                    if kw.arg != "dtype"]:
            lit = _has_float_literal(a)
            if lit:
                break
        if lit is not None:
            yield Finding(
                "dtype-drift", Severity.WARNING, ctx.path, node.lineno,
                f"`jnp.{node.func.attr}` with float literal {lit.value!r} "
                "and no dtype= — under x64 this becomes f64 and silently "
                "promotes the expression (pin the space/operand dtype)")


#: test-expression shapes that are STATIC even when they touch a traced
#: parameter: structure, dtype/shape metadata, identity-vs-None
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "impl", "substeps",
                 "keys", "values", "items"}
#: calls whose result is static even over a traced argument. NOTE:
#: bool() is deliberately NOT here — bool(tracer) is exactly the
#: ConcretizationTypeError this rule exists to catch
_STATIC_CALLS = {"isinstance", "len", "hasattr", "getattr",
                 "issubdtype", "tuple", "sorted", "list", "set"}


def _branch_on_traced(test: ast.AST, params: set[str]) -> Optional[str]:
    """Name of the traced parameter the test genuinely branches on, or
    None when every reference is structural (is-None, isinstance, len,
    .shape/.dtype metadata, dict membership)."""
    static_roots: set[ast.AST] = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in n.ops):
            static_roots.update(ast.walk(n))
        elif (isinstance(n, ast.Call)
              and _dotted_last(n.func) in _STATIC_CALLS):
            static_roots.update(ast.walk(n))
        elif isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            static_roots.update(ast.walk(n))
    for n in ast.walk(test):
        if (isinstance(n, ast.Name) and n.id in params
                and n not in static_roots):
            return n.id
    return None


@rule("traced-branch", Severity.WARNING,
      "a Python `if`/`while` on a traced value raises "
      "ConcretizationTypeError at trace time (or silently bakes one "
      "branch); use lax.cond/jnp.where",
      fix_hint="rewrite the branch as lax.cond/lax.while_loop or a "
      "jnp.where select")
def check_traced_branch(ctx: ModuleCtx):
    for fn in ctx.traced_scopes:
        if isinstance(fn, ast.Lambda):
            continue  # lambdas cannot contain statements
        params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                                  + fn.args.kwonlyargs)}
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            hit = _branch_on_traced(node.test, params)
            if hit:
                yield Finding(
                    "traced-branch", Severity.WARNING, ctx.path,
                    node.lineno,
                    f"Python branch on traced parameter `{hit}` inside a "
                    "traced scope — use lax.cond/lax.select/jnp.where "
                    "(or branch on static metadata only)")


# -- heavy-test rule (the marker audit, generalized) --------------------------
# Absorbed from tests/test_marker_audit.py (ISSUE 2/3 satellites): the
# tier-1 870 s wall stays thin only if every test that spawns a
# subprocess, runs a multihost/multichip dryrun, or steps a >= 2048²
# grid is marked slow. ``tests/test_marker_audit.py`` now fronts this
# rule and keeps its original self-tests.

#: referencing any of these names marks a function heavy
HEAVY_NAMES = {"subprocess", "Popen", "pexpect"}
#: calling anything whose name contains one of these marks it heavy
HEAVY_NAME_PARTS = ("dryrun",)
#: a call carrying >= 2 literal ints >= this constructs a >= GRID²
#: grid: ~17M+ cells per array on the CPU rig — inner-loop poison
GRID_LIMIT = 2048


def _marks_slow(node: ast.AST) -> bool:
    """True when the expression contains a ``...slow`` attribute (any
    spelling of pytest.mark.slow, including parametrized/called forms
    and marker lists)."""
    return any(isinstance(n, ast.Attribute) and n.attr == "slow"
               for n in ast.walk(node))


def _const_env(tree: ast.AST) -> dict[str, int]:
    """name → int for simple ``g = 4096``-style assignments anywhere in
    the module (module or function scope) — enough constant propagation
    to catch the idiomatic ``g = 4096; create(g, g, ...)`` shape. A
    name assigned two different ints keeps the LARGER (conservative:
    the audit must not under-flag)."""
    env: dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                env[t.id] = max(env.get(t.id, 0), node.value.value)
    return env


def _call_int_literals(call: ast.Call, env: dict[str, int]) -> list[int]:
    """Integer literals carried by a call's args/keywords, tuples
    flattened, simple names resolved through ``env``."""
    out: list[int] = []

    def visit(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            out.append(node.value)
        elif isinstance(node, ast.Name) and node.id in env:
            out.append(env[node.id])
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                visit(e)

    for a in call.args:
        visit(a)
    for kw in call.keywords:
        visit(kw.value)
    return out


def _builds_big_grid(fn: ast.AST, env: dict[str, int]) -> bool:
    """True when some call in ``fn`` carries >= 2 int literals >=
    GRID_LIMIT — the >= 2048² grid-construction shape (one big literal
    alone — a 1024x2048 strip, a byte count — does not trip it)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            big = [v for v in _call_int_literals(node, env)
                   if v >= GRID_LIMIT]
            if len(big) >= 2:
                return True
    return False


def _directly_heavy(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is None:
            continue
        if name in HEAVY_NAMES:
            return True
        if any(part in name for part in HEAVY_NAME_PARTS):
            return True
    return False


def _called_names(fn: ast.AST) -> set[str]:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                out.add(f.attr)
    return out


def _unmarked_heavy_tests(ctx: ModuleCtx) -> list[ast.AST]:
    tree = ctx.tree
    module_slow = any(
        isinstance(stmt, ast.Assign)
        and any(isinstance(t, ast.Name) and t.id == "pytestmark"
                for t in stmt.targets)
        and _marks_slow(stmt.value)
        for stmt in tree.body)
    if module_slow:
        return []

    # module-local function defs (incl. methods), for one-level-deep
    # transitive heaviness through helpers
    funcs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)

    env = _const_env(tree)
    heavy = {name for name, fn in funcs.items()
             if _directly_heavy(fn) or _builds_big_grid(fn, env)}
    changed = True
    while changed:  # propagate through helper calls to a fixpoint
        changed = False
        for name, fn in funcs.items():
            if name in heavy:
                continue
            if _called_names(fn) & heavy:
                heavy.add(name)
                changed = True

    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith("test_"):
            continue
        if node.name not in heavy:
            continue
        if any(_marks_slow(d) for d in node.decorator_list):
            continue
        out.append(node)
    return out


@rule("heavy-test", Severity.ERROR,
      "tests that spawn subprocesses, run dryrun rigs, or build >= "
      "2048² grids must carry @pytest.mark.slow (tier-1 870 s wall)",
      scope=SCOPE_TESTS,
      fix_hint="decorate the test with @pytest.mark.slow (or shrink the "
      "grid below 2048²)")
def check_heavy_test(ctx: ModuleCtx):
    for node in _unmarked_heavy_tests(ctx):
        yield Finding(
            "heavy-test", Severity.ERROR, ctx.path, node.lineno,
            f"`{node.name}` spawns subprocesses, runs a multihost/"
            "multichip dryrun, or constructs a >= 2048² grid but is not "
            "marked slow — it would fatten the tier-1 inner loop (mark "
            "it @pytest.mark.slow or set a module pytestmark)")


# -- naked-save rule (ISSUE 5 satellite) --------------------------------------
# Checkpoint durability now includes INTEGRITY: the manager's save path
# writes per-array checksums and resume falls back to the newest step
# that verifies. That guarantee holds only if every checkpoint write in
# the package flows through the io writers / the supervisor-and-flush
# boundaries — a module calling the raw writers (or a manager's .save)
# from arbitrary code can reintroduce unverifiable checkpoints or break
# the async staged-commit protocol.

#: the raw checkpoint writers — callable only from the io layer itself
#: (ISSUE 7 extends the set with the delta chain's raw record writer:
#: a record written outside the chain's save path never reaches the
#: chain manifest, so it would be an uncommitted — hence unrestorable —
#: husk at best and a chain-corrupting overwrite at worst)
CHECKPOINT_WRITERS = {"save_checkpoint", "save_checkpoint_sharded",
                      "stage_checkpoint_sharded", "write_chain_record"}
#: receiver names that read as a CheckpointManager, a DeltaChain or the
#: scenario-tiering vault (`mgr.save(...)`, `chain.save(...)`,
#: `vault.save(...)` / `tiering.hibernate` targets — ISSUE 14 extends
#: the one-format discipline to hibernation writes: scenario state may
#: only reach disk through the io/delta.py chain writers driven from
#: the ensemble/tiering.py boundary)
_MANAGERISH = None  # compiled lazily; module-level re import kept local


def _managerish():
    global _MANAGERISH
    if _MANAGERISH is None:
        import re

        _MANAGERISH = re.compile(r"(manager|mgr|ckpt|chain|vault|tiering)",
                                 re.IGNORECASE)
    return _MANAGERISH


def _save_boundary_module(ctx: ModuleCtx) -> bool:
    """io/checkpoint.py, io/sharded.py, io/delta.py, the resilience
    package and ensemble/tiering.py (ISSUE 14: the hibernate/wake
    paging layer drives the delta-chain writers — the ONE sanctioned
    place a scenario's state is written outside a checkpoint) are the
    supervisor/flush boundaries the rule exempts."""
    parts = ctx.resolved_parts
    if "resilience" in parts:
        return True
    if (len(parts) >= 2 and parts[-2] == "ensemble"
            and parts[-1] == "tiering.py"):
        return True
    return (len(parts) >= 2 and parts[-2] == "io"
            and parts[-1] in ("checkpoint.py", "sharded.py", "delta.py"))


@rule("naked-save", Severity.ERROR,
      "checkpoint writes outside the supervisor/flush boundaries must "
      "go through CheckpointManager's checksum-writing path — raw "
      "writer calls can reintroduce unverifiable checkpoints",
      scope=SCOPE_PACKAGE,
      fix_hint="route the write through CheckpointManager.save so the "
      "checksum sidecar is written atomically")
def check_naked_save(ctx: ModuleCtx):
    if _save_boundary_module(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = _dotted_last(fn)
        if name in CHECKPOINT_WRITERS:
            yield Finding(
                "naked-save", Severity.ERROR, ctx.path, node.lineno,
                f"direct `{name}` call outside the io/resilience "
                "boundaries — route the write through "
                "CheckpointManager.save (the checksum-writing, "
                "prune-aware path), or pragma a genuine low-level "
                "boundary with its reason")
        elif (name == "save" and isinstance(fn, ast.Attribute)
              and (recv := _dotted_last(fn.value)) is not None
              and _managerish().search(recv)):
            # _dotted_last resolves chained receivers too (self.mgr.save,
            # cfg.manager.save) — a stored manager must not bypass the rule
            yield Finding(
                "naked-save", Severity.ERROR, ctx.path, node.lineno,
                f"`{recv}.save(...)` outside the supervisor/"
                "flush boundaries — checkpoint cadence belongs to "
                "resilience.supervised_run / io.run_checkpointed (they "
                "carry the conservation baseline and commit staged "
                "async writes); pragma a genuine boundary with its "
                "reason")


# -- raw-transport rule (ISSUE 13 satellite) ----------------------------------
# The multi-process fleet's correctness rests on every byte that
# crosses a process boundary flowing through the ensemble.wire codec:
# CRC-framed, deadline-bounded, typed errors, chaos-seamed. A module
# opening its own socket or spawning its own subprocess bypasses all
# four — an unframed byte stream can hang the supervisor, and an
# unmanaged child is a process the fleet can neither heartbeat nor
# fence. This mirrors the naked-save boundary pattern: the codec
# modules are the sanctioned boundary, everything else pragmas a
# genuine low-level rig with its reason.

#: constructor/spawn entry points of the two transport modules
_SUBPROCESS_CALLS = {"Popen", "run", "call", "check_call", "check_output"}
_SOCKET_CALLS = {"socket", "socketpair", "create_connection",
                 "create_server"}
#: transport-AUTH primitives (ISSUE 20): the TCP members' shared-secret
#: HMAC challenge–response and its secret minting live in the wire
#: handshake — a module reaching for ``hmac``/``secrets`` elsewhere is
#: hand-rolling a second, unaudited authentication path beside it
_HMAC_CALLS = {"new", "compare_digest", "digest"}
_SECRETS_CALLS = {"token_hex", "token_bytes", "token_urlsafe"}
#: bare names that unambiguously mean a transport was opened even
#: through a from-import ("run"/"call"/"socket"/"new" alone are too
#: generic)
_TRANSPORT_BARE = {"Popen", "socketpair", "create_connection",
                   "create_server", "compare_digest", "token_hex",
                   "token_bytes", "token_urlsafe"}


def _transport_boundary_module(ctx: ModuleCtx) -> bool:
    """ensemble/wire.py and ensemble/member_proc.py are THE transport
    boundary: the codec and the member spawn/serve machinery."""
    parts = ctx.resolved_parts
    return (len(parts) >= 2 and parts[-2] == "ensemble"
            and parts[-1] in ("wire.py", "member_proc.py"))


@rule("raw-transport", Severity.ERROR,
      "raw socket/subprocess/transport-auth use outside the ensemble "
      "wire boundary — bytes crossing a process edge must ride the "
      "CRC-framed, deadline-bounded codec, and its HMAC handshake is "
      "the ONE auth path (ensemble/wire.py, member_proc.py)",
      scope=SCOPE_PACKAGE,
      fix_hint="send the bytes through the wire codec (ensemble/wire.py) "
      "or add the module to the transport boundary with a "
      "reasoned pragma")
def check_raw_transport(ctx: ModuleCtx):
    if _transport_boundary_module(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit = None
        if isinstance(fn, ast.Attribute):
            recv = _dotted_last(fn.value)
            if recv == "subprocess" and fn.attr in _SUBPROCESS_CALLS:
                hit = f"subprocess.{fn.attr}"
            elif recv == "socket" and fn.attr in _SOCKET_CALLS:
                hit = f"socket.{fn.attr}"
            elif recv == "hmac" and fn.attr in _HMAC_CALLS:
                hit = f"hmac.{fn.attr}"
            elif recv == "secrets" and fn.attr in _SECRETS_CALLS:
                hit = f"secrets.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id in _TRANSPORT_BARE:
            hit = fn.id
        if hit is not None:
            yield Finding(
                "raw-transport", Severity.ERROR, ctx.path, node.lineno,
                f"raw `{hit}(...)` outside the wire boundary — route "
                "process/socket traffic through ensemble.wire/"
                "member_proc (CRC framing, RPC deadlines, chaos "
                "seams), or pragma a genuine low-level rig with its "
                "reason")


# -- unguarded-shared-mutation rule (ISSUE 9 satellite) -----------------------
# The ensemble scheduler/service now run submit/poll on client threads
# while a pump thread dispatches: every class that owns a dispatch lock
# must route its shared-state writes through it. This rule is the
# structural enforcement: in any threaded module (imports ``threading``
# or the ``resilience.lockdep`` lock factories — one definition, shared
# with the concurrency layer), a class that binds a lock ANYWHERE in
# its body (an attribute whose name contains lock/mutex/cond/cv —
# __init__ or, since ISSUE 10, any other method: the fleet supervisor's
# state made late-bound locks a real shape) may only write ``self.*``
# state inside a ``with self.<lock>:`` block. Escapes: ``__init__``
# itself (construction happens-before publication), methods whose name
# ends in ``_locked`` (the caller-holds-the-lock convention,
# self-documenting), and the pragma. Writes = Assign/AugAssign/
# AnnAssign/Delete whose target is rooted at ``self`` (attribute or
# subscript chains included: ``self.x = ...``, ``self.d[k] = ...``,
# ``self.a.b += 1``, ``del self.d[k]``); method-CALL mutations
# (``self.list.append``) are out of scope — the rule catches the
# lost-update/torn-read shapes, the review catches the rest.
#
# ISSUE 12 deduplicated the lock-detection machinery: what counts as a
# lock, a threaded module, a self-rooted write or a guarded region is
# defined ONCE in ``analysis.concurrency`` (the shared lock model the
# acquisition-graph rules build on) and re-fronted here.

from .concurrency import (LOCKISH as _LOCKISH,  # noqa: F401
                          lock_attrs_bound_in_class as
                          _lock_attrs_bound_in_class,
                          module_is_threaded as _module_is_threaded,
                          self_write_targets as _self_write_targets,
                          under_lock_with as _under_lock_with_parents)


def _under_lock_with(ctx: ModuleCtx, node: ast.AST,
                     method: ast.AST) -> bool:
    return _under_lock_with_parents(ctx.parents, node, method)


@rule("unguarded-shared-mutation", Severity.ERROR,
      "in threaded modules, classes that bind a dispatch lock "
      "(anywhere in the class body) must write self.* state inside "
      "`with self.<lock>:` (escapes: __init__, *_locked methods, "
      "pragma) — an unlocked write races the pump thread",
      scope=SCOPE_PACKAGE,
      fix_hint="wrap the write in `with self.<lock>:` or rename the "
      "method *_locked and call it under the lock")
def check_unguarded_shared_mutation(ctx: ModuleCtx):
    if not _module_is_threaded(ctx.tree):
        return
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs_bound_in_class(cls)
        if not locks:
            continue
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__" or method.name.endswith("_locked"):
                continue
            for node in ast.walk(method):
                for t in _self_write_targets(node):
                    if _under_lock_with(ctx, node, method):
                        continue
                    desc = (t.attr if isinstance(t, ast.Attribute)
                            else ast.dump(t)[:40])
                    yield Finding(
                        "unguarded-shared-mutation", Severity.ERROR,
                        ctx.path, node.lineno,
                        f"`{cls.name}.{method.name}` writes shared "
                        f"state `self.{desc}` outside `with "
                        f"self.{sorted(locks)[0]}:` — another thread "
                        "can race this write (guard it, rename the "
                        "method *_locked if the caller holds the lock, "
                        "or pragma a genuinely single-threaded path "
                        "with its reason)")


# -- wall-clock-in-test rule (ISSUE 10 satellite) ------------------------------
# PR 9 established the zero-wall-sleeps discipline: every latency/
# deadline/backoff path in the serving stack runs on an injectable
# clock, so tier-1 tests drive time deterministically instead of
# sleeping through it. This rule makes the discipline structural:
# `time.sleep`/`time.time` in a test module is an ERROR — a test that
# needs time passing advances a fake clock (see tests/test_serving.py's
# `clock = {"t": ...}` idiom); a test that genuinely must touch the
# wall (none today) pragmas the call with its reason.
# `time.monotonic`/`time.perf_counter` stay legal: reading a clock for
# a coarse duration bound does not make a test timing-dependent the way
# sleeping or comparing wall timestamps does.

#: the `time` module attributes whose CALL in a test is wall-clock
#: dependence: sleeping burns tier-1 wall, and `time.time()` asserts
#: against a clock the test does not control
WALL_CLOCK_ATTRS = {"sleep", "time"}


def _time_module_imports(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(function names, module aliases) bound in this module that
    resolve to the wall clock: ``from time import sleep, time as now``
    binds functions; ``import time`` / ``import time as _t`` binds the
    module under a (possibly aliased) name — both spellings are the
    same dependence and must lint the same."""
    funcs: set[str] = set()
    modules: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in WALL_CLOCK_ATTRS:
                    funcs.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    modules.add(a.asname or a.name)
    return funcs, modules


@rule("wall-clock-in-test", Severity.ERROR,
      "`time.sleep`/`time.time` in tests/ couples the suite to the "
      "wall clock — drive the injectable clock instead (pragma a "
      "genuine wall dependency with its reason)",
      scope=SCOPE_TESTS,
      fix_hint="drive the injectable clock (resilience.clock) instead of "
      "time.*")
def check_wall_clock_in_test(ctx: ModuleCtx):
    # only calls through an ACTUAL time import count: in a module that
    # never imports time, a name `time` is a local binding (e.g. a
    # fake-clock fixture — the very idiom this rule recommends), and
    # flagging it would be a false-positive ERROR
    from_imports, module_names = _time_module_imports(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit = None
        if (isinstance(fn, ast.Attribute) and fn.attr in WALL_CLOCK_ATTRS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in module_names):
            hit = f"{fn.value.id}.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id in from_imports:
            hit = fn.id
        if hit is not None:
            yield Finding(
                "wall-clock-in-test", Severity.ERROR, ctx.path,
                node.lineno,
                f"`{hit}(...)` in a test module — tests drive the "
                "injectable clock (a fake `clock`/`sleep` advancing a "
                "dict value), never the wall; sleeping fattens the "
                "tier-1 wall and wall-time asserts flake (pragma a "
                "genuine wall dependency with its reason)")


# -- naked-timer rule (ISSUE 15 satellite) ------------------------------------
# The serving stack now has a real observability layer: spans
# (utils.tracing — trace-context ids, cross-process propagation, the
# telemetry plane's per-stage rollups) and the shared LatencyReservoir
# (utils.metrics). A raw `time.perf_counter()` / `time.monotonic()`
# call in the ensemble modules is timing that BYPASSES both — it
# produces a number nobody can correlate with a ticket, a stage or a
# percentile. New timing should open a span or feed a reservoir; the
# handful of reasoned sites (the occupancy span bridge, client-facing
# wall deadlines, the wake-latency anchor, the wire's socket deadline
# arithmetic) carry pragmas naming why they are not spans.
# References (e.g. `clock=time.monotonic` as an injectable default)
# are NOT calls and stay legal.

#: the `time` attributes whose CALL in a serving module is naked timing
_TIMER_ATTRS = {"perf_counter", "monotonic"}


def _timer_imports(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(function names, module aliases) bound in this module that
    resolve to the monotonic timers — same resolution discipline as
    the wall-clock-in-test rule (only calls through a REAL time import
    count; a fake-clock local named `time` cannot false-positive)."""
    funcs: set[str] = set()
    modules: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _TIMER_ATTRS:
                    funcs.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    modules.add(a.asname or a.name)
    return funcs, modules


def _serving_module(ctx: ModuleCtx) -> bool:
    """The rule's scope: the ensemble serving modules. utils/tracing.py
    and utils/metrics.py are the sanctioned timing layer (not under
    ensemble/, so they are out of scope by construction)."""
    parts = ctx.resolved_parts
    return "ensemble" in parts[:-1]


@rule("naked-timer", Severity.WARNING,
      "direct time.perf_counter()/time.monotonic() timing in the "
      "serving/ensemble modules — new timing should flow through "
      "tracing spans or the metrics LatencyReservoir so it lands on "
      "the telemetry plane (pragma a reasoned site)",
      scope=SCOPE_PACKAGE,
      fix_hint="time the section with a tracing span or feed the sample "
      "into metrics.LatencyReservoir")
def check_naked_timer(ctx: ModuleCtx):
    if not _serving_module(ctx):
        return
    from_imports, module_names = _timer_imports(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        hit = None
        if (isinstance(fn, ast.Attribute) and fn.attr in _TIMER_ATTRS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in module_names):
            hit = f"{fn.value.id}.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id in from_imports:
            hit = fn.id
        if hit is not None:
            yield Finding(
                "naked-timer", Severity.WARNING, ctx.path, node.lineno,
                f"`{hit}(...)` in a serving module — time through "
                "utils.tracing spans (correlatable, exported, rolled "
                "up by the telemetry plane) or the "
                "utils.metrics.LatencyReservoir, or pragma a reasoned "
                "exception")


def audit_test_module(path) -> list[str]:
    """Marker-audit compatibility surface for
    ``tests/test_marker_audit.py``: ``["file.py::test_name", ...]`` for
    every unmarked heavy test, in source order."""
    p = Path(path)
    ctx = parse_module(p.read_text(), str(p))
    nodes = _unmarked_heavy_tests(ctx)
    return [f"{p.name}::{n.name}"
            for n in sorted(nodes, key=lambda n: n.lineno)]


# -- engine entry points ------------------------------------------------------

#: directories never descended into
SKIP_DIRS = {".git", "__pycache__", ".claude", "build", "node_modules",
             ".pytest_cache"}


def iter_py_files(root) -> Iterable[Path]:
    root = Path(root)
    if root.is_file():
        if root.suffix == ".py":
            yield root
        return
    for p in sorted(root.rglob("*.py")):
        if not any(part in SKIP_DIRS for part in p.parts):
            yield p


def _scope_matches(scope: str, ctx: ModuleCtx, package_name: str) -> bool:
    if scope == SCOPE_ALL:
        return True
    if scope == SCOPE_TESTS:
        return ctx.is_test
    if scope == SCOPE_PACKAGE:
        return (package_name in ctx.resolved_parts
                and not ctx.is_test)
    return False


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Iterable[str]] = None,
                package_name: str = "mpi_model_tpu") -> list[Finding]:
    """All findings (suppressed ones included, flagged) for one module's
    source. ``rules`` restricts to a subset of rule ids."""
    ctx = parse_module(source, path)
    selected = [RULES[r] for r in rules] if rules else list(RULES.values())
    raw: list[Finding] = []
    for rl in selected:
        if _scope_matches(rl.scope, ctx, package_name):
            raw.extend(rl.check(ctx))
    raw.sort(key=lambda f: (f.line, f.rule))
    return apply_pragmas(raw, ctx.pragmas, ctx.lines)


def lint_file(path, rules: Optional[Iterable[str]] = None,
              rel_to=None) -> list[Finding]:
    p = Path(path)
    shown = str(p.relative_to(rel_to)) if rel_to else str(p)
    try:
        source = p.read_text()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding("parse-error", Severity.ERROR, shown, 0,
                        f"unreadable: {e}")]
    try:
        return lint_source(source, shown, rules)
    except SyntaxError as e:
        return [Finding("parse-error", Severity.ERROR, shown,
                        e.lineno or 0, f"syntax error: {e.msg}")]


def run_astlint(roots, rules: Optional[Iterable[str]] = None,
                rel_to=None) -> list[Finding]:
    """Lint every ``.py`` under each root; findings keep file order."""
    findings: list[Finding] = []
    for root in roots:
        for p in iter_py_files(root):
            findings.extend(lint_file(p, rules, rel_to=rel_to))
    return findings


# -- hardcoded-physics rule (ISSUE 11 satellite) ------------------------------
# The Flow IR exists so new physics is TERMS + one registered lowering,
# not four hand-mirrored step functions. This rule is the structural
# backstop: transport-shaped arithmetic (the stencil redistribution
# helpers) appearing in package code OUTSIDE the ops/ kernels and the
# ir/ lowering reads as a fifth hand-written step growing back. The
# pre-IR call sites that legitimately remain (the legacy flow paths the
# IR cannot represent exactly) carry pragmas with their reasons — new
# ones must either live in ir/lowerings or justify themselves the same
# way.

#: the transport-shaped helper surface: calling any of these builds a
#: stencil redistribution step (or a piece of one)
_PHYSICS_HELPERS = {"transport", "flow_step", "point_flow_step",
                    "gather_neighbors", "gather_from_padded", "shift2d",
                    "weighted_counts_traced"}


def _physics_boundary_module(ctx: ModuleCtx) -> bool:
    """ops/ (the kernel layer) and ir/ (the registered lowerings) are
    where transport arithmetic lives by design."""
    parts = ctx.resolved_parts
    return "ops" in parts or "ir" in parts


@rule("hardcoded-physics", Severity.WARNING,
      "transport-shaped arithmetic (stencil redistribution helpers) "
      "outside ops/ and ir/ lowerings — new physics belongs in IR "
      "terms lowered once, not in another hand-mirrored step",
      scope=SCOPE_PACKAGE,
      fix_hint="express the stencil as a Flow IR term and lower it in "
      "ir.lower")
def check_hardcoded_physics(ctx: ModuleCtx):
    if _physics_boundary_module(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted_last(node.func)
        if name in _PHYSICS_HELPERS:
            yield Finding(
                "hardcoded-physics", Severity.WARNING, ctx.path,
                node.lineno,
                f"`{name}(...)` outside ops/ and ir/: transport-shaped "
                "arithmetic belongs in an IR term's registered lowering "
                "(ir.lower) so every engine serves it — pragma a "
                "retained legacy path with its reason")


# -- journal-kind-literal rule (ISSUE 19 satellite) ---------------------------
# The lifecycle refactor moved every journal record kind behind the
# constants in ensemble/lifecycle.py; this rule is what keeps them
# there. A raw string literal in an append or dispatch position
# compiles fine, runs fine, and silently re-forks the vocabulary the
# day it drifts from the declaration — exactly the failure class the
# layer-4 protocol audit exists for, caught here at the single-module
# level where the fix is one import away.

#: the helpers whose first argument IS a record kind (shared naming
#: with analysis.protocol's extraction)
_JOURNAL_APPEND_HELPERS = ("_journal_append_locked", "_append_locked")

_JOURNAL_VOCAB: Optional[frozenset] = None


def _journal_vocab() -> frozenset:
    """The declared record-kind strings, read off
    ``ensemble/lifecycle.py``'s AST (uppercase module-level string
    constants; ``INITIAL`` is a state, not a kind) — parsed, not
    imported, so the lint never executes package code."""
    global _JOURNAL_VOCAB
    if _JOURNAL_VOCAB is None:
        path = (Path(__file__).resolve().parent.parent
                / "ensemble" / "lifecycle.py")
        out = set()
        try:
            tree = ast.parse(path.read_text())
        except (OSError, SyntaxError):  # pragma: no cover - lifecycle
            # unreadable: the rule degrades to append-literals only
            tree = ast.Module(body=[], type_ignores=[])
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.isupper()
                    and node.targets[0].id != "INITIAL"
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                out.add(node.value.value)
        _JOURNAL_VOCAB = frozenset(out)
    return _JOURNAL_VOCAB


def _lifecycle_module(ctx: ModuleCtx) -> bool:
    parts = ctx.resolved_parts
    return (len(parts) >= 2 and parts[-2] == "ensemble"
            and parts[-1] == "lifecycle.py")


@rule("journal-kind-literal", Severity.ERROR,
      "a raw record-kind string literal in a journal append or "
      "dispatch position outside ensemble/lifecycle.py — the declared "
      "constants are the vocabulary's single spelling; a literal "
      "re-forks it invisibly",
      scope=SCOPE_PACKAGE,
      fix_hint="import the kind constant from ensemble.lifecycle "
               "(SUBMIT, SERVED, …) and use it instead of the literal")
def check_journal_kind_literal(ctx: ModuleCtx):
    if _lifecycle_module(ctx):
        return
    vocab = _journal_vocab()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            is_append = (
                (isinstance(fn, ast.Name)
                 and fn.id in _JOURNAL_APPEND_HELPERS)
                or (isinstance(fn, ast.Attribute)
                    and (fn.attr in _JOURNAL_APPEND_HELPERS
                         or (fn.attr == "append"
                             and "journal" in
                             (_dotted_last(fn.value) or "").lower()))))
            if (is_append and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                yield Finding(
                    "journal-kind-literal", Severity.ERROR, ctx.path,
                    node.lineno,
                    f"append site spells record kind "
                    f"{node.args[0].value!r} as a raw literal — use "
                    "the ensemble.lifecycle constant")
        elif isinstance(node, ast.Compare):
            left = node.left
            if not (isinstance(left, ast.Attribute)
                    and left.attr == "kind"):
                continue
            lits = [c.value for c in node.comparators
                    if isinstance(c, ast.Constant)
                    and isinstance(c.value, str)]
            for c in node.comparators:
                if isinstance(c, (ast.Tuple, ast.List, ast.Set)):
                    lits.extend(e.value for e in c.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str))
            hits = sorted(set(lits) & vocab)
            if hits:
                yield Finding(
                    "journal-kind-literal", Severity.ERROR, ctx.path,
                    node.lineno,
                    f"dispatch compares .kind against raw literal(s) "
                    f"{', '.join(map(repr, hits))} — use the "
                    "ensemble.lifecycle constants")
