"""``python -m mpi_model_tpu.analysis`` — run the static-analysis
gate over the repo.

Default mode runs the AST lint, the concurrency audit and the protocol
audit and gates on ERROR-severity findings. ``--strict`` is the PR bar
(what the tier-1 test runs): WARNINGs gate too, and the jaxpr contract
audit traces all four registered step impls. Exit status 0 means zero
unsuppressed findings at the selected bar.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .registry import RULES, SCOPE_ENGINE, Severity
from .astlint import run_astlint
from .concurrency import SCOPE_CONCURRENCY, run_concurrency_audit
from .protocol import SCOPE_PROTOCOL, run_protocol_audit
# registering the jaxpr contract rules is import-time cheap (jax itself
# loads lazily inside the audit) and makes --rule/--list-rules see the
# full rule table
from .jaxpr_audit import SCOPE_JAXPR, run_jaxpr_audit  # noqa: E402

#: what a bare invocation scans, relative to the repo root
DEFAULT_ROOTS = ("mpi_model_tpu", "tests", "benchmarks", "examples",
                 "bench.py", "__graft_entry__.py")


def _repo_root() -> Path:
    # the package sits at <root>/mpi_model_tpu/analysis
    return Path(__file__).resolve().parent.parent.parent


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mpi-model-analyze",
        description="AST lint + jaxpr contract audit for mpi_model_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the repo)")
    ap.add_argument("--strict", action="store_true",
                    help="gate WARNINGs too and run the jaxpr audit "
                    "(the PR bar)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the jaxpr audit even under --strict")
    ap.add_argument("--rule", action="append", dest="rules",
                    metavar="RULE-ID",
                    help="restrict the AST lint to these rule ids")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.name:18} {r.severity!s:8} {r.scope:8} {r.doc}")
        return 0

    ast_rules = jaxpr_rules = conc_rules = proto_rules = None
    if args.rules:
        unknown = [r for r in args.rules if r not in RULES]
        if unknown:
            import difflib

            for u in unknown:
                hint = difflib.get_close_matches(u, RULES, n=1)
                print(f"unknown rule id: {u!r}"
                      + (f" — did you mean {hint[0]!r}?" if hint
                         else " (see --list-rules)"),
                      file=sys.stderr)
            return 2
        ast_rules = [r for r in args.rules
                     if RULES[r].scope not in (SCOPE_JAXPR,
                                               SCOPE_CONCURRENCY,
                                               SCOPE_PROTOCOL,
                                               SCOPE_ENGINE)]
        jaxpr_rules = [r for r in args.rules
                       if RULES[r].scope == SCOPE_JAXPR]
        conc_rules = [r for r in args.rules
                      if RULES[r].scope == SCOPE_CONCURRENCY]
        proto_rules = [r for r in args.rules
                       if RULES[r].scope == SCOPE_PROTOCOL]
        if not (ast_rules or jaxpr_rules or conc_rules or proto_rules):
            # engine-scope rules (bare-pragma, parse-error) are
            # SYNTHESIZED alongside real checks — selecting only them
            # would scan nothing and report a hollow pass
            print("rule selection contains only engine-synthesized "
                  f"rule(s) ({', '.join(args.rules)}) — they fire "
                  "alongside real checks and cannot run alone; add a "
                  "checkable rule id or drop --rule",
                  file=sys.stderr)
            return 2

    root = _repo_root()
    if args.paths:
        roots = [Path(p) for p in args.paths]
        rel_to = None
    else:
        roots = [root / p for p in DEFAULT_ROOTS if (root / p).exists()]
        rel_to = root

    findings = []
    if ast_rules or not args.rules:
        findings.extend(run_astlint(roots, rules=ast_rules,
                                    rel_to=rel_to))
    if conc_rules or not args.rules:
        # layer 3 is whole-program: it always models the FULL package
        # (a path-scoped model would silently lose cross-module call
        # resolution — edges and blocking chains would vanish); when
        # the user named paths, only findings IN those paths are
        # reported
        conc = run_concurrency_audit(
            rules=conc_rules, rel_to=None if args.paths else rel_to)
        if args.paths:
            wanted = [Path(p).resolve() for p in args.paths]
            conc = [f for f in conc
                    if any(rp == w or w in rp.parents
                           for w in wanted
                           for rp in (Path(f.path).resolve(),))]
        findings.extend(conc)
    if proto_rules or not args.rules:
        # layer 4 is also whole-program: writer/reader pairs span
        # modules, so the audit always extracts from the full package
        # and path selections only filter the report
        proto = run_protocol_audit(
            rules=proto_rules, rel_to=None if args.paths else rel_to)
        if args.paths:
            wanted = [Path(p).resolve() for p in args.paths]
            proto = [f for f in proto
                     if any(rp == w or w in rp.parents
                            for w in wanted
                            for rp in (Path(f.path).resolve(),))]
        findings.extend(proto)
    run_audit = (jaxpr_rules
                 or (args.strict and not args.no_jaxpr and not args.rules))
    if run_audit:
        audit = run_jaxpr_audit()
        if jaxpr_rules:
            audit = [f for f in audit if f.rule in jaxpr_rules]
        findings.extend(audit)

    gate = (lambda f: True) if args.strict else (
        lambda f: f.severity is Severity.ERROR)
    blocking = [f for f in findings if not f.suppressed and gate(f)]
    advisory = [f for f in findings if not f.suppressed and not gate(f)]
    suppressed = [f for f in findings if f.suppressed]

    if args.as_json:
        def enrich(f):
            # every JSON finding carries its rule's contract and the
            # remedy inline — a CI annotation needs no registry lookup
            d = f.to_json()
            r = RULES.get(f.rule)
            d["rule_doc"] = r.doc if r else ""
            d["fix_hint"] = r.fix_hint if r else ""
            return d

        print(json.dumps({
            "strict": args.strict,
            "blocking": [enrich(f) for f in blocking],
            "advisory": [enrich(f) for f in advisory],
            "suppressed": [enrich(f) for f in suppressed],
        }, indent=2))
    else:
        for f in blocking:
            print(f.format())
        for f in advisory:
            print(f.format() + "  [advisory — gates under --strict]")
        print(f"analysis: {len(blocking)} blocking, "
              f"{len(advisory)} advisory, "
              f"{len(suppressed)} suppressed"
              + (" [strict]" if args.strict else ""))
    return 1 if blocking else 0


if __name__ == "__main__":
    raise SystemExit(main())
