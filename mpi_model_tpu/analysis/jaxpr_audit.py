"""Jaxpr contract audit (ISSUE 4 layer 2): abstract-trace every
registered step impl and machine-check the invariants the runtime layer
only ever asserted in one hand-written place.

Each contract builds a small canonical (model, space) pair, obtains the
impl's pure step function, and traces it with ``jax.make_jaxpr`` over
``ShapeDtypeStruct``s — no compilation, no execution, CPU-safe. The
audited contracts:

``jaxpr-dtype``
    every output aval's dtype equals the space dtype — the f64 oracle
    gates rely on no silent f32 (or weak-promotion f64) leak anywhere
    in a step.
``jaxpr-callback``
    no callback/debug/print primitives in the hot path — a stray
    ``jax.debug.print`` or ``io_callback`` serializes every step
    through the host.
``jaxpr-consts``
    no O(grid) array baked into the jaxpr as a constant (the historical
    ``neighbor_counts`` bug: a materialized count grid is a 256 MB
    constant at 8192² f32, re-shipped on every compile), and total
    consts under a byte budget.
``jaxpr-halo``
    stencil radius vs halo contract: the model's offsets must stay
    within the ring depth the impl's sharded configuration declares
    (ring-1 for dense/active/ensemble; ``k`` rings covering ``k``
    composed sub-steps for the composed filter, with ``k·passes ==
    substeps``).

``jaxpr-fused-flags``
    the fused active runner's per-pass while body carries no reduction
    at tile size or larger outside the kernel — the next-step activity
    flags come out of the Pallas pass itself (ISSUE 8's structural
    win), never a separate per-step re-scan.

``jaxpr-batch-psum``
    the mesh-sharded ensemble runner's per-scenario stat lanes reduce
    over the space axes only: exactly one f64 ``reduce_sum`` per
    channel at batch-grid size (``[B,H,W] → [B]``), nothing else that
    large — the batch-sharded conservation contract of ISSUE 16.

Audited impls: ``dense`` (the XLA stencil step), ``composed`` (k-step
filter), ``active`` (tile-skipping engine), ``ensemble`` (the vmapped
parametric scenario step), ``ensemble_mesh`` (the sharding-constrained
ensemble runner over a (batch, space) mesh, with the batch-psum
stat-lane contract), ``active_fused`` (the stateless fused
Pallas active step — scalar-prefetch-argument and halo k·passes ==
substeps contracts) and ``active_fused_runner`` (the amortized fused
loop — the jaxpr-fused-flags contract). The dense Pallas kernel impl
is exercised by its own runtime suite; its jaxpr is backend-shaped and
is audited where it matters — through the composed contract, which
traces the same ``_stencil_call`` machinery in interpret mode.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Callable, Optional

from .registry import RULES, Finding, Rule, Severity

#: registry scope tag for contract rules (never run by the AST engine)
SCOPE_JAXPR = "jaxpr"

#: total bytes of jaxpr consts a step may carry at the audit geometry —
#: generous for tap tables / index templates, far below any O(grid) bake
CONST_TOTAL_BUDGET = 1 << 20

#: primitive-name fragments that mean host traffic in the hot path
FORBIDDEN_PRIMITIVE_PARTS = ("callback", "debug", "print", "infeed",
                             "outfeed")


def _register(name: str, doc: str, fix_hint: str = "") -> None:
    if name not in RULES:
        RULES[name] = Rule(name, Severity.ERROR, doc,
                           check=lambda ctx: (), scope=SCOPE_JAXPR,
                           fix_hint=fix_hint)


_register("jaxpr-dtype",
          "every step output dtype must equal the space dtype (no "
          "silent f32/f64 leaks past the oracle gates)",
          fix_hint="cast with .astype(space.dtype) at the leak site "
                   "(usually a bare literal or np constant)")
_register("jaxpr-callback",
          "no callback/debug/print primitives inside a traced step",
          fix_hint="hoist the debug I/O out of the jitted function — "
                   "inspect outputs at the caller instead")
_register("jaxpr-consts",
          "no O(grid) constant baked into a step jaxpr; total consts "
          "within budget (recompile/memory bloat)",
          fix_hint="pass the array as a traced argument (donate if "
                   "large) instead of closing over it")
_register("jaxpr-halo",
          "stencil radius must fit the halo depth the impl's sharded "
          "configuration declares",
          fix_hint="widen halo_depth in the impl's sharding config or "
                   "shrink the stencil radius")
_register("jaxpr-term-registry",
          "every Flow IR term kind has exactly one registered, audited "
          "lowering, and it lives in ir.lower — no impl-private term "
          "branches",
          fix_hint="move the term's lowering into ir.lower and register "
                   "it there; delete the impl-local branch")
_register("jaxpr-fused-flags",
          "the fused active runner's per-pass loop must carry no "
          "reduction at tile size or larger outside the kernel — "
          "activity flags come out of the Pallas pass, never a "
          "separate per-step reduction",
          fix_hint="emit the activity flag from the Pallas kernel's "
                   "accumulator output rather than reducing the field "
                   "again outside it")
_register("jaxpr-batch-psum",
          "the mesh-sharded ensemble runner's per-scenario stat lanes "
          "must reduce over the space axes only (one f64 reduce_sum "
          "per channel, [B,H,W] -> [B]) — a full-batch or "
          "wrong-dtype reduction would break the batch-sharded "
          "conservation contract",
          fix_hint="reduce with axis=(1, 2) (space only) and cast the "
                   "accumulator to f64 before the sum")


@dataclasses.dataclass
class BuiltStep:
    """What a contract build hands the checker."""

    impl: str
    fn: Callable                 # traced as fn(*args)
    args: tuple                  # ShapeDtypeStructs / pytrees thereof
    space_dtype: object
    grid_nbytes: int             # one channel's bytes at audit geometry
    offsets: tuple
    halo_depth: int              # ring depth the sharded config declares
    composed_k: Optional[int] = None
    composed_passes: Optional[int] = None
    substeps: int = 1
    #: False for runner-shaped contracts whose outputs legitimately
    #: carry stat counters beside the space-dtype values
    dtype_check: bool = True
    #: the fused impls: every pallas_call must scalar-prefetch its
    #: index buffer as a traced ARGUMENT (never a baked literal)
    expect_prefetch_arg: bool = False
    #: when set (tile cell count), enforce jaxpr-fused-flags on every
    #: innermost while body that contains a pallas_call
    fused_flags_tile_elems: Optional[int] = None
    #: mesh-runner stat-lane contract (ISSUE 16): {"count": n_channels,
    #: "dtype": np dtype, "min_elems": B*H*W} — exactly ``count``
    #: reduce_sum eqns at batch-grid size, each producing ``dtype``
    #: (the [B,H,W] -> [B] per-scenario reductions, and nothing else
    #: at that size)
    batch_psum: Optional[dict] = None


#: impl name → zero-arg builder (registered below)
CONTRACTS: dict[str, Callable[[], BuiltStep]] = {}


def contract(name: str):
    def deco(fn):
        CONTRACTS[name] = fn
        return fn
    return deco


def _sds(arr):
    import jax
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def _space_model(dtype: str, grid: int = 16, with_point: bool = True):
    from ..core.cellular_space import CellularSpace
    from ..models.model import Model
    from ..ops.flow import Diffusion, Exponencial
    space = CellularSpace.create(grid, grid, 1.0, dtype=dtype)
    flows = [Diffusion(0.1)]
    if with_point:
        flows.append(Exponencial((3, 3), 0.05))
    return space, Model(flows, 10.0, 0.2)


@contract("dense")
def _build_dense() -> BuiltStep:
    space, model = _space_model("float64", 16)
    step = model.make_step(space, impl="xla")
    args = {k: _sds(v) for k, v in space.values.items()}
    v0 = next(iter(space.values.values()))
    return BuiltStep("dense", step, (args,), space.dtype,
                     v0.dtype.itemsize * v0.size, model.offsets, 1)


@contract("composed")
def _build_composed() -> BuiltStep:
    # composed eligibility: all-Diffusion, full f32 grid; 64² admits
    # k=4 (max_k is the window ghost depth, 8 rows at f32)
    space, model = _space_model("float32", 64, with_point=False)
    step = model.make_step(space, impl="composed", substeps=4)
    args = {k: _sds(v) for k, v in space.values.items()}
    v0 = next(iter(space.values.values()))
    return BuiltStep("composed", step, (args,), space.dtype,
                     v0.dtype.itemsize * v0.size, model.offsets,
                     halo_depth=step.composed_k,
                     composed_k=step.composed_k,
                     composed_passes=step.composed_passes, substeps=4)


@contract("active")
def _build_active() -> BuiltStep:
    space, model = _space_model("float64", 64, with_point=False)
    with warnings.catch_warnings():
        # the CPU rig cannot compile the real Pallas dense fallback; the
        # probe's RuntimeWarning is expected and the XLA fallback is the
        # path we audit
        warnings.simplefilter("ignore")
        step = model.make_step(space, impl="active")
    args = {k: _sds(v) for k, v in space.values.items()}
    v0 = next(iter(space.values.values()))
    return BuiltStep("active", step, (args,), space.dtype,
                     v0.dtype.itemsize * v0.size, model.offsets, 1)


@contract("ensemble")
def _build_ensemble() -> BuiltStep:
    import jax
    import numpy as np
    from ..ensemble.batch import flow_params, make_scenario_step
    space, model = _space_model("float64", 16)
    single = make_scenario_step(model, space)
    B = 3
    rates, frozens = flow_params([model] * B)
    vals_b = {k: jax.ShapeDtypeStruct((B,) + v.shape, v.dtype)
              for k, v in space.values.items()}
    fn = jax.vmap(single)
    v0 = next(iter(space.values.values()))
    return BuiltStep(
        "ensemble", fn,
        (vals_b, jax.ShapeDtypeStruct(rates.shape, np.float64),
         jax.ShapeDtypeStruct(frozens.shape, np.float64)),
        space.dtype, v0.dtype.itemsize * v0.size, model.offsets, 1)


@contract("ensemble_mesh")
def _build_ensemble_mesh() -> BuiltStep:
    # the mesh-sharded ensemble runner (ISSUE 16): the REAL compiled
    # artifact — EnsembleExecutor._build_xla with the (batch, space)
    # carry constraint — plus the per-scenario stat lanes
    # (batched_totals' float branch: f64 sums over the space axes).
    # Degrades to a 1-device mesh when the rig has a single CPU device
    # (`analysis --strict` runs without the test conftest's 8-device
    # XLA flag), which still audits the sharding-constrained lowering.
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..ensemble.batch import EnsembleExecutor, EnsembleSpace, flow_params
    from ..ensemble.mesh import make_ensemble_mesh

    space, model = _space_model("float64", 16)
    cpu = jax.devices("cpu")
    n = max(1, min(2, len(cpu)))
    emesh = make_ensemble_mesh(batch=n, devices=cpu[:n])
    B = 2 * n
    espace = EnsembleSpace.stack([space] * B)
    ex = EnsembleExecutor(mesh=emesh)
    run = ex.runner_for(model, espace)
    rates, frozens = flow_params([model] * B)

    def fn(vb, rates_b, frozens_b, q, r):
        out = run(vb, rates_b, frozens_b, q, r)
        # the per-scenario stat lanes: device-side f64 sums over the
        # space axes only — [B,H,W] -> [B], batch-sharded throughout
        return {k: jnp.sum(v, axis=(1, 2), dtype=jnp.float64)
                for k, v in out.items()}

    vals_b = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
              for k, v in espace.values.items()}
    args = (vals_b, _sds(rates), _sds(frozens),
            jax.ShapeDtypeStruct((), np.dtype("int32")),
            jax.ShapeDtypeStruct((), np.dtype("int32")))
    v0 = next(iter(space.values.values()))
    return BuiltStep(
        "ensemble_mesh", fn, args, space.dtype,
        v0.dtype.itemsize * v0.size, model.offsets, 1,
        batch_psum={"count": len(espace.values),
                    "dtype": np.dtype("float64"),
                    "min_elems": B * space.shape[0] * space.shape[1]})


@contract("active_fused")
def _build_active_fused() -> BuiltStep:
    # the stateless fused step: substeps=4 on a 64² f64 grid composes
    # one k=4 pass per call (tile (64, 64) admits k up to MAX_FUSED_K);
    # the runner-shaped loop contract is audited separately below
    space, model = _space_model("float64", 64, with_point=False)
    with warnings.catch_warnings():
        # CPU rig: the dense-fallback Pallas probe warns and degrades
        # to the XLA transport — expected, and the path we audit
        warnings.simplefilter("ignore")
        step = model.make_step(space, impl="active_fused", substeps=4)
    args = {k: _sds(v) for k, v in space.values.items()}
    v0 = next(iter(space.values.values()))
    return BuiltStep("active_fused", step, (args,), space.dtype,
                     v0.dtype.itemsize * v0.size, model.offsets,
                     halo_depth=step.composed_k,
                     composed_k=step.composed_k,
                     composed_passes=step.composed_passes, substeps=4,
                     expect_prefetch_arg=True)


@contract("active_fused_runner")
def _build_active_fused_runner() -> BuiltStep:
    # the amortized whole-run form (SerialExecutor's fast path): the
    # jaxpr-fused-flags contract lives HERE — its per-pass while body
    # must carry no tile-or-larger reduction outside the kernel
    import jax
    import numpy as np
    from ..ops.active import plan_for
    from ..ops.pallas_active import build_fused_runner, choose_fused_k
    space, model = _space_model("float64", 64, with_point=False)
    plan = plan_for(space.shape)
    k = choose_fused_k(4, plan)
    rates = model.pallas_rates()
    run = build_fused_runner(space.shape, rates, model.offsets,
                             space.dtype, plan=plan, k=k,
                             track_dirty=True)
    args = ({kk: _sds(v) for kk, v in space.values.items()},
            jax.ShapeDtypeStruct((), np.dtype("int32")))
    v0 = next(iter(space.values.values()))
    return BuiltStep("active_fused_runner", run, args, space.dtype,
                     v0.dtype.itemsize * v0.size, model.offsets,
                     halo_depth=k, composed_k=k, composed_passes=1,
                     substeps=k, dtype_check=False,
                     expect_prefetch_arg=True,
                     fused_flags_tile_elems=plan.tile[0] * plan.tile[1])


def _ir_contract(model_name: str, impl: str, grid: int = 32):
    """One Flow IR lowering golden: trace ``FlowIRModel.make_step`` for
    a registered library model under one eligible impl (the per-term
    lowering goldens satellite — ISSUE 11). The audited jaxpr is the
    SAME registered lowering every engine consumes, so dtype/callback/
    const/halo violations in any term's lowering surface here once."""
    def build() -> BuiltStep:
        import jax
        from ..ir import library
        from ..ir.model import FlowIRModel
        model, space = library.build_model(model_name, grid,
                                           dtype="float64")
        if impl == "active":
            # a sub-grid tile plan so the WINDOW machinery (not the
            # one-tile dense degeneration) is what gets audited
            model = FlowIRModel(model.ir_terms, model.time,
                                model.time_step,
                                active_opts={"tile": (grid // 4,
                                                      grid // 4)})
        step = model.make_step(space, impl=impl)
        args = {k: _sds(v) for k, v in space.values.items()}
        v0 = next(iter(space.values.values()))
        return BuiltStep(f"ir_{model_name}_{impl}", step, (args,),
                         space.dtype, v0.dtype.itemsize * v0.size,
                         model.offsets, 1)
    return build


for _m in ("gray_scott", "sir", "predator_prey"):
    for _i in ("xla", "composed", "active"):
        CONTRACTS[f"ir_{_m}_{_i}"] = _ir_contract(_m, _i)
CONTRACTS["ir_diffusion_xla"] = _ir_contract("diffusion", "xla")


def check_term_registry() -> list[Finding]:
    """The ``jaxpr-term-registry`` rule: walk every Term subclass the
    package defines (transitively) and assert the ir.lower registry
    holds exactly one lowering for each, defined IN ir.lower. A term
    kind lowered elsewhere — an impl-private branch — is exactly the
    hand-mirroring the IR exists to end."""
    from ..ir import lower as ir_lower
    from ..ir.terms import Term

    findings: list[Finding] = []

    def subclasses(cls):
        for sub in cls.__subclasses__():
            yield sub
            yield from subclasses(sub)

    for kind in subclasses(Term):
        low = ir_lower.LOWERINGS.get(kind)
        inherited = None
        if low is None:
            # a subclass may legitimately inherit its base kind's
            # registered lowering (same apply contract); only a kind
            # with NO lowering anywhere in its MRO is unregistered
            for base in kind.__mro__[1:]:
                if base in ir_lower.LOWERINGS:
                    inherited = ir_lower.LOWERINGS[base]
                    break
            if inherited is None:
                findings.append(Finding(
                    "jaxpr-term-registry", Severity.ERROR,
                    "jaxpr:term-registry", 0,
                    f"term kind {kind.__name__} has no registered "
                    "lowering — register exactly one in ir.lower"))
                continue
        target = low if low is not None else inherited
        mod = getattr(target, "__module__", "")
        if mod != ir_lower.__name__:
            findings.append(Finding(
                "jaxpr-term-registry", Severity.ERROR,
                "jaxpr:term-registry", 0,
                f"term kind {kind.__name__}'s lowering {target!r} is "
                f"defined in {mod!r}, not ir.lower — impl-private term "
                "lowerings reintroduce the hand-mirroring the IR "
                "replaces"))
    return findings


# -- jaxpr walks --------------------------------------------------------------

def _iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and (recursively) in any sub-jaxpr held in
    eqn params (pjit/scan/while/cond/closed_call/pallas grids)."""
    from ..compat import jaxpr_type
    Jaxpr = jaxpr_type()
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in _as_jaxprs(val, Jaxpr):
                yield from _iter_eqns(sub)


def _as_jaxprs(val, Jaxpr):
    if isinstance(val, Jaxpr):
        yield val
    elif hasattr(val, "jaxpr") and isinstance(val.jaxpr, Jaxpr):
        yield val.jaxpr  # ClosedJaxpr
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from _as_jaxprs(v, Jaxpr)


#: reduction primitives the jaxpr-fused-flags contract scans for —
#: genuine cross-element reductions only (``reduce_precision`` is an
#: elementwise cast and must NOT match, hence no substring matching)
REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min",
    "reduce_or", "reduce_and", "reduce_xor", "argmax", "argmin",
    "reduce_window_sum", "reduce_window_max", "reduce_window_min",
})


def _iter_eqns_outside_pallas(jaxpr):
    """Like ``_iter_eqns`` but does NOT descend into a pallas_call's
    kernel jaxpr — the fused-flags contract is about what runs OUTSIDE
    the kernel (in-kernel reductions over the VMEM-resident tile are
    the whole point)."""
    from ..compat import jaxpr_type
    Jaxpr = jaxpr_type()
    for eqn in jaxpr.eqns:
        yield eqn
        if "pallas" in eqn.primitive.name:
            continue
        for val in eqn.params.values():
            for sub in _as_jaxprs(val, Jaxpr):
                yield from _iter_eqns_outside_pallas(sub)


def _has_eqn(jaxpr, pred) -> bool:
    return any(pred(eqn) for eqn in _iter_eqns_outside_pallas(jaxpr))


def _grid_reductions(jaxpr, min_elems: int):
    """Reduction eqns (outside kernels) whose any input reaches
    ``min_elems`` elements — the per-pass loop of the fused runner must
    have none (flags come out of the kernel)."""
    import math
    for eqn in _iter_eqns_outside_pallas(jaxpr):
        if eqn.primitive.name not in REDUCE_PRIMS:
            continue
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            size = int(math.prod(getattr(aval, "shape", ())))
            if size >= min_elems:
                yield eqn, size
                break


def stencil_radius(offsets) -> int:
    """Chebyshev radius of a neighborhood: rings of halo a step needs."""
    return max(max(abs(int(dx)), abs(int(dy))) for dx, dy in offsets)


def _const_nbytes(c) -> int:
    size = getattr(c, "size", None)
    itemsize = getattr(getattr(c, "dtype", None), "itemsize", None)
    if size is None or itemsize is None:
        return 0
    return int(size) * int(itemsize)


# -- the audit ----------------------------------------------------------------

def audit_built(built: BuiltStep) -> list[Finding]:
    import jax
    where = f"jaxpr:{built.impl}"
    findings: list[Finding] = []
    try:
        closed = jax.make_jaxpr(built.fn)(*built.args)
    # analysis: ignore[broad-except] — the audit must report a trace
    # failure as a finding, not crash the analyzer, whatever it raised
    except Exception as e:
        findings.append(Finding(
            "jaxpr-dtype", Severity.ERROR, where, 0,
            f"step impl {built.impl!r} failed to trace: "
            f"{type(e).__name__}: {e}"))
        return findings

    # dtype stability: every output aval carries the space dtype
    # (runner-shaped contracts opt out — their stat counters are
    # integer outputs by design)
    import numpy as np
    want = np.dtype(built.space_dtype)
    if built.dtype_check:
        for i, aval in enumerate(closed.out_avals):
            got = np.dtype(aval.dtype)
            if got != want:
                findings.append(Finding(
                    "jaxpr-dtype", Severity.ERROR, where, 0,
                    f"output {i} of the {built.impl} step has dtype "
                    f"{got.name}, space dtype is {want.name} — a silent "
                    "promotion/downcast crossed the step boundary"))

    # hot-path purity: no host-callback/debug primitives anywhere
    for eqn in _iter_eqns(closed.jaxpr):
        pname = eqn.primitive.name
        if any(part in pname for part in FORBIDDEN_PRIMITIVE_PARTS):
            findings.append(Finding(
                "jaxpr-callback", Severity.ERROR, where, 0,
                f"primitive `{pname}` inside the {built.impl} step — "
                "host traffic in the traced hot path"))

    # consts budget: nothing O(grid), total bounded
    total = 0
    for c in closed.consts:
        nb = _const_nbytes(c)
        total += nb
        if nb >= built.grid_nbytes:
            findings.append(Finding(
                "jaxpr-consts", Severity.ERROR, where, 0,
                f"a {nb}-byte constant (>= one {built.grid_nbytes}-byte "
                f"grid channel) is baked into the {built.impl} jaxpr — "
                "compute it traced (the neighbor_counts_traced "
                "discipline) or pass it as an argument"))
    if total > CONST_TOTAL_BUDGET:
        findings.append(Finding(
            "jaxpr-consts", Severity.ERROR, where, 0,
            f"jaxpr consts total {total} bytes for the {built.impl} "
            f"step (budget {CONST_TOTAL_BUDGET}) — recompile/memory "
            "bloat; move large tables to arguments"))

    # halo contract
    radius = stencil_radius(built.offsets)
    per_exchange = built.composed_k or 1
    need = radius * per_exchange
    if need > built.halo_depth:
        findings.append(Finding(
            "jaxpr-halo", Severity.ERROR, where, 0,
            f"{built.impl} step needs {need} halo ring(s) (offsets "
            f"radius {radius} × {per_exchange} sub-step(s) per "
            f"exchange) but its sharded config declares halo_depth="
            f"{built.halo_depth} — shard edges would read stale ghosts"))
    if built.composed_k is not None:
        k, passes = built.composed_k, built.composed_passes
        if k * passes != built.substeps:
            findings.append(Finding(
                "jaxpr-halo", Severity.ERROR, where, 0,
                f"composed k={k} × passes={passes} != substeps="
                f"{built.substeps} — the composed call no longer equals "
                "the iterated step count"))

    # fused-impl contracts (ISSUE 8): the kernel actually lowered, and
    # its scalar-prefetched operands — the compacted index buffer above
    # all — are traced ARGUMENTS, never baked literals (a literal ids
    # buffer would freeze one activity pattern into the compile)
    if built.expect_prefetch_arg:
        from ..compat import literal_type
        Literal = literal_type()
        n_pallas = 0
        for eqn in _iter_eqns(closed.jaxpr):
            if "pallas" not in eqn.primitive.name:
                continue
            n_pallas += 1
            gm = eqn.params.get("grid_mapping")
            nsp = int(getattr(gm, "num_index_operands", 0) or 0)
            if nsp < 1:
                findings.append(Finding(
                    "jaxpr-consts", Severity.ERROR, where, 0,
                    f"a pallas_call in the {built.impl} step prefetches "
                    "no scalar operands — the fused contract requires "
                    "the compacted index buffer to ride scalar prefetch"))
                continue
            for v in eqn.invars[:nsp]:
                if isinstance(v, Literal):
                    findings.append(Finding(
                        "jaxpr-consts", Severity.ERROR, where, 0,
                        f"a scalar-prefetch operand of a pallas_call in "
                        f"the {built.impl} step is a baked literal — the "
                        "index buffer must be a traced argument"))
        if n_pallas == 0:
            findings.append(Finding(
                "jaxpr-consts", Severity.ERROR, where, 0,
                f"the {built.impl} step lowered no pallas_call at all — "
                "the fused kernel is not in the hot path"))

    # jaxpr-batch-psum (ISSUE 16): the mesh runner's per-scenario stat
    # lanes — exactly one batch-grid-size reduce_sum per channel, each
    # producing f64. More means a stray whole-state reduction crept
    # into the hot path; fewer (or a narrower dtype) means the [B]
    # conservation lanes are no longer the audited f64 space-axis sums
    if built.batch_psum is not None:
        import math
        spec = built.batch_psum
        found = []
        for eqn in _iter_eqns(closed.jaxpr):
            if eqn.primitive.name != "reduce_sum":
                continue
            for v in eqn.invars:
                aval = getattr(v, "aval", None)
                size = int(math.prod(getattr(aval, "shape", ())))
                if size >= int(spec["min_elems"]):
                    found.append(eqn)
                    break
        if len(found) != int(spec["count"]):
            findings.append(Finding(
                "jaxpr-batch-psum", Severity.ERROR, where, 0,
                f"{len(found)} batch-grid-size reduce_sum eqn(s) in the "
                f"{built.impl} runner, contract expects exactly "
                f"{spec['count']} (one [B,H,W] -> [B] stat reduction "
                "per channel, nothing else at that size)"))
        for eqn in found:
            got = np.dtype(eqn.outvars[0].aval.dtype)
            if got != np.dtype(spec["dtype"]):
                findings.append(Finding(
                    "jaxpr-batch-psum", Severity.ERROR, where, 0,
                    f"a batch-axis stat reduction in the {built.impl} "
                    f"runner produces {got.name}, contract requires "
                    f"{np.dtype(spec['dtype']).name} — the conservation "
                    "lanes must stay f64"))

    # jaxpr-fused-flags: every innermost while body that runs the
    # kernel must be free of tile-or-larger reductions outside it —
    # the per-pass activity flags come out of the Pallas pass, never a
    # separate per-step reduction (the O(grid)/O(capacity-buffer)
    # re-scan the fused engine exists to eliminate)
    if built.fused_flags_tile_elems is not None:
        thresh = int(built.fused_flags_tile_elems)
        for eqn in _iter_eqns(closed.jaxpr):
            if eqn.primitive.name != "while":
                continue
            body = eqn.params["body_jaxpr"].jaxpr
            if not _has_eqn(body, lambda e: "pallas" in e.primitive.name):
                continue
            if _has_eqn(body, lambda e: e.primitive.name == "while"):
                continue  # outer nest: the dense-fallback branch may scan
            for bad, size in _grid_reductions(body, thresh):
                findings.append(Finding(
                    "jaxpr-fused-flags", Severity.ERROR, where, 0,
                    f"`{bad.primitive.name}` over {size} elements inside "
                    f"the {built.impl} per-pass loop — activity flags "
                    "must come out of the fused kernel, not a separate "
                    "per-step reduction"))
    return findings


def run_jaxpr_audit(impls=None) -> list[Finding]:
    """Audit the registered step impls (all four by default). Pins jax
    to CPU-compatible tracing only — nothing compiles or executes."""
    import jax
    # the dtype contract is about the f64 oracle tier: without x64 the
    # canonical f64 spaces silently truncate to f32 and the check is
    # vacuous (the test rig's conftest sets the same two knobs); both
    # knobs are restored on exit so a library caller's ambient config
    # survives the audit
    prev_x64 = jax.config.jax_enable_x64
    prev_dev = jax.config.jax_default_device
    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_default_device", "cpu")
    findings: list[Finding] = []
    if impls is None or "term-registry" in impls:
        findings.extend(check_term_registry())
    try:
        for name, build in CONTRACTS.items():
            if impls is not None and name not in impls:
                continue
            try:
                built = build()
            # analysis: ignore[broad-except] — a broken contract build
            # must surface as a finding for ITS impl; the other
            # contracts run on
            except Exception as e:
                findings.append(Finding(
                    "jaxpr-dtype", Severity.ERROR, f"jaxpr:{name}", 0,
                    f"contract build for {name!r} failed: "
                    f"{type(e).__name__}: {e}"))
                continue
            findings.extend(audit_built(built))
    finally:
        jax.config.update("jax_enable_x64", prev_x64)
        jax.config.update("jax_default_device", prev_dev)
    return findings
