from .metrics import marginal_runner_time, marginal_step_time
from .roofline import chip_peaks, stencil_roofline
from .tracing import Span, Tracer, get_tracer, set_tracer, trace_span

__all__ = [
    "marginal_step_time",
    "marginal_runner_time",
    "chip_peaks",
    "stencil_roofline",
    "Span",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "trace_span",
]
