from .metrics import marginal_runner_time, marginal_step_time

__all__ = ["marginal_step_time", "marginal_runner_time"]
