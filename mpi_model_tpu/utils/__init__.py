from .compile_cache import configure_compile_cache, configured_dir
from .metrics import (ThroughputCounter, interleaved_ab,
                      marginal_runner_time, marginal_runner_trials,
                      marginal_step_time, marginal_step_trials,
                      median_spread, positive_spread)
from .roofline import chip_peaks, stencil_roofline
from .tracing import Span, Tracer, get_tracer, set_tracer, trace_span

__all__ = [
    "ThroughputCounter",
    "configure_compile_cache",
    "configured_dir",
    "marginal_step_time",
    "marginal_step_trials",
    "median_spread",
    "positive_spread",
    "marginal_runner_time",
    "marginal_runner_trials",
    "interleaved_ab",
    "chip_peaks",
    "stencil_roofline",
    "Span",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "trace_span",
]
