from .metrics import marginal_runner_time, marginal_step_time
from .tracing import Span, Tracer, get_tracer, set_tracer, trace_span

__all__ = [
    "marginal_step_time",
    "marginal_runner_time",
    "Span",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "trace_span",
]
