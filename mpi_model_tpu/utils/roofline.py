"""Roofline accounting: how close a measured step runs to chip ceilings.

Round-3 VERDICT missing #4: BENCH reported cell-updates/s but never the
achieved fraction of peak, so "84× the north star" could still be far
from this chip's roofline and nobody could tell from the artifacts.
Since the reference publishes nothing (``/root/reference/README.md:1``),
we own the baseline AND its ceiling analysis (SURVEY §6).

Peaks are parameterized per ``device_kind`` from public datasheet
numbers; the VPU figure is an ESTIMATE (vector-unit throughput is not
published the way MXU TFLOPs are: lanes × ALU slots × clock). Override
with env vars when better numbers are known for a given part:
``MMTPU_HBM_PEAK_GBPS``, ``MMTPU_VPU_PEAK_GOPS``.

The stencil model (``stencil_roofline``) charges the fused kernel
2·bytes/cell of HBM traffic per ``substeps``-step chunk (one read + one
write of the grid; inter-tile ghost re-reads are a few % and ignored)
and ``flops_per_cell`` VPU ops per cell per step — 11 for the Moore-8
closed-form interior (1 mul rate·v, 1 div-by-count folded to a mul,
7 adds for the 8-share sum, 2 update adds), counting every add/mul as
one op. These are *useful-arithmetic* floors: the kernel also spends
VPU slots on the shifted-window data movement, so pct_of_compute_peak
understates true VPU occupancy.
"""

from __future__ import annotations

import os
from typing import Any, Optional

#: public-datasheet peaks per jax ``device_kind`` (VPU = estimate, see
#: module docstring). hbm in GB/s; vpu in Gop/s for f32 elementwise.
CHIP_PEAKS: dict[str, dict[str, float]] = {
    # v5e: 819 GB/s HBM, 197 bf16 MXU TFLOPs; VPU ≈ 8·128 lanes ×
    # 4 ALU slots × ~0.94 GHz ≈ 3.9 Top/s
    "TPU v5 lite": {"hbm_gbps": 819.0, "vpu_gops": 3900.0,
                    "mxu_bf16_tflops": 197.0},
    # v4: 1228 GB/s, 275 bf16 TFLOPs
    "TPU v4": {"hbm_gbps": 1228.0, "vpu_gops": 4300.0,
               "mxu_bf16_tflops": 275.0},
    # v5p: 2765 GB/s, 459 bf16 TFLOPs
    "TPU v5": {"hbm_gbps": 2765.0, "vpu_gops": 7000.0,
               "mxu_bf16_tflops": 459.0},
    # v6e (Trillium): 1640 GB/s, 918 bf16 TFLOPs
    "TPU v6 lite": {"hbm_gbps": 1640.0, "vpu_gops": 7800.0,
                    "mxu_bf16_tflops": 918.0},
}


#: jax ``device_kind`` spellings that mean a chip already in the table.
#: Letter suffixes denote DIFFERENT chips ('v5e' is the lite part, 'v5p'
#: the full part, 'v4i' the inference part) — they must be mapped
#: explicitly, never by prefix, or 'TPU v5e' would inherit v5p's 2765
#: GB/s and report a ~3.4x-understated percent-of-peak.
KIND_ALIASES: dict[str, str] = {
    "TPU v5e": "TPU v5 lite",
    "TPU v5p": "TPU v5",
    "TPU v6e": "TPU v6 lite",
}


def _lookup_peaks(kind: str) -> dict[str, float]:
    """Exact match, then the alias table, then the longest table key
    that prefixes the reported ``device_kind`` AT A WORD BOUNDARY
    ('TPU v4 pod slice' → 'TPU v4'; 'TPU v4i' does NOT match — a letter
    suffix is a different chip). An unmatched TPU part warns once
    instead of silently losing its percent-of-peak (round-4 ADVICE);
    inventing the wrong ceiling would be worse than omitting it."""
    k = " ".join(kind.split())
    if k in CHIP_PEAKS:
        return dict(CHIP_PEAKS[k])
    if k in KIND_ALIASES:
        return dict(CHIP_PEAKS[KIND_ALIASES[k]])
    for key in sorted(CHIP_PEAKS, key=len, reverse=True):
        if k.startswith(key + " "):
            return dict(CHIP_PEAKS[key])
    if "tpu" in k.lower() and k not in _WARNED_KINDS:
        import warnings

        _WARNED_KINDS.add(k)
        warnings.warn(
            f"unrecognized TPU device_kind {kind!r}: no peak table entry "
            f"(known: {sorted(CHIP_PEAKS)}); percent-of-peak will be "
            f"omitted — set MMTPU_HBM_PEAK_GBPS / MMTPU_VPU_PEAK_GOPS "
            f"to supply peaks", stacklevel=3)
    return {}


_WARNED_KINDS: set[str] = set()


def chip_peaks(device=None) -> Optional[dict[str, Any]]:
    """Peak table entry for ``device`` (default: first jax device), with
    env overrides applied; None for unknown parts (e.g. CPU test rigs —
    report measurements without percent-of-peak rather than invent a
    ceiling)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    peaks = _lookup_peaks(kind)
    hbm = os.environ.get("MMTPU_HBM_PEAK_GBPS")
    vpu = os.environ.get("MMTPU_VPU_PEAK_GOPS")
    if hbm:
        peaks["hbm_gbps"] = float(hbm)
    if vpu:
        peaks["vpu_gops"] = float(vpu)
    if not peaks.get("hbm_gbps"):
        return None
    peaks["device_kind"] = kind
    return peaks


def stencil_roofline(grid: int, itemsize: int, t_step_s: float,
                     substeps: int = 1, nchannels: int = 1,
                     flops_per_cell: float = 11.0,
                     device=None) -> dict[str, Any]:
    """Achieved bandwidth/throughput (and % of peak when the chip is
    known) for one measured stencil step of ``t_step_s`` seconds.

    ``t_step_s`` is the per-FLOW-step time; with ``substeps``-fused
    kernels the HBM traffic amortizes over the chunk, the arithmetic
    does not."""
    cells = float(grid) * float(grid) * nchannels
    bytes_per_step = 2.0 * cells * itemsize / max(1, substeps)
    flops_per_step = flops_per_cell * cells
    out: dict[str, Any] = {
        "bytes_per_step": bytes_per_step,
        "flops_per_step": flops_per_step,
        "achieved_gbps": bytes_per_step / t_step_s / 1e9,
        "achieved_gflops": flops_per_step / t_step_s / 1e9,
        "pct_of_hbm_peak": None,
        "pct_of_compute_peak": None,
        "device_kind": None,
    }
    peaks = chip_peaks(device)
    if peaks is not None:
        out["device_kind"] = peaks["device_kind"]
        out["pct_of_hbm_peak"] = round(
            100.0 * out["achieved_gbps"] / peaks["hbm_gbps"], 1)
        if peaks.get("vpu_gops"):
            out["pct_of_compute_peak"] = round(
                100.0 * out["achieved_gflops"] / peaks["vpu_gops"], 1)
    return out
