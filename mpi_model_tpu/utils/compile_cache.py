"""Persistent JAX compilation cache (cold-start elimination, slice 1 of
ROADMAP direction 5).

Every process restart recompiles every kernel — for the fused active
path (ISSUE 8) that is two Pallas kernels per (plan, k, dtype) plus the
runner loops, seconds each on a laptop and worse over a remote-compile
tunnel. The JAX persistent compilation cache keys compiled executables
by (HLO, jaxlib version, flags, device kind) and serves them across
processes, so a machine pays each compile ONCE — a restarted service
reaches full throughput on its first batch.

``configure_compile_cache(dir)`` is the ONE place the knobs are set;
the CLI's ``--compile-cache DIR`` flag, ``EnsembleService(
compile_cache=...)`` and ``bench.enable_compile_cache`` all route here.
The bar for entry is dropped to zero compile seconds / any entry size —
on the CPU test rigs even the tiny kernels should populate, which is
what the cross-process test asserts.
"""

from __future__ import annotations

import os
from typing import Optional

#: config knobs to apply: (name, value). Applied best-effort in order —
#: an older jax missing a knob keeps the cache as a plain optimization.
_KNOBS = (
    ("jax_persistent_cache_min_compile_time_secs", 0),
    ("jax_persistent_cache_min_entry_size_bytes", -1),
)

_configured: Optional[str] = None


def configure_compile_cache(cache_dir: Optional[str]) -> Optional[str]:
    """Point the JAX persistent compilation cache at ``cache_dir``
    (created if missing) and lower the entry bars so every compile is
    cached. Returns the directory actually configured, or None when
    ``cache_dir`` is None/empty (explicitly disabled — the caller's
    flag was not set) or the running jax has no cache support.

    Idempotent: reconfiguring with the same directory is a no-op;
    a DIFFERENT directory re-points the cache (jax allows updating the
    config between compiles)."""
    global _configured
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(os.path.expanduser(str(cache_dir)))
    if _configured == cache_dir:
        return cache_dir
    import jax
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (AttributeError, KeyError, ValueError, OSError) as e:
        # the cache is an optimization, never a hard failure — but the
        # caller ASKED for it, so a dir that can't be armed must warn
        # (the CLI's errors-not-silent-no-ops rule), not vanish
        import warnings
        warnings.warn(
            f"persistent compile cache at {cache_dir!r} could not be "
            f"armed ({type(e).__name__}: {e}); every compile will be "
            "paid per process", RuntimeWarning)
        return None
    for name, value in _KNOBS:
        try:
            jax.config.update(name, value)
        except (AttributeError, KeyError, ValueError):
            pass  # older jax without this knob
    # jax memoizes its cache-used decision at the FIRST compile of the
    # process; a process that compiled anything before this call (test
    # rigs, library embedders) would silently keep the cache off —
    # reset so the next compile re-initializes against the new dir
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except (ImportError, AttributeError):
        pass  # older jax: the dir config alone armed it
    _configured = cache_dir
    return cache_dir


def configured_dir() -> Optional[str]:
    """The directory the cache was last pointed at via
    ``configure_compile_cache`` (None = never configured here)."""
    return _configured


def default_cache_dir() -> str:
    """The default persistent-cache directory for ``"auto"``:
    ``$JAX_COMPILATION_CACHE_DIR`` when set, else a PER-USER cache path
    (``$XDG_CACHE_HOME``/``~/.cache`` + ``mpi_model_tpu/jax_cache``).
    Deliberately NOT a world-shared tempdir: the cache deserializes and
    executes compiled artifacts, and a predictable shared path would
    let another local user pre-plant entries (or simply own the
    directory so ours fails to arm) — the bench's opt-in ``/tmp``
    default is its own, explicit, choice."""
    env = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env:
        return env
    base = (os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "mpi_model_tpu", "jax_cache")


def resolve_compile_cache(spec) -> Optional[str]:
    """Map a ``compile_cache`` knob value to a directory: ``"auto"`` →
    ``default_cache_dir()`` (the ISSUE 9 satellite — the persistent
    cache rides under the scheduler's runner cache BY DEFAULT, so a
    restarted service reaches full throughput on its first batch);
    ``None``/empty → disabled; any other string → that directory."""
    if spec == "auto":
        return default_cache_dir()
    return spec or None
