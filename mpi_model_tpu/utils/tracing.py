"""Structured tracing — the observability layer the reference lacks.

The reference's only tracing is ``cout << __FILE__ << ": " << __LINE__``
at unhandled branches and a ``__TIMESTAMP__`` in the output filename
(SURVEY §5: "No timers anywhere — the reference never measures its own
speed"). Here tracing is structured and first-class:

- ``Tracer.span(name)`` — nested, thread-safe wall-clock spans with
  per-thread nesting (one span stack per thread, like a profiler);
- **trace context propagation** (ISSUE 15): every span carries a
  ``trace_id``/``span_id``/``parent_id``; nesting parents automatically
  through a per-thread context stack, ``span(parent=ctx)`` parents
  explicitly (a dispatch span under its ticket's submit span), and
  ``attach(ctx)`` adopts a context that crossed a PROCESS boundary (the
  fleet wire carries ``TraceContext.to_meta()`` in the submit frame, so
  member-side spans parent under the fleet-side submit span);
- ``summary()`` — per-name aggregates (count / total / mean / max /
  p50 / p99 via the shared ``metrics.LatencyReservoir`` percentile
  machinery) plus an explicit ``__tracer__`` entry carrying ``dropped``
  — a truncated trace says so in the artifact, not just on the object;
- ``export_chrome()`` — the Chrome trace-event format, loadable in
  ``chrome://tracing`` / Perfetto alongside XLA's own device traces;
  multi-process merges are labeled via ``process_name`` metadata
  records (``label_process`` — the fleet stamps members m<slot>g<gen>)
  and the export carries a top-level ``dropped`` count;
- ``export_stream()`` (ISSUE 20) — a streaming JSONL sink: every span
  appends to the file AS IT COMPLETES (bounded flush cadence), so a
  killed supervisor's trace survives up to the kill instead of dying
  with the never-written end-of-run export; ``obs.timeline`` accepts
  the ``.jsonl`` file wherever it accepts a Chrome trace;
- ``ingest()`` / ``spans_since()`` — the heartbeat shipping lane:
  a member exports its completed-span deltas as plain dicts
  (wall-clock-anchored, so merged timelines order across processes)
  and the supervisor absorbs them into its own ring;
- ``device_trace()`` — wraps ``jax.profiler.trace`` so host spans and
  the XLA/TPU device profile are captured over the same window (this is
  how BASELINE's halo-exchange share is attributed on real hardware);
- a process-wide default tracer (``get_tracer``/``trace_span``) that the
  framework's own phases report into: ``Model.execute`` emits
  ``model.execute`` / ``executor.run``, the sharded executors emit their
  build-vs-run phases, the serving stack emits per-dispatch
  assemble/launch/fetch and per-wake spans.

Recording one span is two ``perf_counter`` calls, two id formats and a
list append — cheap enough to leave on (the bench's
``tracing_overhead_frac`` field gates the claim with a measured
number); ``Tracer(enabled=False)`` makes it free.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import json
import os
import threading
import time
from typing import Any, Iterator, Optional

__all__ = [
    "Span",
    "TraceContext",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "trace_span",
]

#: process-unique span-id source: ids are ``<pid:x>-<n:x>`` so two
#: processes (a fleet and its spawned members) can never collide —
#: no randomness needed, and ids stay stable/debuggable
_ids = itertools.count(1)


def _new_id() -> str:
    return f"{os.getpid():x}-{next(_ids):x}"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One position in a trace: the trace it belongs to and the span
    that is the current parent. Immutable; crosses thread and process
    boundaries as a two-key dict (``to_meta``/``from_meta`` — the TW1
    wire frames and the journal submit records carry exactly this)."""

    trace_id: str
    span_id: str

    def to_meta(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_meta(cls, meta: Optional[dict]) -> Optional["TraceContext"]:
        """None-safe decode — a frame without trace meta propagates
        nothing (spans then root locally), it never errors."""
        if not isinstance(meta, dict):
            return None
        t, s = meta.get("trace_id"), meta.get("span_id")
        if not (isinstance(t, str) and isinstance(s, str)):
            return None
        return cls(t, s)


@dataclasses.dataclass
class Span:
    """One completed span. ``start_s`` is ``perf_counter``-based and only
    meaningful relative to other spans from the same tracer;
    ``start_wall_s`` is the wall-clock anchor (``time.time`` epoch
    seconds) that lets spans from DIFFERENT processes merge into one
    ordered timeline."""

    name: str
    start_s: float
    duration_s: float
    thread: int
    depth: int
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)
    #: trace-context identity (ISSUE 15); None on spans recorded by a
    #: pre-context tracer dict (ingest tolerates their absence)
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None
    #: wall-clock anchor of start_s (epoch seconds)
    start_wall_s: Optional[float] = None
    #: recording process (spans ingested from a member keep theirs)
    pid: int = 0
    #: monotone per-tracer append index — the heartbeat delta cursor
    seq: int = 0

    def to_dict(self) -> dict:
        """The wire/export projection (plain JSON-able dict)."""
        return {
            "name": self.name, "start_s": self.start_s,
            "duration_s": self.duration_s, "thread": self.thread,
            "depth": self.depth, "meta": dict(self.meta),
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_wall_s": self.start_wall_s, "pid": self.pid,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(
            name=d.get("name", "?"), start_s=float(d.get("start_s", 0.0)),
            duration_s=float(d.get("duration_s", 0.0)),
            thread=int(d.get("thread", 0)), depth=int(d.get("depth", 0)),
            meta=dict(d.get("meta") or {}), trace_id=d.get("trace_id"),
            span_id=d.get("span_id"), parent_id=d.get("parent_id"),
            start_wall_s=d.get("start_wall_s"),
            pid=int(d.get("pid", 0)))


class Tracer:
    """Thread-safe span recorder with per-thread nesting and trace
    contexts.

    The buffer is a ring of at most ``max_spans`` (oldest dropped first,
    ``dropped`` counts them) so the always-on default tracer stays
    bounded over arbitrarily long runs."""

    def __init__(self, enabled: bool = True, max_spans: int = 20_000):
        self.enabled = enabled
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=int(max_spans))
        self.dropped = 0
        self._lock = threading.Lock()
        self._local = threading.local()
        self._pid = os.getpid()
        #: wall-clock anchor: start_wall_s = start_s + _wall_off (one
        #: pair of clock reads at construction, not two reads per span)
        self._wall_off = time.time() - time.perf_counter()
        self._seq = 0
        #: pid → human label for export_chrome's process_name metadata
        #: (the fleet labels members m<slot>g<gen> at heartbeat ingest)
        self._process_labels: dict[int, str] = {}
        #: streaming JSONL sink (ISSUE 20): (open file, path, spans
        #: written since the last flush) — see export_stream
        self._stream = None
        self._stream_path: Optional[str] = None
        self._stream_pending = 0

    # -- trace context ------------------------------------------------------

    def _ctx_stack(self) -> list:
        s = getattr(self._local, "ctx", None)
        if s is None:
            s = []
            # analysis: ignore[unguarded-shared-mutation] — threading.local
            # storage: each thread mutates only its own context stack
            self._local.ctx = s
        return s

    def current(self) -> Optional[TraceContext]:
        """The calling thread's innermost open context (a span in
        progress, or an ``attach``-ed remote parent), or None."""
        s = self._ctx_stack()
        return s[-1] if s else None

    @contextlib.contextmanager
    def attach(self, ctx: Optional[TraceContext]) -> Iterator[None]:
        """Adopt a context from elsewhere (another thread or — via the
        wire's ``trace`` meta — another process) as the calling
        thread's current parent, for the duration of the block. A None
        context is a no-op, so call sites need no branching."""
        if ctx is None:
            yield
            return
        stack = self._ctx_stack()
        stack.append(ctx)
        try:
            yield
        finally:
            stack.pop()

    # -- recording ----------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[TraceContext] = None,
             **meta: Any) -> Iterator[dict]:
        """Record one span around the block; yields the (mutable) meta
        dict so values learned inside the block (an allocated ticket
        id) still land on the completed span. ``parent`` overrides the
        thread's current context (the cross-ticket case: a dispatch
        span parenting under ITS ticket's submit span, not under
        whatever the pump thread happens to have open)."""
        if not self.enabled:
            yield meta
            return
        depth = getattr(self._local, "depth", 0)
        # analysis: ignore[unguarded-shared-mutation] — threading.local
        # storage: each thread mutates only its own depth slot
        self._local.depth = depth + 1
        p = parent if parent is not None else self.current()
        trace_id = p.trace_id if p is not None else _new_id()
        span_id = _new_id()
        stack = self._ctx_stack()
        stack.append(TraceContext(trace_id, span_id))
        t0 = time.perf_counter()
        try:
            yield meta
        finally:
            dt = time.perf_counter() - t0
            stack.pop()
            # analysis: ignore[unguarded-shared-mutation] — threading.local
            # storage: each thread mutates only its own depth slot
            self._local.depth = depth
            s = Span(name=name, start_s=t0, duration_s=dt,
                     thread=threading.get_ident(), depth=depth,
                     meta=dict(meta), trace_id=trace_id, span_id=span_id,
                     parent_id=(p.span_id if p is not None else None),
                     start_wall_s=t0 + self._wall_off, pid=self._pid)
            self._append(s)

    def instant(self, name: str, **meta: Any) -> None:
        """Record a zero-duration marker (the structured version of the
        reference's ``__FILE__:__LINE__`` couts). Parents under the
        thread's current context like a nested span would."""
        if not self.enabled:
            return
        p = self.current()
        t0 = time.perf_counter()
        s = Span(name=name, start_s=t0, duration_s=0.0,
                 thread=threading.get_ident(),
                 depth=getattr(self._local, "depth", 0), meta=dict(meta),
                 trace_id=(p.trace_id if p is not None else _new_id()),
                 span_id=_new_id(),
                 parent_id=(p.span_id if p is not None else None),
                 start_wall_s=t0 + self._wall_off, pid=self._pid)
        self._append(s)

    #: flush the streaming sink every N spans — bounded data-at-risk
    #: (a kill loses at most this many buffered spans) without paying
    #: a syscall per span on the dispatch hot path
    _STREAM_FLUSH_EVERY = 32

    def _append(self, s: Span) -> None:
        with self._lock:
            self._seq += 1
            s.seq = self._seq
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(s)
            if self._stream is not None:
                self._stream_write_locked(s)

    def _stream_write_locked(self, s: Span) -> None:
        try:
            # default=repr: a span's meta may hold anything; the sink
            # must never make recording a span raise at the call site.
            # analysis: ignore[blocking-under-lock] — a buffered
            # ~200-byte write into the libc FILE buffer (no syscall
            # except at the bounded flush below); serializing it under
            # the tracer lock is what keeps the JSONL lines whole when
            # many threads complete spans at once
            self._stream.write(
                json.dumps(s.to_dict(), default=repr) + "\n")
            self._stream_pending += 1
            if self._stream_pending >= self._STREAM_FLUSH_EVERY:
                # analysis: ignore[blocking-under-lock] — the bounded
                # flush cadence: one syscall per _STREAM_FLUSH_EVERY
                # spans, the documented data-at-risk/latency trade
                self._stream.flush()
                self._stream_pending = 0
        except (OSError, ValueError) as e:
            # a dead sink (full disk, closed fd) detaches — tracing
            # continues into the ring; the loss is loud, once
            import warnings

            try:
                self._stream.close()
            except OSError:
                pass
            self._stream = None
            self._stream_path = None
            warnings.warn(
                f"span stream sink failed and was detached: {e}",
                RuntimeWarning)

    # -- cross-process shipping ---------------------------------------------

    def spans_since(self, cursor: int) -> tuple[int, list[dict]]:
        """``(new_cursor, span dicts)`` appended after ``cursor`` — the
        heartbeat telemetry delta a member ships to its supervisor.
        Spans that aged out of the ring before being shipped are simply
        gone (the ring bounds memory; ``dropped`` counts them)."""
        out: list[Span] = []
        with self._lock:
            cur = self._seq
            for s in reversed(self._spans):
                if s.seq <= cursor:
                    break
                out.append(s)
        return cur, [s.to_dict() for s in reversed(out)]

    def ingest(self, span_dicts: list, label: Optional[str] = None
               ) -> int:
        """Absorb spans recorded by ANOTHER process (heartbeat
        telemetry / a fence's final cut) into this ring; returns how
        many were absorbed. Spans stamped with THIS process's pid are
        skipped — the loopback member transport shares the process
        tracer, and shipping its spans over the socketpair must not
        duplicate them. ``label`` names the sending process for
        ``export_chrome``'s process metadata (m<slot>g<gen>)."""
        n = 0
        pids: set = set()
        for d in span_dicts or ():
            s = Span.from_dict(d)
            if s.pid == self._pid:
                continue
            pids.add(s.pid)
            self._append(s)
            n += 1
        if label is not None and pids:
            # one label write per DISTINCT pid per call, not one lock
            # round-trip per span — this runs on every heartbeat
            with self._lock:
                for p in pids:
                    self._process_labels[p] = label
        return n

    def label_process(self, label: str, pid: Optional[int] = None) -> None:
        """Name a pid in chrome exports (``process_name`` metadata)."""
        with self._lock:
            self._process_labels[self._pid if pid is None else pid] = label

    # -- inspection ---------------------------------------------------------

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def summary(self) -> dict[str, dict[str, float]]:
        """Per-name aggregates: count, total_s, mean_s, max_s, p50_s,
        p99_s (percentiles via the shared ``metrics.LatencyReservoir``
        discipline — the per-stage rollup the telemetry plane
        publishes). The reserved ``__tracer__`` entry carries
        ``dropped``/``recorded`` so a truncated trace is explicit in
        every artifact built from this summary."""
        from .metrics import LatencyReservoir

        out: dict[str, dict[str, float]] = {}
        durs: dict[str, list[float]] = {}
        spans = self.spans
        for s in spans:
            agg = out.setdefault(
                s.name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += s.duration_s
            agg["max_s"] = max(agg["max_s"], s.duration_s)
            durs.setdefault(s.name, []).append(s.duration_s)
        for name, agg in out.items():
            agg["mean_s"] = agg["total_s"] / agg["count"]
            d = sorted(durs[name])
            agg["p50_s"] = LatencyReservoir.percentile_of(d, 0.50)
            agg["p99_s"] = LatencyReservoir.percentile_of(d, 0.99)
        out["__tracer__"] = {"dropped": self.dropped,
                             "recorded": len(spans)}
        return out

    # -- export -------------------------------------------------------------

    def chrome_events(self) -> list[dict]:
        """Spans as Chrome trace-event ``X`` (complete) events, µs.
        Timestamps use the wall-clock anchor when present, so spans
        ingested from member processes land on one merged timeline."""
        events = []
        with self._lock:
            # ingest() mutates the label map under the lock from the
            # heartbeat thread — the copy must be under it too
            labels = dict(self._process_labels)
        pids = set()
        for s in self.spans:
            ts = (s.start_wall_s if s.start_wall_s is not None
                  else s.start_s)
            pids.add(s.pid)
            args = dict(s.meta)
            if s.trace_id is not None:
                args.update({"trace_id": s.trace_id, "span_id": s.span_id,
                             "parent_id": s.parent_id})
            events.append({
                "name": s.name,
                "ph": "X",
                "ts": ts * 1e6,
                "dur": s.duration_s * 1e6,
                "pid": s.pid or 1,
                "tid": s.thread,
                "args": args,
            })
        # process metadata records: a merged multi-process trace must
        # label members m<slot>g<gen>, not bare pids (ISSUE 15)
        for pid in sorted(pids):
            name = labels.get(pid)
            if name is None:
                name = ("fleet" if pid == self._pid else f"pid-{pid}")
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid or 1, "args": {"name": name}})
        return events

    def export_stream(self, path: str) -> str:
        """Attach a streaming JSONL sink (ISSUE 20): every span
        COMPLETED from now on appends to ``path`` as one JSON line
        (the ``Span.to_dict`` projection) the moment it lands in the
        ring — unlike ``export_chrome``, which writes nothing until
        the run survives to its end. Flushes every
        ``_STREAM_FLUSH_EVERY`` spans (bounded data-at-risk, no
        syscall per span); ``close_stream()`` flushes the tail and
        detaches. Append-mode: re-attaching after a takeover continues
        the same file. A later ``export_stream`` replaces the sink."""
        f = open(path, "a")
        try:
            # the previous writer may have been KILLED mid-line (the
            # sink's whole point): appending straight after its torn
            # tail would garble the first new span — start it on a
            # fresh line instead (the reader skips the torn fragment)
            with open(path, "rb") as rf:
                rf.seek(0, os.SEEK_END)
                if rf.tell() > 0:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        f.write("\n")
        except OSError:  # pragma: no cover - best effort
            pass
        with self._lock:
            old, self._stream = self._stream, f
            self._stream_path = path
            self._stream_pending = 0
        if old is not None:
            try:
                old.flush()
                old.close()
            except OSError:  # pragma: no cover - best effort
                pass
        return path

    def close_stream(self) -> Optional[str]:
        """Flush + detach the streaming sink; returns its path (None
        when no sink was attached). The ring keeps recording."""
        with self._lock:
            f, self._stream = self._stream, None
            path, self._stream_path = self._stream_path, None
            self._stream_pending = 0
        if f is None:
            return None
        try:
            f.flush()
            f.close()
        except OSError:  # pragma: no cover - best effort
            pass
        return path

    def export_chrome(self, path: str) -> str:
        """Write the trace as a ``chrome://tracing``/Perfetto JSON file.
        The document carries the ring's ``dropped`` count at top level:
        a truncated trace must say so in the artifact itself."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_events(),
                       "displayTimeUnit": "ms",
                       "dropped": self.dropped}, f)
        return path

    # -- device profiling ----------------------------------------------------

    @contextlib.contextmanager
    def device_trace(self, logdir: str, name: str = "device_trace"
                     ) -> Iterator[None]:
        """Capture an XLA device profile (``jax.profiler.trace``) over the
        block, alongside a host span of the same name — so host phases
        can be lined up against compiled-program device time (the way
        BASELINE's halo-exchange wallclock share is attributed on real
        hardware)."""
        import jax

        with self.span(name, logdir=logdir):
            with jax.profiler.trace(logdir):
                yield


# -- process-wide default tracer ---------------------------------------------

_default = Tracer(enabled=True)
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _default


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-wide tracer (e.g. a disabled one); returns the
    previous tracer."""
    global _default
    with _default_lock:
        prev, _default = _default, tracer
    return prev


def trace_span(name: str, **meta: Any):
    """``get_tracer().span(...)`` resolved at call time (so a tracer
    swapped in mid-process is honored)."""
    return get_tracer().span(name, **meta)
