"""Structured tracing — the observability layer the reference lacks.

The reference's only tracing is ``cout << __FILE__ << ": " << __LINE__``
at unhandled branches and a ``__TIMESTAMP__`` in the output filename
(SURVEY §5: "No timers anywhere — the reference never measures its own
speed"). Here tracing is structured and first-class:

- ``Tracer.span(name)`` — nested, thread-safe wall-clock spans with
  per-thread nesting (one span stack per thread, like a profiler);
- ``summary()`` — per-name aggregates (count / total / mean / max);
- ``export_chrome()`` — the Chrome trace-event format, loadable in
  ``chrome://tracing`` / Perfetto alongside XLA's own device traces;
- ``device_trace()`` — wraps ``jax.profiler.trace`` so host spans and
  the XLA/TPU device profile are captured over the same window (this is
  how BASELINE's halo-exchange share is attributed on real hardware);
- a process-wide default tracer (``get_tracer``/``trace_span``) that the
  framework's own phases report into: ``Model.execute`` emits
  ``model.execute`` / ``executor.run``, the sharded executors emit their
  build-vs-run phases.

Recording one span is two ``perf_counter`` calls and a list append —
cheap enough to leave on; ``Tracer(enabled=False)`` makes it free.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import threading
import time
from typing import Any, Iterator, Optional

__all__ = [
    "Span",
    "Tracer",
    "get_tracer",
    "set_tracer",
    "trace_span",
]


@dataclasses.dataclass
class Span:
    """One completed span. ``start_s`` is ``perf_counter``-based and only
    meaningful relative to other spans from the same tracer."""

    name: str
    start_s: float
    duration_s: float
    thread: int
    depth: int
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)


class Tracer:
    """Thread-safe span recorder with per-thread nesting.

    The buffer is a ring of at most ``max_spans`` (oldest dropped first,
    ``dropped`` counts them) so the always-on default tracer stays
    bounded over arbitrarily long runs."""

    def __init__(self, enabled: bool = True, max_spans: int = 20_000):
        self.enabled = enabled
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=int(max_spans))
        self.dropped = 0
        self._lock = threading.Lock()
        self._local = threading.local()

    # -- recording ----------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **meta: Any) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        depth = getattr(self._local, "depth", 0)
        # analysis: ignore[unguarded-shared-mutation] — threading.local
        # storage: each thread mutates only its own depth slot
        self._local.depth = depth + 1
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            # analysis: ignore[unguarded-shared-mutation] — threading.local
            # storage: each thread mutates only its own depth slot
            self._local.depth = depth
            s = Span(name=name, start_s=t0, duration_s=dt,
                     thread=threading.get_ident(), depth=depth,
                     meta=dict(meta))
            self._append(s)

    def instant(self, name: str, **meta: Any) -> None:
        """Record a zero-duration marker (the structured version of the
        reference's ``__FILE__:__LINE__`` couts)."""
        if not self.enabled:
            return
        s = Span(name=name, start_s=time.perf_counter(), duration_s=0.0,
                 thread=threading.get_ident(),
                 depth=getattr(self._local, "depth", 0), meta=dict(meta))
        self._append(s)

    def _append(self, s: Span) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(s)

    # -- inspection ---------------------------------------------------------

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def summary(self) -> dict[str, dict[str, float]]:
        """Per-name aggregates: count, total_s, mean_s, max_s."""
        out: dict[str, dict[str, float]] = {}
        for s in self.spans:
            agg = out.setdefault(
                s.name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += s.duration_s
            agg["max_s"] = max(agg["max_s"], s.duration_s)
        for agg in out.values():
            agg["mean_s"] = agg["total_s"] / agg["count"]
        return out

    # -- export -------------------------------------------------------------

    def chrome_events(self) -> list[dict]:
        """Spans as Chrome trace-event ``X`` (complete) events, µs."""
        return [
            {
                "name": s.name,
                "ph": "X",
                "ts": s.start_s * 1e6,
                "dur": s.duration_s * 1e6,
                "pid": 1,
                "tid": s.thread,
                "args": s.meta,
            }
            for s in self.spans
        ]

    def export_chrome(self, path: str) -> str:
        """Write the trace as a ``chrome://tracing``/Perfetto JSON file."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_events(),
                       "displayTimeUnit": "ms"}, f)
        return path

    # -- device profiling ----------------------------------------------------

    @contextlib.contextmanager
    def device_trace(self, logdir: str, name: str = "device_trace"
                     ) -> Iterator[None]:
        """Capture an XLA device profile (``jax.profiler.trace``) over the
        block, alongside a host span of the same name — so host phases
        can be lined up against compiled-program device time (the way
        BASELINE's halo-exchange wallclock share is attributed on real
        hardware)."""
        import jax

        with self.span(name, logdir=logdir):
            with jax.profiler.trace(logdir):
                yield


# -- process-wide default tracer ---------------------------------------------

_default = Tracer(enabled=True)
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _default


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-wide tracer (e.g. a disabled one); returns the
    previous tracer."""
    global _default
    with _default_lock:
        prev, _default = _default, tracer
    return prev


def trace_span(name: str, **meta: Any):
    """``get_tracer().span(...)`` resolved at call time (so a tracer
    swapped in mid-process is honored)."""
    return get_tracer().span(name, **meta)
