"""Timing/metrics helpers shared by bench.py and benchmarks/ladder.py.

The reference never measures its own speed (SURVEY §5: no timers
anywhere), so the framework carries its own instrumentation. The core
primitive is MARGINAL step timing: the remote-TPU tunnel adds ~100ms of
fixed dispatch overhead per call, so per-step cost is measured as
``(t(s2) - t(s1)) / (s2 - s1)`` between two scan lengths, with completion
forced by an on-device reduction fetched to host (``block_until_ready``
alone does not block through the tunnel).
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Optional

from ..resilience import lockdep

Values = dict


def marginal_step_time(step: Callable, values: Values, s1: int = 50,
                       s2: int = 250, reps: int = 2,
                       donate: bool = True) -> float:
    """Seconds per step of ``step`` (a Values→Values function), measured
    marginally between scan lengths ``s1`` and ``s2`` with donated carry
    buffers (SURVEY §7.6) and best-of-``reps`` timing."""
    import jax
    import jax.numpy as jnp

    times = {}
    for steps in (s1, s2):
        def run_fn(v, _steps=steps):
            def body(c, _):
                return step(c), None
            out, _ = jax.lax.scan(body, v, None, length=_steps)
            # force real completion through the tunnel: tiny reduction
            # fetched to host after the scan
            return out, jnp.sum(
                jax.tree.leaves(out)[0].astype(jnp.float32))
        # donation consumes the input, so each rep runs on a fresh
        # on-device copy made outside the timed region
        run = jax.jit(run_fn, donate_argnums=0 if donate else ())
        fresh = jax.tree.map(jnp.copy, values)
        out, s = run(fresh)
        _ = float(s)  # warmup / compile
        best = float("inf")
        for _ in range(reps):
            fresh = jax.tree.map(jnp.copy, values)
            t0 = time.perf_counter()
            out, s = run(fresh)
            _ = float(s)
            best = min(best, time.perf_counter() - t0)
        times[steps] = best
    return (times[s2] - times[s1]) / (s2 - s1)


def _scan_runners(step: Callable, values: Values, lengths: tuple,
                  donate: bool = True) -> dict:
    """Build and WARM one donated-scan runner per scan length (compile
    happens here, never inside a timed region): length → jitted
    ``values -> (out, scalar)``; fetching the scalar forces completion
    through the tunnel."""
    import jax
    import jax.numpy as jnp

    runners = {}
    for steps in lengths:
        def run_fn(v, _steps=steps):
            def body(c, _):
                return step(c), None
            out, _ = jax.lax.scan(body, v, None, length=_steps)
            return out, jnp.sum(
                jax.tree.leaves(out)[0].astype(jnp.float32))
        run = jax.jit(run_fn, donate_argnums=0 if donate else ())
        fresh = jax.tree.map(jnp.copy, values)
        _, s = run(fresh)
        _ = float(s)  # warmup / compile
        runners[steps] = run
    return runners


def _marginal_sample(runners: dict, values: Values, s1: int,
                     s2: int) -> float:
    """One marginal per-step estimate from pre-warmed runners: both
    scan lengths timed back-to-back so chip-state drift hits the two
    arms of the estimate together."""
    import time as _time

    import jax
    import jax.numpy as jnp

    ts = {}
    for steps in (s1, s2):
        fresh = jax.tree.map(jnp.copy, values)
        t0 = _time.perf_counter()
        _, s = runners[steps](fresh)
        _ = float(s)
        ts[steps] = _time.perf_counter() - t0
    return (ts[s2] - ts[s1]) / (s2 - s1)


def marginal_step_trials(step: Callable, values: Values, s1: int = 10,
                         s2: int = 60, trials: int = 5,
                         donate: bool = True) -> list[float]:
    """``trials`` independent marginal per-step estimates (seconds).

    The two scan lengths are timed back-to-back WITHIN each trial, so
    chip-state drift on the shared tunnel chip hits both arms of one
    marginal estimate together; the runners are built and warmed once
    (one compile), then every trial is pure timing. Callers take the
    MEDIAN and report the min/max spread — BASELINE.md's noise
    discipline ("interleaved medians are not optional"), now applied to
    the driver headline too (round-4 VERDICT weak #1)."""
    runners = _scan_runners(step, values, (s1, s2), donate)
    return [_marginal_sample(runners, values, s1, s2)
            for _ in range(trials)]


def marginal_runner_trials(make_output: Callable[[int], object],
                           s1: int = 10, s2: int = 40,
                           trials: int = 3) -> list[float]:
    """``trials`` marginal per-step estimates for an arbitrary runner
    (``make_output(num_steps)`` must block until the work is done): the
    runner-shaped counterpart of ``marginal_step_trials``, with the same
    back-to-back-within-a-trial discipline. Call ``make_output(s1)``
    once yourself first if warmup/compile must not pollute trial 1 —
    this function times every call it makes."""
    import time as _time

    out: list[float] = []
    for _ in range(trials):
        ts = {}
        for steps in (s1, s2):
            t0 = _time.perf_counter()
            make_output(steps)
            ts[steps] = _time.perf_counter() - t0
        out.append((ts[s2] - ts[s1]) / (s2 - s1))
    return out


def interleaved_ab(steps: dict, values: Values, *, s1: int = 5,
                   s2: int = 25, reps: int = 4,
                   spread: bool = False) -> dict:
    """Interleaved A/B medians: one marginal sample per arm per round,
    arms alternating so chip-state drift on the shared tunnel chip hits
    every arm of a round together (BASELINE.md's noise discipline —
    speedup claims are only made when they survive interleaving).

    EVERY arm's two scan-length runners are built and warmed up front
    (one compile per arm per length, the same once-only protocol as
    ``marginal_step_trials``) — the rounds are then pure timing, so
    ``reps`` can be raised to settle a claim without re-paying ``reps``
    jit compilations per arm (the round-5 harness re-jitted both scan
    lengths every round, which both wasted minutes and let compile-side
    state leak into the later rounds' timings).

    ``steps`` maps arm name → step function; returns arm name → median
    marginal seconds per step call, or — with ``spread=True`` — arm
    name → ``{value, spread_lo, spread_hi}`` so callers can test
    whether an A/B gap clears the cross-round spread."""
    runners = {name: _scan_runners(step, values, (s1, s2))
               for name, step in steps.items()}
    times: dict = {name: [] for name in steps}
    for _ in range(reps):
        for name in steps:
            times[name].append(
                _marginal_sample(runners[name], values, s1, s2))
    if spread:
        return {name: median_spread(ts) for name, ts in times.items()}
    import statistics

    return {name: statistics.median(ts) for name, ts in times.items()}


def positive_spread(samples: list[float], scale: float) -> dict:
    """{lo, hi} of ``scale / t`` over the POSITIVE samples — a noise
    transient can make an individual marginal estimate non-positive,
    and such samples carry no spread information (a negative per-step
    time inverts into a negative throughput bound). Null fields when
    none survive. The one implementation behind every cups/scenarios-
    per-second spread the bench and ladder publish."""
    pos = [s for s in samples if s > 0]
    return {"lo": scale / max(pos) if pos else None,
            "hi": scale / min(pos) if pos else None}


def median_spread(samples: list[float]) -> dict:
    """{value: median, spread_lo: min, spread_hi: max} of the samples —
    the shape BENCH/ladder rows report so successive rounds don't read
    tunnel noise as regressions."""
    import statistics

    return {"value": statistics.median(samples),
            "spread_lo": min(samples), "spread_hi": max(samples)}


#: latency samples kept for the percentile fields — bounded so a
#: long-lived service cannot grow the reservoir forever (at 64Ki samples
#: the p50/p99 of the RECENT traffic is what the snapshot reports, which
#: is what an operator watching a live service wants anyway)
LATENCY_RESERVOIR = 65536


class LatencyReservoir:
    """THE bounded percentile reservoir (ISSUE 15 satellite): one
    implementation behind the queue-latency and wake-latency fields
    that used to be two copy-pasted deque+sort blocks, and behind the
    tracer's per-stage span rollups.

    Bounded (the most recent ``maxlen`` samples — what an operator
    watching a live service wants anyway) and locked with its own LEAF
    lock (a plain ``threading.Lock``; nothing is ever acquired under
    it, and callers holding their own locks read percentiles BEFORE
    taking them, so the reservoir adds no acquisition-graph edges)."""

    def __init__(self, maxlen: int = LATENCY_RESERVOIR):
        import threading

        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque(
            maxlen=int(maxlen))

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(float(seconds))

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    @staticmethod
    def percentile_of(sorted_samples: list, q: float):
        """Nearest-rank percentile over an already-sorted list (None
        when empty) — the one percentile definition every p50/p99 the
        package publishes shares."""
        if not sorted_samples:
            return None
        i = min(int(round(q * (len(sorted_samples) - 1))),
                len(sorted_samples) - 1)
        return sorted_samples[i]

    def snapshot(self, prefix: str = "latency") -> dict:
        """One consistent percentile cut:
        ``{<prefix>_n, <prefix>_p50_s, <prefix>_p99_s}``."""
        with self._lock:
            samples = sorted(self._samples)
        return {
            f"{prefix}_n": len(samples),
            f"{prefix}_p50_s": self.percentile_of(samples, 0.50),
            f"{prefix}_p99_s": self.percentile_of(samples, 0.99),
        }


class ThroughputCounter:
    """Monotonic serving counters for the ensemble engine (scheduler /
    service): scenarios served, dispatches, dispatched lanes (incl.
    bucket padding), busy wall seconds, runner-cache hits.

    THREAD-SAFE (ISSUE 9 satellite): the async serving loop mutates
    these counters from the dispatch thread while clients read
    ``snapshot()`` (and bump shed counters) from their own threads, so
    every mutation goes through a method that takes the single internal
    lock, and ``snapshot()`` is taken under the same lock — the returned
    dict is one consistent cut, never a torn read (e.g. ``scenarios``
    from before a dispatch with ``busy_s`` from after it). Counters are
    never written by attribute assignment from outside; use
    ``record_dispatch`` / ``record_latency`` / ``bump``.

    ``snapshot()`` derives the serving metrics the bench/CLI publish:
    ``scenarios_per_s`` (scenarios / busy seconds — DISPATCH wall only,
    so queueing latency from a max-wait policy is not billed as
    compute), ``batch_occupancy`` (real lanes / dispatched lanes — how
    much of each padded bucket did real work),
    ``compile_cache_hit_rate`` (dispatches that reused a built runner)
    and the queue-latency percentiles ``latency_p50_s``/``latency_p99_s``
    (submit-to-served by the scheduler's clock, over the most recent
    ``LATENCY_RESERVOIR`` served scenarios).

    The self-healing counters (ISSUE 5) make recovery observable, never
    silent: ``solo_retries`` (failed scenarios re-dispatched alone),
    ``recovered_failures`` (scenarios whose solo retry succeeded — the
    fault was the batch's, not theirs), ``quarantined`` (scenarios whose
    solo retry failed too — deterministic scenario faults, isolated with
    their ``FailureEvent``) and ``impl_faults`` (whole-dispatch failures
    feeding the degradation ladder). ISSUE 9 adds the overload/deadline
    ledger: ``shed`` (submissions refused at admission —
    ``ServiceOverloaded``) and ``expired`` (tickets whose deadline
    passed before dispatch — resolved as ``TicketExpired`` with a
    complete ``FailureEvent``, never silently dropped).
    """

    #: the integer counters bump() accepts — a typo'd name must fail
    #: loudly, not silently count into a new attribute nothing reads
    COUNTERS = ("dispatches", "scenarios", "lanes", "cache_hits",
                "solo_retries", "recovered_failures", "quarantined",
                "impl_faults", "shed", "expired", "loop_faults",
                "member_faults", "readmitted", "scale_ups", "scale_downs",
                "respawns", "heartbeats", "heartbeat_misses",
                "wire_errors", "hibernations", "rehibernations",
                "wakes", "wake_faults", "supervisor_kills",
                "stale_epoch_rejections")

    def __init__(self):
        # lockdep factory (ISSUE 12): plain Lock disarmed, witnessed
        # when the order witness is armed — the counter lock is a LEAF
        # of the static acquisition graph (bump/snapshot call nothing)
        self._lock = lockdep.lock("ThroughputCounter._lock")
        self.dispatches = 0
        self.scenarios = 0
        self.lanes = 0
        self.busy_s = 0.0
        #: launch-to-complete span per dispatch, summed — the time a
        #: dispatch was OUTSTANDING (device had work in flight). Under
        #: the async loop this exceeds busy_s (which bills only the
        #: host-observed launch+fetch segments): inflight_s/wall is the
        #: serving occupancy metric; busy_s feeds scenarios_per_s.
        #: Synchronously the two coincide.
        self.inflight_s = 0.0
        self.cache_hits = 0
        self.solo_retries = 0
        self.recovered_failures = 0
        self.quarantined = 0
        self.impl_faults = 0
        #: submissions refused at admission (bounded queue / health gate)
        self.shed = 0
        #: tickets whose per-ticket deadline passed before dispatch
        self.expired = 0
        #: dispatch-loop iterations that raised and were supervised
        #: (the loop stays alive; the fault is counted, never silent)
        self.loop_faults = 0
        #: fleet members fenced (dead pump / wedge / ladder bottom) —
        #: each carries a kind="member" FailureEvent (ISSUE 10)
        self.member_faults = 0
        #: tickets re-admitted to a healthy member after their member
        #: was fenced or a crash-restart recovery replayed the journal
        self.readmitted = 0
        #: autoscaling actions (fleet supervisor)
        self.scale_ups = 0
        self.scale_downs = 0
        #: ISSUE 13 (multi-process fleet): members respawned in place
        #: (fence → gen+1), heartbeat RPCs sent / missed, and wire
        #: failures classified as member faults
        self.respawns = 0
        self.heartbeats = 0
        self.heartbeat_misses = 0
        self.wire_errors = 0
        #: ISSUE 14 (scenario tiering): scenarios paged to the
        #: hibernation tier (rehibernations = the subset that had
        #: already hibernated once — their chain writes are deltas),
        #: scenarios woken back to residency, and wakes that could not
        #: restore their chain (fell back to the journal or resolved
        #: as a HibernationError — never a silent fresh start)
        self.hibernations = 0
        self.rehibernations = 0
        self.wakes = 0
        self.wake_faults = 0
        #: ISSUE 20 (supervisor failover): injected supervisor kills
        #: (the ``supervisor_kill`` chaos seam turning this supervisor
        #: into a zombie) and journal appends the epoch fence refused
        #: because a standby had already taken the stream over
        self.supervisor_kills = 0
        self.stale_epoch_rejections = 0
        #: the queue-latency and wake-latency reservoirs share ONE
        #: implementation (ISSUE 15 satellite): bounded, self-locked
        #: LatencyReservoir — wake latency is the wall seconds each
        #: wake spent materializing its scenario (chain restore +
        #: resubmit), the paging cost a client actually observes
        self._latencies = LatencyReservoir()
        self._wake_latencies = LatencyReservoir()

    def record_dispatch(self, scenarios: int, bucket: int, wall_s: float,
                        cache_hit: bool,
                        inflight_s: Optional[float] = None) -> None:
        with self._lock:
            self.dispatches += 1
            self.scenarios += int(scenarios)
            self.lanes += int(bucket)
            self.busy_s += float(wall_s)
            self.inflight_s += float(wall_s if inflight_s is None
                                     else inflight_s)
            if cache_hit:
                self.cache_hits += 1

    def bump(self, name: str, n: int = 1) -> None:
        """Increment one named counter under the lock — the ONLY
        sanctioned way to mutate a counter from outside (attribute
        ``+=`` from another thread is a lost-update race)."""
        if name not in self.COUNTERS:
            raise ValueError(
                f"unknown counter {name!r} (expected one of "
                f"{self.COUNTERS})")
        with self._lock:
            setattr(self, name, getattr(self, name) + int(n))

    def busy_per_scenario(self) -> Optional[float]:
        """busy seconds per served scenario (None before any serve) —
        a two-read O(1) accessor for hot paths (admission's retry-after
        estimate) that must not pay ``snapshot()``'s reservoir sort."""
        with self._lock:
            return self.busy_s / self.scenarios if self.scenarios else None

    def record_latency(self, seconds: float) -> None:
        """One served scenario's submit-to-served latency (scheduler
        clock), feeding the p50/p99 snapshot fields. The reservoir
        carries its own leaf lock — the counter lock is not taken."""
        self._latencies.record(seconds)

    def record_wake_latency(self, seconds: float) -> None:
        """One wake's wall seconds (hibernation-chain restore through
        resubmission — ``time.perf_counter`` spans, real even under a
        fake scheduler clock), feeding the ``wake_latency_p50_s``/
        ``wake_latency_p99_s`` snapshot fields."""
        self._wake_latencies.record(seconds)

    def snapshot(self) -> dict:
        # percentile cuts are read BEFORE the counter lock: the
        # reservoirs are their own (leaf-) locked objects, so taking
        # them under the counter lock would add an acquisition edge
        # for no atomicity gain (a latency sample racing a counter
        # bump was never one transaction to begin with)
        lat = self._latencies.snapshot("latency")
        wlat = self._wake_latencies.snapshot("wake_latency")
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "scenarios": self.scenarios,
                "scenarios_per_s": (self.scenarios / self.busy_s
                                    if self.busy_s > 0 else None),
                "batch_occupancy": (self.scenarios / self.lanes
                                    if self.lanes else None),
                "compile_cache_hits": self.cache_hits,
                "compile_cache_hit_rate": (self.cache_hits / self.dispatches
                                           if self.dispatches else None),
                "busy_s": self.busy_s,
                "inflight_s": self.inflight_s,
                "solo_retries": self.solo_retries,
                "recovered_failures": self.recovered_failures,
                "quarantined": self.quarantined,
                "impl_faults": self.impl_faults,
                "shed": self.shed,
                "expired": self.expired,
                "loop_faults": self.loop_faults,
                "member_faults": self.member_faults,
                "readmitted": self.readmitted,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "respawns": self.respawns,
                "heartbeats": self.heartbeats,
                "heartbeat_misses": self.heartbeat_misses,
                "wire_errors": self.wire_errors,
                "hibernations": self.hibernations,
                "rehibernations": self.rehibernations,
                "wakes": self.wakes,
                "wake_faults": self.wake_faults,
                "supervisor_kills": self.supervisor_kills,
                "stale_epoch_rejections": self.stale_epoch_rejections,
                **lat,
                **wlat,
            }


def marginal_runner_time(make_output: Callable[[int], object],
                         s1: int = 10, s2: int = 50,
                         reps: int = 2) -> float:
    """Marginal per-step seconds for an arbitrary runner: calls
    ``make_output(num_steps)`` (which must block until the work is truly
    done and may be a subprocess run) at two step counts."""
    times = {}
    for steps in (s1, s2):
        make_output(steps)  # warmup / compile / page-in
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            make_output(steps)
            best = min(best, time.perf_counter() - t0)
        times[steps] = best
    return (times[s2] - times[s1]) / (s2 - s1)
