"""Per-shard (scalable) checkpoint layout: O(shard) host memory, not O(grid).

The dense format (``io.checkpoint``) gathers every channel to every host
and lets process 0 write one ``.npz`` — fine on one host, O(grid) host
memory and DCN traffic per checkpoint at scale. The reference itself
writes per-rank files and merges afterwards
(``/root/reference/src/Model.hpp:246-260`` — per-rank was the right
idea); this module is that idea done properly for sharded ``jax.Array``s:

- a checkpoint is a DIRECTORY: ``shards_p{proc:05d}.npz`` written by each
  process holding only its addressable, replica-0 device shards (raw
  little-endian bytes + a JSON piece table), plus a ``manifest.json``
  written LAST by process 0 — manifest presence marks the checkpoint
  complete, so a crash mid-save never yields a readable-but-partial
  checkpoint;
- no gather anywhere on the save path: every process touches only the
  bytes it already owns (dedup across replicas via ``Shard.replica_id``);
- restore is assembly: without a mesh, the pieces concatenate into full
  host arrays (the master merge); WITH a mesh + ``PartitionSpec``s, each
  process reads only the pieces overlapping its own addressable shards
  via ``jax.make_array_from_callback`` — restore is O(shard) too.

Interoperates with ``CheckpointManager`` (``layout="sharded"``) and hence
with ``run_checkpointed`` / ``resilience.supervised_run``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import zlib
from typing import Any, Mapping, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.cellular_space import CellularSpace
from ..resilience import inject
from .checkpoint import Checkpoint, CheckpointCorruptionError

SHARDED_FORMAT_VERSION = 1
MANIFEST = "manifest.json"


def _atomic_write(path: str, write_fn) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _shard_file(proc: int) -> str:
    return f"shards_p{proc:05d}.npz"


@dataclasses.dataclass
class StagedShardSave:
    """A per-process shard save with the DEVICE→HOST copy done but the
    file not yet written: ``write()`` (any thread) makes this process's
    shard file durable; ``commit_checkpoint_sharded`` (main thread, all
    processes) then publishes the manifest. Splitting save this way is
    what makes async checkpointing possible — the write overlaps the
    next compute chunk, and the manifest stays a true commit record."""

    path: str
    manifest: dict
    _payload: dict
    _proc: int

    def write(self) -> None:
        target = os.path.join(self.path, _shard_file(self._proc))
        _atomic_write(target, lambda f: np.savez(f, **self._payload))
        # chaos seam (resilience.inject): an armed "torn" fault damages
        # this process's just-written shard file — the per-piece CRC32s
        # and latest()'s verified fallback are what it exercises
        inject.checkpoint_torn(target, int(self.manifest["step"]))


def stage_checkpoint_sharded(path: str, space: CellularSpace, step: int = 0,
                             extra: Optional[dict] = None) -> StagedShardSave:
    """Phase 1 of a sharded save: retract any stale manifest (collective)
    and snapshot this process's replica-0 shards to host memory. No file
    I/O on the grid data yet."""
    from ..parallel.multihost import master_only, process_count, process_index

    proc = process_index()
    nprocs = process_count()
    os.makedirs(path, exist_ok=True)

    # re-saving into an existing checkpoint: retract the commit record
    # BEFORE touching any shard file, or a crash mid-rewrite would leave
    # a stale manifest pointing at mixed old/new shards. Shard files
    # from a previous save with a LARGER process_count would survive
    # unreferenced forever (round-4 ADVICE) — the master clears any not
    # in the new file list while the manifest is down.
    new_files = {_shard_file(p) for p in range(nprocs)}
    with master_only("sharded-ckpt-retract") as master:
        if master:
            if os.path.exists(os.path.join(path, MANIFEST)):
                os.unlink(os.path.join(path, MANIFEST))
            for fn in os.listdir(path):
                if (fn.startswith("shards_p") and fn.endswith(".npz")
                        and fn not in new_files):
                    os.unlink(os.path.join(path, fn))

    pieces: list[dict] = []
    payload: dict[str, np.ndarray] = {}
    channels: dict[str, dict] = {}
    for name, arr in space.values.items():
        if not hasattr(arr, "addressable_shards"):
            arr = jnp.asarray(arr)
        channels[name] = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one device in the cluster writes each piece
            starts, shape = [], []
            for sl, dim in zip(shard.index, arr.shape):
                lo, hi, _ = sl.indices(dim)
                starts.append(lo)
                shape.append(hi - lo)
            data = np.ascontiguousarray(shard.data)
            key = f"d:{len(pieces)}"
            raw = data.reshape(-1).view(np.uint8)
            # per-piece CRC32 (the dense format's per-array checksum at
            # shard granularity): restore verifies each piece it reads
            pieces.append({"channel": name, "start": starts, "shape": shape,
                           "key": key,
                           "crc32": zlib.crc32(raw) & 0xFFFFFFFF})
            payload[key] = raw
    payload["meta"] = np.frombuffer(
        json.dumps({"pieces": pieces}).encode("utf-8"), dtype=np.uint8)
    manifest = {
        "format": SHARDED_FORMAT_VERSION,
        "layout": "sharded",
        "step": int(step),
        "dim_x": space.dim_x,
        "dim_y": space.dim_y,
        "x_init": space.x_init,
        "y_init": space.y_init,
        "global_dim_x": space.global_dim_x,
        "global_dim_y": space.global_dim_y,
        "channels": channels,
        "extra": extra or {},
        "process_count": nprocs,
        "files": [_shard_file(p) for p in range(nprocs)],
    }
    return StagedShardSave(path=path, manifest=manifest, _payload=payload,
                           _proc=proc)


def commit_checkpoint_sharded(staged: StagedShardSave) -> str:
    """Phase 2 (main thread, every process, AFTER ``staged.write()``
    returned): barrier proving all shard files durable, then the master
    publishes the manifest — the commit record."""
    from ..parallel.multihost import master_only, sync

    sync("sharded-ckpt-shards")
    with master_only("sharded-ckpt-manifest") as master:
        if master:
            mpath = os.path.join(staged.path, MANIFEST)
            _atomic_write(
                mpath,
                lambda f: f.write(
                    json.dumps(staged.manifest, indent=1).encode()))
            # chaos seam: a "torn" fault with channel="manifest" damages
            # the commit record itself (an unreadable manifest = an
            # incomplete checkpoint; resume must fall back past it)
            inject.checkpoint_torn(mpath, int(staged.manifest["step"]),
                                   part="manifest")
    return staged.path


def save_checkpoint_sharded(path: str, space: CellularSpace, step: int = 0,
                            extra: Optional[dict] = None) -> str:
    """Write ``space`` as a sharded checkpoint directory at ``path``.

    Every process writes exactly one file containing its replica-0
    addressable shards — no cross-host traffic, no full-grid gather
    (contrast ``save_checkpoint``, which funnels O(grid) bytes to every
    host). Process 0 writes the manifest after a barrier proves all
    shard files are durable. Assumes (like the dense format's restore)
    a filesystem every process sees. (= stage → write → commit in one
    synchronous call; ``CheckpointManager(async_writes=True)`` overlaps
    the write with compute instead.)
    """
    staged = stage_checkpoint_sharded(path, space, step, extra)
    err: Optional[BaseException] = None
    try:
        staged.write()
    # analysis: ignore[broad-except] — vote boundary: a bare raise here
    # strands peer ranks mid-commit; the failure becomes this rank's
    # vote and every rank raises together
    except BaseException as e:
        err = e
    vote_writes_or_raise(err, step)
    return commit_checkpoint_sharded(staged)


def vote_writes_or_raise(err: Optional[BaseException],
                         step: Optional[int] = None) -> None:
    """Collective vote that every process's shard write succeeded; on
    any failure EVERY process raises here together (the local error
    where there is one). The commit barrier must only be entered when
    ALL can commit — one process raising while the rest sit in ``sync``
    would strand them until the cluster heartbeat kills the job."""
    from ..parallel.multihost import all_agree

    if all_agree(err is None):
        return
    if err is not None:
        raise err
    which = f"step {step}" if step is not None else "the step"
    raise RuntimeError(
        "a peer process failed to write its checkpoint shard; "
        f"{which} was not committed")


class _ShardFileReader:
    """Lazy reader over one per-process shard file: piece table up front,
    piece bytes only when an overlap demands them (``np.load`` keeps zip
    members unread until indexed)."""

    def __init__(self, path: str):
        import zipfile

        self.path = path
        try:
            self._z = np.load(path)
            self.pieces = json.loads(
                bytes(self._z["meta"]).decode("utf-8"))["pieces"]
        except (zipfile.BadZipFile, EOFError, KeyError, OSError,
                ValueError) as e:
            # a torn shard file is corruption, typed so latest() can
            # fall back to the previous verified step
            raise CheckpointCorruptionError(
                f"shard file {path} is torn/unreadable: "
                f"{type(e).__name__}: {e}") from e

    def read(self, piece: dict, dtype) -> np.ndarray:
        raw = self._z[piece["key"]]
        want = piece.get("crc32")
        if want is not None and (zlib.crc32(raw) & 0xFFFFFFFF) != want:
            raise CheckpointCorruptionError(
                f"piece {piece['key']} (channel {piece['channel']!r}) in "
                f"{self.path} fails its CRC32 (bytes changed since the "
                "shard was written)")
        return raw.view(dtype).reshape(piece["shape"])

    def close(self) -> None:
        self._z.close()


def _assemble(readers: list[_ShardFileReader], channel: str, dtype,
              region_start: tuple[int, ...], region_shape: tuple[int, ...],
              ) -> np.ndarray:
    """Fill one requested region of ``channel`` from overlapping pieces;
    incomplete coverage (corrupt/mismatched checkpoint) is an error, not
    silent zeros."""
    out = np.empty(region_shape, dtype=dtype)
    covered = np.zeros(region_shape, dtype=bool)
    for rd in readers:
        for piece in rd.pieces:
            if piece["channel"] != channel:
                continue
            # overlap of piece box and requested region, in region coords
            src_sel, dst_sel = [], []
            empty = False
            for ps, pn, rs, rn in zip(piece["start"], piece["shape"],
                                      region_start, region_shape):
                lo, hi = max(ps, rs), min(ps + pn, rs + rn)
                if lo >= hi:
                    empty = True
                    break
                src_sel.append(slice(lo - ps, hi - ps))
                dst_sel.append(slice(lo - rs, hi - rs))
            if empty:
                continue
            data = rd.read(piece, dtype)
            out[tuple(dst_sel)] = data[tuple(src_sel)]
            covered[tuple(dst_sel)] = True
    if not covered.all():
        # incomplete coverage = a corrupt/mismatched checkpoint, typed
        # so latest() falls back (subclasses ValueError — callers that
        # caught the old type still do)
        raise CheckpointCorruptionError(
            f"sharded checkpoint does not cover channel {channel!r} region "
            f"start={region_start} shape={region_shape} "
            f"({int(covered.sum())}/{covered.size} cells present)")
    return out


def load_checkpoint_sharded(
    path: str,
    *,
    mesh=None,
    spec: Union[None, Any, Mapping[str, Any]] = None,
) -> Checkpoint:
    """Restore a sharded checkpoint directory.

    Without ``mesh``: assemble full host arrays (the reference's master
    merge, ``Model.hpp:110-131``) — O(grid), single-host use.

    With ``mesh`` (+ optional ``spec``: one ``PartitionSpec`` for every
    channel or a per-channel mapping; default shards the leading array
    dims over ``mesh.axis_names``): each process builds global sharded
    arrays via ``jax.make_array_from_callback``, reading ONLY the pieces
    overlapping its own addressable shards — O(shard) restore.
    """
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        raise FileNotFoundError(
            f"no {MANIFEST} in {path}: not a (complete) sharded checkpoint")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        raise CheckpointCorruptionError(
            f"manifest in {path} is torn/unreadable: "
            f"{type(e).__name__}: {e}") from e
    if manifest.get("format") != SHARDED_FORMAT_VERSION:
        raise ValueError(
            f"unsupported sharded checkpoint format "
            f"{manifest.get('format')!r} in {path}")

    readers = [_ShardFileReader(os.path.join(path, fn))
               for fn in manifest["files"]]
    try:
        values: dict[str, jax.Array] = {}
        for name, ch in manifest["channels"].items():
            dtype = jnp.dtype(ch["dtype"])
            shape = tuple(ch["shape"])
            if mesh is None:
                full = _assemble(readers, name, dtype,
                                 (0,) * len(shape), shape)
                values[name] = jnp.asarray(full)
                continue
            from jax.sharding import NamedSharding, PartitionSpec as P

            if isinstance(spec, Mapping):
                ch_spec = spec[name]
            elif spec is not None:
                ch_spec = spec
            else:
                ch_spec = P(*mesh.axis_names[:len(shape)])
            sharding = NamedSharding(mesh, ch_spec)

            def cb(index, _name=name, _dtype=dtype, _shape=shape):
                starts, sub = [], []
                for sl, dim in zip(index, _shape):
                    lo, hi, _ = sl.indices(dim)
                    starts.append(lo)
                    sub.append(hi - lo)
                return _assemble(readers, _name, _dtype,
                                 tuple(starts), tuple(sub))

            values[name] = jax.make_array_from_callback(shape, sharding, cb)
    finally:
        for rd in readers:
            rd.close()

    space = CellularSpace(
        values, manifest["dim_x"], manifest["dim_y"],
        manifest["x_init"], manifest["y_init"],
        manifest["global_dim_x"], manifest["global_dim_y"])
    return Checkpoint(space=space, step=manifest["step"],
                      extra=manifest["extra"])


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, MANIFEST))
