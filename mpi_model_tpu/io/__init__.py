from .checkpoint import (
    Checkpoint,
    CheckpointManager,
    load_checkpoint,
    run_checkpointed,
    save_checkpoint,
)
from .sharded import (
    commit_checkpoint_sharded,
    is_sharded_checkpoint,
    load_checkpoint_sharded,
    save_checkpoint_sharded,
    stage_checkpoint_sharded,
)
from .delta import (
    DeltaChain,
    MigrationError,
    MigrationResult,
    migrate_scenario,
    transfer_space,
)
from .output import (
    merge_dumps,
    output_filename,
    partition_dump_lines,
    write_output,
    write_partition_dump,
)

__all__ = [
    "Checkpoint",
    "CheckpointManager",
    "save_checkpoint",
    "load_checkpoint",
    "run_checkpointed",
    "save_checkpoint_sharded",
    "load_checkpoint_sharded",
    "is_sharded_checkpoint",
    "stage_checkpoint_sharded",
    "commit_checkpoint_sharded",
    "DeltaChain",
    "MigrationError",
    "MigrationResult",
    "migrate_scenario",
    "transfer_space",
    "partition_dump_lines",
    "write_partition_dump",
    "merge_dumps",
    "output_filename",
    "write_output",
]
