"""Output subsystem: per-partition state dumps + master merge (Python path).

Rebuild of the reference's end-of-run output protocol: each worker writes
its partition as ``x<TAB>y<TAB>value`` lines to ``comm_rank{r}.txt``
(``/root/reference/src/Model.hpp:246-260``) and the master concatenates
them rank-by-rank into ``output <timestamp>.txt``
(``Model.hpp:100-131``). TPU-native differences:

- the "workers" are partitions of a (possibly sharded) global array —
  ``gather_to_host`` is the process-0 gather, ``slice_partition`` the
  per-rank view, so the same code serves serial, sharded and multi-host
  runs;
- coordinates in the dump are GLOBAL (the reference's cells store global
  x/y, ``Model.hpp:154-157``), fixing nothing and omitting nothing: the
  merged file covers every cell exactly once, in rank-major then
  row-major order, byte-comparable across execution strategies;
- the value format defaults to C++ ``operator<<`` 6-significant-digit
  style for eyeball parity with the reference's files; pass
  ``fmt="{:.17g}"`` for round-trip-exact dumps.
"""

from __future__ import annotations

import datetime as _dt
import os
import shutil
from typing import Iterable, Optional

import jax
import numpy as np

from ..core.cellular_space import (
    CellularSpace,
    DEFAULT_ATTR,
    Partition,
    row_partitions,
)
from ..parallel.collectives import gather_to_host


def partition_dump_lines(space: CellularSpace, attr: str = DEFAULT_ATTR,
                         fmt: str = "{:.6g}") -> Iterable[str]:
    """Row-major ``x<TAB>y<TAB>value`` lines with global coordinates (the
    reference's per-cell dump loop, ``Model.hpp:252-256``)."""
    # Per-RANK dump: the space here is host-local (a partition slice, or a
    # single-process grid) — a plain device_get, NOT the cross-process
    # gather (which would concatenate every rank's data and corrupt the
    # per-rank files). write_output performs the global gather once.
    vals = np.asarray(jax.device_get(space.values[attr]))
    for lx in range(space.dim_x):
        x = space.x_init + lx
        row = vals[lx]
        for ly in range(space.dim_y):
            yield f"{x}\t{space.y_init + ly}\t{fmt.format(float(row[ly]))}"


def write_partition_dump(directory: str, space: CellularSpace, rank: int,
                         attr: str = DEFAULT_ATTR,
                         fmt: str = "{:.6g}") -> str:
    """One worker's ``comm_rank{r}.txt`` (``Model.hpp:249-257``)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"comm_rank{rank}.txt")
    with open(path, "w") as f:
        for line in partition_dump_lines(space, attr, fmt):
            f.write(line + "\n")
    return path


def merge_dumps(out_path: str, dump_paths: Iterable[str]) -> str:
    """Master merge: concatenate worker dumps in rank order into one file
    (``Model.hpp:110-131``)."""
    d = os.path.dirname(os.path.abspath(out_path)) or "."
    os.makedirs(d, exist_ok=True)
    with open(out_path, "wb") as out:
        for p in dump_paths:
            with open(p, "rb") as f:
                shutil.copyfileobj(f, out)  # streamed: rank dumps can be GBs
    return out_path


def output_filename(timestamp: Optional[str] = None) -> str:
    """``output <timestamp>.txt`` — the reference stamps the merge with
    ``__TIMESTAMP__`` (``Model.hpp:104``); we stamp with wall time."""
    ts = timestamp or _dt.datetime.now().strftime("%a %b %d %H:%M:%S %Y")
    return f"output {ts}.txt"


def write_output(directory: str, space: CellularSpace,
                 partitions: Optional[list[Partition]] = None,
                 comm_size: int = 1, attr: str = DEFAULT_ATTR,
                 fmt: str = "{:.6g}",
                 timestamp: Optional[str] = None) -> str:
    """Full output pipeline on the Python/TPU path: per-partition dumps +
    merged master file; returns the merged file's path.

    ``partitions`` defaults to the reference's 1-D row striping over
    ``comm_size`` ranks (``Model.hpp:62-76``); the master itself holds no
    cells there, so ranks here are the data-holding workers only.
    """
    from ..parallel.multihost import broadcast_str, master_only

    if partitions is None:
        partitions = row_partitions(space.dim_x, space.dim_y, comm_size)
    # one global gather (multi-host safe; every process participates),
    # then ONLY process 0 writes — the reference's master role — with all
    # processes barriered even if the master's write fails. The filename
    # is the MASTER's (wall-clock stamps would skew across hosts and
    # leave workers returning a path that doesn't exist).
    host_space = space.with_values(
        {k: gather_to_host(v) for k, v in space.values.items()})
    out_path = os.path.join(
        directory, broadcast_str(output_filename(timestamp)))
    with master_only("output-write") as master:
        if master:
            dumps = [
                write_partition_dump(directory,
                                     host_space.slice_partition(p),
                                     p.rank, attr, fmt)
                for p in partitions
            ]
            merge_dumps(out_path, dumps)
    return out_path
