"""Incremental (dirty-tile) checkpointing + the live-migration stream.

The full-grid layouts make every snapshot cost O(grid): at the bench
geometry (16384² f32) that is a ~1 GB write per checkpoint interval —
the dominant cost of a tight-interval supervised run, and the reason an
operator turns the PR 5 safety net off. The active-tile engine (PR 3)
already knows which tiles a run wrote; this module turns that into a
**delta chain**:

- a chain is a sequence of records in the manager's directory: periodic
  full **keyframes** (``{prefix}_{step:010d}.kf.npz``) and per-interval
  **delta records** (``{prefix}_{step:010d}.d.npz``) holding only the
  DIRTY tiles — each record a piece table + raw payload in the PR 5
  sharded-manifest shape (``{channel, start, shape, key, crc32}`` per
  piece; a keyframe's pieces cover each channel whole, a delta's pieces
  are the dirty tiles);
- dirtiness comes from the active engine's dirty-tile export
  (``SerialExecutor.last_dirty_tiles`` — the union of tiles the run
  wrote, a guaranteed superset of changed tiles) when the caller can
  vouch for one, else from ``ops.active.changed_tile_map`` — a
  byte-level tile diff against the last saved state (always correct,
  costs one vectorized compare over the grid);
- ``{prefix}_chain.json`` is the chain manifest — the COMMIT record
  (the sharded layout's manifest discipline): records are linked by
  ``base`` step, and a record not in the manifest does not exist;
- restore REPLAYS: load the nearest keyframe at-or-before the target,
  apply each delta in order, verifying every piece CRC32 and every
  base link. A torn, CRC-failing or missing record makes the restore
  raise ``CheckpointCorruptionError`` — ``CheckpointManager.latest()``
  then falls back to the previous step, which truncates the chain at
  the last record that VERIFIES. A missing/unreadable chain manifest
  degrades the chain to its self-contained keyframes (with a warning)
  — never a silent fresh start, never a silently stale delta.

The same record format is the **live-migration stream**:
``migrate_scenario`` hands a running scenario between executors
(serial ↔ sharded) by snapshotting a keyframe, letting the source keep
stepping while the bulk copy is "in flight", then shipping only the
dirty-tile delta at cutover and resuming on the target after a
bitwise verification — the rebalancing primitive that doesn't stop the
world. ``transfer_space`` is the one-shot (keyframe-only) form the
ensemble scheduler's ``migrate_ticket`` uses to drain a queued
scenario onto another scheduler through the same CRC-verified wire
format.

Checkpoints are host-side like the dense layout: channels are gathered
with the multihost-safe global gather, only process 0 writes, and the
chain writer's in-memory last-saved state makes the tile diff local.
"""

from __future__ import annotations

import dataclasses
import json
import os
import warnings
import zlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.cellular_space import CellularSpace
from ..ops.active import ActivePlan, changed_tile_map, plan_for
from ..resilience import inject
from .checkpoint import Checkpoint, CheckpointCorruptionError
from .sharded import _atomic_write

DELTA_FORMAT_VERSION = 1
SUFFIX_KEYFRAME = ".kf.npz"
SUFFIX_DELTA = ".d.npz"


class MigrationError(RuntimeError):
    """A live-migration handoff failed its bitwise verification: the
    state materialized on the target does not reproduce the source's
    byte for byte (a dirty map that missed a changed tile, or a payload
    corrupted in flight). The source's state is untouched — the caller
    keeps running there."""


# -- piece encoding (the PR 5 sharded piece table, tile-grained) --------------

def _geom_meta(space) -> dict:
    return {
        "dim_x": space.dim_x, "dim_y": space.dim_y,
        "x_init": space.x_init, "y_init": space.y_init,
        "global_dim_x": space.global_dim_x,
        "global_dim_y": space.global_dim_y,
    }


def _channels_meta(values: dict) -> dict:
    return {k: {"dtype": str(v.dtype), "shape": list(v.shape)}
            for k, v in values.items()}


def _piece(channel: str, start, shape, raw: np.ndarray, key: str) -> dict:
    return {"channel": channel, "start": list(start), "shape": list(shape),
            "key": key, "crc32": zlib.crc32(raw) & 0xFFFFFFFF}


def _full_pieces(values: dict) -> tuple[list, dict]:
    """One piece per channel covering it whole — a keyframe's table."""
    pieces, payload = [], {}
    for name, arr in values.items():
        raw = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
        key = f"d:{len(pieces)}"
        pieces.append(_piece(name, (0,) * arr.ndim, arr.shape, raw, key))
        payload[key] = raw
    return pieces, payload


def _tile_pieces(values: dict, plan: ActivePlan,
                 dirty: dict[str, np.ndarray]) -> tuple[list, dict]:
    """Dirty tiles as pieces: ``dirty`` maps channel → bool [gi, gj]
    (a superset of the tiles whose bytes changed)."""
    (th, tw) = plan.tile
    pieces, payload = [], {}
    for name, arr in values.items():
        dmap = dirty[name]
        for ti, tj in zip(*np.nonzero(dmap)):
            r, c = int(ti) * th, int(tj) * tw
            tile = np.ascontiguousarray(arr[r:r + th, c:c + tw])
            raw = tile.reshape(-1).view(np.uint8)
            key = f"d:{len(pieces)}"
            pieces.append(_piece(name, (r, c), (th, tw), raw, key))
            payload[key] = raw
    return pieces, payload


def _apply_pieces(arrays: dict[str, np.ndarray], meta: dict, get_raw,
                  where: str) -> None:
    """Apply a record's pieces onto ``arrays`` in place, verifying every
    piece's CRC32 against the bytes read."""
    for piece in meta["pieces"]:
        ch = piece["channel"]
        dst = arrays.get(ch)
        if dst is None:
            raise CheckpointCorruptionError(
                f"record {where} carries channel {ch!r} the chain's "
                "keyframe does not (channel set changed mid-chain)")
        raw = np.asarray(get_raw(piece["key"])).reshape(-1)
        want = piece.get("crc32")
        if want is not None and (zlib.crc32(raw) & 0xFFFFFFFF) != want:
            raise CheckpointCorruptionError(
                f"piece {piece['key']} (channel {ch!r}) in {where} fails "
                "its CRC32 (bytes changed since the record was written)")
        sel = tuple(slice(s, s + n)
                    for s, n in zip(piece["start"], piece["shape"]))
        try:
            dst[sel] = raw.view(dst.dtype).reshape(piece["shape"])
        except ValueError as e:
            raise CheckpointCorruptionError(
                f"piece {piece['key']} in {where} does not fit channel "
                f"{ch!r}: {e}") from e


def _new_arrays(channels: dict) -> dict[str, np.ndarray]:
    return {name: np.empty(tuple(ch["shape"]), dtype=jnp.dtype(ch["dtype"]))
            for name, ch in channels.items()}


# -- the raw record writer (lint boundary: naked-save covers it) --------------

def write_chain_record(path: str, meta: dict, payload: dict) -> str:
    """Write one chain record file atomically (tmp + replace) and fire
    the chaos seam for its kind. RAW writer — outside ``io``/
    ``resilience`` all writes must flow through ``CheckpointManager``
    (the ``naked-save`` analysis rule enforces this), or the chain
    manifest stops being a commit record."""
    body = dict(payload)
    body["meta"] = np.frombuffer(json.dumps(meta).encode("utf-8"),
                                 dtype=np.uint8)
    _atomic_write(path, lambda f: np.savez(f, **body))
    # chaos seam (resilience.inject): an armed "torn" fault damages the
    # just-committed record — part "keyframe" or "delta" (an unpinned
    # "data" fault matches either)
    inject.checkpoint_torn(path, int(meta["step"]), part=meta["kind"])
    return path


class _RecordReader:
    """One chain record file: meta up front, piece bytes on demand
    (``np.load`` keeps zip members unread until indexed) — the sharded
    layout's lazy reader, chain-record flavored."""

    def __init__(self, path: str):
        import zipfile

        self.path = path
        try:
            self._z = np.load(path)
        except FileNotFoundError:
            # a MISSING chain record is corruption at this layer: the
            # manifest promised it, so the chain is broken here — typed
            # so latest() truncates to the last verified record
            raise CheckpointCorruptionError(
                f"chain record {path} is missing (the chain manifest "
                "references it)")
        except (zipfile.BadZipFile, EOFError, KeyError, OSError,
                ValueError) as e:
            raise CheckpointCorruptionError(
                f"chain record {path} is torn/unreadable: "
                f"{type(e).__name__}: {e}") from e
        try:
            self.meta = json.loads(bytes(self._z["meta"]).decode("utf-8"))
            # analysis: ignore[journal-meta-drift] — this is the delta
            # CHAIN record's meta (the checkpoint codec's vocabulary),
            # not a ticket-journal record; the lifecycle machines do
            # not govern it
            fmt = self.meta.get("format")
            if fmt != DELTA_FORMAT_VERSION:
                raise CheckpointCorruptionError(
                    f"chain record {path} has unsupported format "
                    f"{fmt!r}")
        except CheckpointCorruptionError:
            self._z.close()  # a raising __init__ must not leak the zip
            raise
        except (zipfile.BadZipFile, EOFError, KeyError, OSError,
                ValueError, UnicodeDecodeError) as e:
            self._z.close()
            raise CheckpointCorruptionError(
                f"chain record {path} is torn/unreadable: "
                f"{type(e).__name__}: {e}") from e

    def raw(self, key: str) -> np.ndarray:
        import zipfile

        try:
            return self._z[key]
        except (zipfile.BadZipFile, KeyError, OSError, ValueError,
                EOFError) as e:
            # the zip layer's own member CRC can catch the damage before
            # this format's per-piece CRC32 does; both mean corruption
            raise CheckpointCorruptionError(
                f"piece {key} in {self.path} is unreadable: "
                f"{type(e).__name__}: {e}") from e

    def close(self) -> None:
        self._z.close()


# -- the chain --------------------------------------------------------------

class DeltaChain:
    """One delta-checkpoint chain in a directory (module docstring has
    the format). The in-memory ``_last_values`` snapshot is what makes
    the tile diff local; after a restart it is empty, so the first save
    is a keyframe — the conservative, always-correct restart.
    ``keyframe_every`` bounds a chain segment to that many RECORDS
    (1 keyframe + keyframe_every-1 deltas); 1 makes every save a
    keyframe (≈ the dense layout with chain bookkeeping)."""

    def __init__(self, directory: str, prefix: str = "ckpt",
                 keyframe_every: int = 8,
                 tile: Optional[tuple[int, int]] = None):
        self.directory = directory
        self.prefix = prefix
        self.keyframe_every = max(1, int(keyframe_every))
        #: tile dims for delta records (None → ops.active.plan_for's
        #: default, 128²-preferred divisors — the active engine's grid)
        self.tile = tile
        self._last_values: Optional[dict[str, np.ndarray]] = None
        self._last_step: Optional[int] = None
        #: (manifest stat signature, steps) — see steps()
        self._steps_cache: Optional[tuple] = None

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, f"{self.prefix}_chain.json")

    def record_path(self, step: int, kind: str) -> str:
        suffix = SUFFIX_KEYFRAME if kind == "keyframe" else SUFFIX_DELTA
        return os.path.join(self.directory,
                            f"{self.prefix}_{step:010d}{suffix}")

    def _manifest(self) -> tuple[Optional[list], Optional[str]]:
        """(records, error): records is None when the manifest is
        missing; error carries the unreadable-manifest detail (records
        None too) — the degraded keyframes-only mode."""
        try:
            with open(self.manifest_path) as f:
                doc = json.load(f)
            return list(doc["records"]), None
        except FileNotFoundError:
            return None, None
        except (json.JSONDecodeError, UnicodeDecodeError, KeyError,
                TypeError, OSError) as e:
            return None, f"{type(e).__name__}: {e}"

    def _write_manifest(self, records: list) -> None:
        doc = {"format": DELTA_FORMAT_VERSION, "prefix": self.prefix,
               "keyframe_every": self.keyframe_every, "records": records}
        _atomic_write(self.manifest_path,
                      lambda f: f.write(json.dumps(doc, indent=1).encode()))

    # -- save ---------------------------------------------------------------

    def save(self, space: CellularSpace, step: int,
             extra: Optional[dict] = None,
             dirty_tiles: Optional[dict] = None) -> str:
        """Append one record for ``step``: a keyframe on the first save,
        at the ``keyframe_every`` cadence, or whenever the writer cannot
        vouch for a delta (restart, manifest damage, geometry change);
        a dirty-tile delta otherwise. Records at steps >= ``step`` are
        retracted first (a resumed run recomputes them), so the chain
        stays a single timeline.

        ``dirty_tiles`` is the active engine's export
        ({"tile", "grid", "map"}); it is used only when its tile grid
        matches this chain's, else the writer falls back to the byte
        diff against the last saved state."""
        from ..parallel.multihost import gather_global, master_only

        step = int(step)
        values = {k: np.ascontiguousarray(gather_global(v))
                  for k, v in space.values.items()}
        # the tile plan must follow the GATHERED arrays: under
        # jax.distributed space.shape is the local partition, while
        # gather_global returns the global grid — a local-shaped plan
        # would silently regroup the wrong bytes into "tiles"
        gshape = next(iter(values.values())).shape
        plan = plan_for(gshape, tile=self.tile)
        records, _merr = self._manifest()
        if records is None:
            # missing/unreadable manifest: adopt the surviving
            # self-contained keyframes into the rebuilt manifest (each
            # its own one-record segment) — rebuilding from only the
            # new record would let the next prune's orphan sweep delete
            # verified history the degraded mode promised to keep
            records = [
                {"step": s, "kind": "keyframe",
                 "file": os.path.basename(self.record_path(s, "keyframe")),
                 "base": None}
                for s in self._keyframes_on_disk()]
        keep = [r for r in records if r["step"] < step]
        dropped = [r for r in records if r["step"] >= step]
        tail = keep[-1] if keep else None

        prev_ok = (
            tail is not None
            and self._last_step == tail["step"]
            and self._last_values is not None
            and set(self._last_values) == set(values)
            and all(self._last_values[k].shape == values[k].shape
                    and self._last_values[k].dtype == values[k].dtype
                    for k in values))
        since_kf, has_kf = 0, False
        for r in reversed(keep):
            if r["kind"] == "keyframe":
                has_kf = True
                break
            since_kf += 1
        kind = ("delta" if (prev_ok and has_kf
                            and since_kf + 1 < self.keyframe_every)
                else "keyframe")
        if kind == "delta":
            dirty = self._dirty_maps(values, plan, dirty_tiles)
            th, tw = plan.tile
            dbytes = sum(int(dirty[k].sum()) * th * tw * v.dtype.itemsize
                         for k, v in values.items())
            if dbytes >= sum(v.nbytes for v in values.values()):
                # a delta dirtier than the grid costs MORE than a
                # keyframe (per-piece overhead on top of the payload):
                # write the keyframe — which also restarts the segment,
                # so replay chains never grow through dense phases
                kind = "keyframe"

        if kind == "keyframe":
            pieces, payload = _full_pieces(values)
        else:
            pieces, payload = _tile_pieces(values, plan, dirty)
        meta = {
            "format": DELTA_FORMAT_VERSION,
            "kind": kind,
            "step": step,
            "base": tail["step"] if kind == "delta" else None,
            **_geom_meta(space),
            "channels": _channels_meta(values),
            "extra": extra or {},
            "tile": list(plan.tile),
            "pieces": pieces,
        }
        path = self.record_path(step, kind)
        entry = {"step": step, "kind": kind,
                 "file": os.path.basename(path),
                 "base": meta["base"]}
        with master_only("delta-ckpt-save") as master:
            if master:
                os.makedirs(self.directory, exist_ok=True)
                write_chain_record(path, meta, payload)
                self._write_manifest(keep + [entry])
                # chaos seam: a "torn" fault pinned to part "chain"
                # damages the commit record itself
                inject.checkpoint_torn(self.manifest_path, step,
                                       part="chain")
                # retracted records' files and a stale other-kind file
                # at this step are no longer referenced — clear them
                other = self.record_path(
                    step, "delta" if kind == "keyframe" else "keyframe")
                for p in [os.path.join(self.directory, r["file"])
                          for r in dropped] + [other]:
                    if os.path.exists(p) and os.path.abspath(p) \
                            != os.path.abspath(path):
                        os.unlink(p)
        self._last_values = values
        self._last_step = step
        return path

    def _dirty_maps(self, values: dict, plan: ActivePlan,
                    dirty_tiles: Optional[dict]) -> dict:
        """Per-channel dirty maps for a delta record: the supplied
        activity export when its tile grid matches this chain's plan
        (one map for every channel — it is a superset of every write
        the run made), else the byte-level tile diff per channel."""
        if (dirty_tiles is not None
                and tuple(dirty_tiles.get("tile", ())) == plan.tile
                and tuple(dirty_tiles.get("grid", ())) == plan.grid):
            dmap = np.asarray(dirty_tiles["map"], bool)
            return {k: dmap for k in values}
        return {k: changed_tile_map(self._last_values[k], v, plan)
                for k, v in values.items()}

    # -- restore ------------------------------------------------------------

    def _keyframes_on_disk(self) -> list[int]:
        """Steps of the self-contained keyframe files present — the
        degraded (manifest-less) chain view."""
        out = []
        prefix = self.prefix + "_"
        if not os.path.isdir(self.directory):
            return out
        for fn in os.listdir(self.directory):
            if fn.startswith(prefix) and fn.endswith(SUFFIX_KEYFRAME):
                try:
                    out.append(int(fn[len(prefix):-len(SUFFIX_KEYFRAME)]))
                except ValueError:
                    continue
        return sorted(out)

    def steps(self) -> list[int]:
        """Committed (manifested) steps; with the manifest missing or
        unreadable, the self-contained keyframes found on disk. Cached
        against the manifest's stat signature — ``latest()`` probes
        every step through here and must not re-read the manifest per
        probe."""
        sig = None
        try:
            st = os.stat(self.manifest_path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            pass
        if sig is not None and self._steps_cache is not None \
                and self._steps_cache[0] == sig:
            return list(self._steps_cache[1])
        records, _ = self._manifest()
        if records is not None:
            out = sorted(r["step"] for r in records)
            if sig is not None:
                self._steps_cache = (sig, list(out))
            return out
        return self._keyframes_on_disk()

    def has_step(self, step: int) -> bool:
        return int(step) in self.steps()

    def restore(self, step: int) -> Checkpoint:
        """Replay the chain up to ``step``: nearest keyframe at-or-
        before it, then each delta in base-link order, every piece
        CRC-verified. Raises ``CheckpointCorruptionError`` on any torn,
        CRC-failing or missing record in the segment (the manager's
        ``latest()`` then falls back — truncating the chain at the last
        verified record) and ``FileNotFoundError`` for a step the chain
        never committed."""
        step = int(step)
        records, merr = self._manifest()
        if records is None:
            # degraded: only self-contained keyframes can be trusted
            kfp = self.record_path(step, "keyframe")
            if os.path.exists(kfp):
                if merr is not None:
                    warnings.warn(
                        f"chain manifest {self.manifest_path} is "
                        f"unreadable ({merr}); restoring the self-"
                        "contained keyframe without delta replay",
                        RuntimeWarning, stacklevel=3)
                return self._restore_segment(
                    [({"kind": "keyframe", "step": step, "base": None},
                      kfp)])
            if merr is not None:
                raise CheckpointCorruptionError(
                    f"chain manifest {self.manifest_path} is unreadable "
                    f"({merr}) and step {step} is not a keyframe — its "
                    "delta records cannot be validated")
            raise FileNotFoundError(
                f"no delta-chain record for step {step} in "
                f"{self.directory}")
        idx = next((i for i, r in enumerate(records)
                    if r["step"] == step), None)
        if idx is None:
            raise FileNotFoundError(
                f"no delta-chain record for step {step} in "
                f"{self.directory}")
        seg = [records[idx]]
        while seg[0]["kind"] != "keyframe":
            if idx == 0:
                raise CheckpointCorruptionError(
                    f"chain record for step {seg[0]['step']} has no "
                    "keyframe ancestor in the manifest")
            prev = records[idx - 1]
            if seg[0].get("base") != prev["step"]:
                raise CheckpointCorruptionError(
                    f"chain link broken at step {seg[0]['step']}: its "
                    f"base is {seg[0].get('base')} but the previous "
                    f"manifested record is step {prev['step']}")
            idx -= 1
            seg.insert(0, prev)
        return self._restore_segment(
            [(r, os.path.join(self.directory, r["file"])) for r in seg])

    def _restore_segment(self, seg: list) -> Checkpoint:
        arrays: Optional[dict] = None
        meta: Optional[dict] = None
        for rec, path in seg:
            kind = rec["kind"]
            rd = _RecordReader(path)
            try:
                meta = rd.meta
                # EVERY record's identity must match its manifest entry
                # (kind, step, base) — a swapped/mixed-up record file of
                # the right kind would otherwise replay wrong-interval
                # tiles with every per-piece CRC passing
                if (meta["kind"] != kind
                        or int(meta["step"]) != int(rec["step"])
                        or meta.get("base") != rec.get("base")):
                    raise CheckpointCorruptionError(
                        f"chain record {path} does not match its "
                        f"manifest entry (kind/step/base drift: file "
                        f"says {meta['kind']}@{meta['step']} base "
                        f"{meta.get('base')}, manifest says "
                        f"{kind}@{rec['step']} base {rec.get('base')})")
                if kind == "keyframe":
                    arrays = _new_arrays(meta["channels"])
                    covered = {k: False for k in arrays}
                    for piece in meta["pieces"]:
                        if list(piece["shape"]) != list(
                                meta["channels"][piece["channel"]]
                                ["shape"]):
                            raise CheckpointCorruptionError(
                                f"keyframe {path}: piece for channel "
                                f"{piece['channel']!r} does not cover "
                                "it whole")
                        covered[piece["channel"]] = True
                    if not all(covered.values()):
                        raise CheckpointCorruptionError(
                            f"keyframe {path} is missing channel "
                            "pieces: "
                            f"{[k for k, v in covered.items() if not v]}")
                    _apply_pieces(arrays, meta, rd.raw, path)
                else:
                    _apply_pieces(arrays, meta, rd.raw, path)
            finally:
                rd.close()
        values = {k: jnp.asarray(v) for k, v in arrays.items()}
        space = CellularSpace(
            values, meta["dim_x"], meta["dim_y"], meta["x_init"],
            meta["y_init"], meta["global_dim_x"], meta["global_dim_y"])
        # seed the writer: a save right after this restore may continue
        # the chain with a delta instead of forcing a keyframe (save()
        # retracts any records past this step first, so the seed can
        # never describe a different timeline)
        self._last_values = arrays
        self._last_step = int(meta["step"])
        return Checkpoint(space=space, step=int(meta["step"]),
                          extra=meta.get("extra", {}))

    # -- retention ----------------------------------------------------------

    def prune(self, keep: int) -> None:
        """Keep the newest ``keep`` records WITHOUT ever breaking a live
        segment: the cut only lands on a keyframe boundary, so a
        keyframe that retained deltas still replay from is never
        deleted (the cut moves older — retention errs toward keeping
        more, never toward an unrestorable chain)."""
        records, merr = self._manifest()
        if records is None or merr is not None or keep <= 0:
            return
        cut = max(0, len(records) - int(keep))
        while cut > 0 and records[cut]["kind"] != "keyframe":
            cut -= 1
        live = records[cut:]
        if cut > 0:
            self._write_manifest(live)
            for r in records[:cut]:
                p = os.path.join(self.directory, r["file"])
                if os.path.exists(p):
                    os.unlink(p)
        # orphan sweep: record files not referenced by the manifest are
        # retracted/overwritten leftovers
        referenced = {r["file"] for r in live}
        prefix = self.prefix + "_"
        for fn in os.listdir(self.directory):
            if (fn.startswith(prefix)
                    and (fn.endswith(SUFFIX_KEYFRAME)
                         or fn.endswith(SUFFIX_DELTA))
                    and fn not in referenced):
                os.unlink(os.path.join(self.directory, fn))


# -- live migration ----------------------------------------------------------

def _verified_clone(values: dict[str, np.ndarray], where: str
                    ) -> dict[str, np.ndarray]:
    """Round one state through the record wire format (full pieces +
    CRC32 per piece) and return the materialized copy — the CRC-verified
    handoff both migration entry points share."""
    pieces, payload = _full_pieces(values)
    meta = {"channels": _channels_meta(values), "pieces": pieces}
    arrays = _new_arrays(meta["channels"])
    _apply_pieces(arrays, meta, lambda key: payload[key], where)
    return arrays


def transfer_space(space: CellularSpace) -> CellularSpace:
    """One-shot (keyframe-only) handoff of a scenario's state through
    the delta-stream wire format, CRC-verified — what the ensemble
    scheduler's ``migrate_ticket`` drains a queued scenario through."""
    values = {k: np.ascontiguousarray(v) for k, v in space.values.items()}
    arrays = _verified_clone(values, "migration keyframe")
    return dataclasses.replace(
        space, values={k: jnp.asarray(v) for k, v in arrays.items()})


@dataclasses.dataclass
class MigrationResult:
    """What a live handoff produced: the final state (after the
    remaining steps ran on the target), provenance, and the stream
    accounting that makes the 'doesn't stop the world' claim checkable
    — the cutover payload is ``delta_bytes``, not ``keyframe_bytes``."""

    space: CellularSpace
    step: int
    handoff_step: int
    keyframe_bytes: int
    delta_bytes: int
    dirty_tiles: int
    ntiles: int
    report: Optional[object] = None


def migrate_scenario(model, space: CellularSpace, *, source=None,
                     target=None, steps: Optional[int] = None,
                     handoff_at: Optional[int] = None,
                     transfer_steps: int = 0,
                     tile: Optional[tuple[int, int]] = None,
                     verify: bool = True) -> MigrationResult:
    """Move a LIVE scenario from ``source`` to ``target`` executor
    mid-run via the delta stream (serial ↔ sharded, any executor pair
    that steps bitwise-identically).

    Protocol: run ``handoff_at`` steps on the source; snapshot the
    keyframe (the bulk copy — while it is "in flight" the source keeps
    running ``transfer_steps`` more steps); at cutover ship only the
    dirty-tile delta between the source's current state and the
    keyframe; materialize keyframe+delta on the target side (every
    piece CRC-verified) and — with ``verify`` (default) — check the
    materialized state is BITWISE equal to the source's before
    resuming; then run the remaining steps on the target. A mismatch
    raises ``MigrationError`` and the source state is untouched.

    Returns a ``MigrationResult`` whose ``space`` equals an
    uninterrupted ``steps``-step run bitwise (tested for serial ↔
    sharded both ways)."""
    steps = model.num_steps if steps is None else int(steps)
    if handoff_at is None:
        handoff_at = steps // 2
    handoff_at = int(handoff_at)
    transfer_steps = int(transfer_steps)
    if not 0 <= handoff_at <= steps:
        raise ValueError(
            f"handoff_at={handoff_at} outside [0, steps={steps}]")
    if transfer_steps < 0 or handoff_at + transfer_steps > steps:
        raise ValueError(
            f"transfer_steps={transfer_steps} overruns the run: "
            f"handoff_at + transfer_steps must be <= steps={steps}")

    from ..parallel.multihost import gather_global

    def host(sp):
        return {k: np.ascontiguousarray(gather_global(v))
                for k, v in sp.values.items()}

    live = space
    if handoff_at > 0:
        live, _ = model.execute(live, source, steps=handoff_at,
                                check_conservation=False)
    # the bulk copy: keyframe snapshot at the handoff point
    kf_values = host(live)
    kf_pieces, kf_payload = _full_pieces(kf_values)
    keyframe_bytes = sum(p.nbytes for p in kf_payload.values())

    # the source keeps the scenario live while the keyframe transfers
    cutover_step = handoff_at + transfer_steps
    if transfer_steps > 0:
        live, _ = model.execute(live, source, steps=transfer_steps,
                                check_conservation=False)
    cur_values = host(live)

    # cutover: only the tiles that changed while the copy was in flight
    # (plan follows the GATHERED arrays — live.shape is the local
    # partition under jax.distributed, the host() values are global)
    plan = plan_for(next(iter(cur_values.values())).shape, tile=tile)
    dirty = {k: changed_tile_map(kf_values[k], cur_values[k], plan)
             for k in cur_values}
    d_pieces, d_payload = _tile_pieces(cur_values, plan, dirty)
    delta_bytes = sum(p.nbytes for p in d_payload.values())
    ndirty = int(sum(int(m.sum()) for m in dirty.values()))

    # target side: keyframe + delta replay, every piece CRC-verified
    channels = _channels_meta(kf_values)
    arrays = _new_arrays(channels)
    _apply_pieces(arrays, {"channels": channels, "pieces": kf_pieces},
                  lambda key: kf_payload[key], "migration keyframe")
    _apply_pieces(arrays, {"channels": channels, "pieces": d_pieces},
                  lambda key: d_payload[key], "migration delta")
    if verify:
        for k, src in cur_values.items():
            if not np.array_equal(src.view(np.uint8),
                                  arrays[k].view(np.uint8)):
                raise MigrationError(
                    f"migrated state for channel {k!r} is not bitwise "
                    "equal to the source at cutover — handoff aborted, "
                    "the scenario stays on the source")
    tspace = dataclasses.replace(
        live, values={k: jnp.asarray(v) for k, v in arrays.items()})

    remaining = steps - cutover_step
    report = None
    out = tspace
    if remaining > 0:
        out, report = model.execute(tspace, target, steps=remaining,
                                    check_conservation=False)
    return MigrationResult(
        space=out, step=steps, handoff_step=cutover_step,
        keyframe_bytes=keyframe_bytes, delta_bytes=delta_bytes,
        dirty_tiles=ndirty, ntiles=plan.ntiles * len(cur_values),
        report=report)
