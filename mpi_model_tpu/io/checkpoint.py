"""Checkpoint / restore: simulation state to disk and back.

The reference has no checkpointing; its nearest artifact is the
end-of-run per-rank state dump + master merge
(``/root/reference/src/Model.hpp:100-131, 246-260``), which SURVEY §5
names the natural seed for a real design. Here that becomes:

- one self-contained ``.npz`` per checkpoint holding every attribute
  channel as raw little-endian bytes (dtype-safe for bfloat16, which
  plain ``np.savez`` can't store without pickling) plus a JSON metadata
  record (geometry, step counter, user extras, per-channel CRC32);
- atomic writes (tmp + ``os.replace``) so a crash mid-save never
  corrupts the latest checkpoint — and per-array checksums so a
  checkpoint torn/corrupted AFTER the rename (disk fault, chaos
  injection) is DETECTED at restore instead of silently resuming bad
  state: every unreadable or checksum-failing read raises
  ``CheckpointCorruptionError``, and ``CheckpointManager.latest()``
  falls back to the newest checkpoint that VERIFIES;
- ``CheckpointManager`` for periodic save / prune / resume-from-latest;
- ``run_checkpointed`` — the chunked execute loop proving
  resume-equivalence (restart produces bit-identical state).

Checkpoints are host-side by design: state is fetched with the
multihost-safe global gather (``parallel.multihost.gather_global`` — a
plain ``device_get`` single-process, a cross-host allgather under
``jax.distributed``), ONLY process 0 writes (the reference's master
merge), every process barriers on the save, and restore is a plain
``jnp.asarray`` — re-sharding is the executor's job on the next run,
exactly like the reference re-scatters on restart. Multi-host restore
assumes the checkpoint directory is on a filesystem every host sees.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import warnings
import zlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.cellular_space import CellularSpace
from ..resilience import inject

FORMAT_VERSION = 1


class CheckpointCorruptionError(ValueError):
    """A checkpoint failed verification at restore: unreadable (torn
    write, truncated archive) or a channel's bytes no longer match the
    CRC32 recorded when they were written. ``CheckpointManager.latest``
    treats this as "fall back to the previous verified step"; an
    explicit ``restore(step)`` propagates it."""


@dataclasses.dataclass
class Checkpoint:
    """A restored checkpoint: the space, its step counter, user extras."""

    space: CellularSpace
    step: int
    extra: dict


def save_checkpoint(path: str, space: CellularSpace, step: int = 0,
                    extra: Optional[dict] = None) -> str:
    """Serialize ``space`` (+ step counter) to ``path`` atomically.

    Multihost-safe: channels are gathered with the cross-host-aware
    global gather (every process participates), only process 0 writes
    the file, and all processes barrier before returning — so a
    supervised run under ``jax.distributed`` checkpoints exactly once
    per cluster, the way the reference's master merges rank files."""
    from ..parallel.multihost import gather_global, master_only

    meta: dict[str, Any] = {
        "format": FORMAT_VERSION,
        "step": int(step),
        "dim_x": space.dim_x,
        "dim_y": space.dim_y,
        "x_init": space.x_init,
        "y_init": space.y_init,
        "global_dim_x": space.global_dim_x,
        "global_dim_y": space.global_dim_y,
        "channels": {},
        "extra": extra or {},
    }
    payload: dict[str, np.ndarray] = {}
    for name, arr in space.values.items():
        a = np.ascontiguousarray(gather_global(arr))
        raw = a.reshape(-1).view(np.uint8)
        # per-array CRC32: restore verifies bytes against it, so a
        # torn/bit-rotted checkpoint is detected instead of resumed
        meta["channels"][name] = {"dtype": str(a.dtype), "shape": a.shape,
                                  "crc32": zlib.crc32(raw) & 0xFFFFFFFF}
        payload[f"ch:{name}"] = raw
    payload["meta"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8)

    # master_only: every process reaches the barrier even when the
    # master's write fails (a disk error propagates instead of stranding
    # workers in the barrier)
    with master_only("checkpoint-save") as master:
        if master:
            d = os.path.dirname(os.path.abspath(path)) or "."
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez(f, **payload)
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            # chaos seam (resilience.inject): an armed "torn" fault
            # damages the just-committed file — the checksum/fallback
            # machinery below is what it exists to exercise
            inject.checkpoint_torn(path, int(step))
    return path


def load_checkpoint(path: str) -> Checkpoint:
    """Restore a checkpoint written by ``save_checkpoint``; raises
    ``CheckpointCorruptionError`` when the file is unreadable (torn
    write) or any channel fails its recorded checksum."""
    import zipfile

    values = {}
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode("utf-8"))
            fmt = meta.get("format")
            if fmt == FORMAT_VERSION:
                # per channel: read raw bytes, verify, build the array,
                # DROP the bytes — peak host memory stays one channel
                # over the final state, not a second full copy
                for name, ch in meta.get("channels", {}).items():
                    raw = bytes(z[f"ch:{name}"])
                    want = ch.get("crc32")
                    if (want is not None
                            and (zlib.crc32(raw) & 0xFFFFFFFF) != want):
                        raise CheckpointCorruptionError(
                            f"channel {name!r} in {path} fails its "
                            "CRC32 (bytes changed since the checkpoint "
                            "was written)")
                    dtype = jnp.dtype(ch["dtype"])  # resolves bfloat16
                    values[name] = jnp.asarray(np.frombuffer(
                        raw, dtype=dtype).reshape(ch["shape"]))
    except CheckpointCorruptionError:
        raise
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, EOFError, KeyError, OSError,
            ValueError) as e:
        # a torn/truncated archive surfaces as any of these depending on
        # where the damage landed (central directory, a member, the
        # meta json, a short buffer in frombuffer); they all mean the
        # same thing at this boundary
        raise CheckpointCorruptionError(
            f"checkpoint {path} is torn/unreadable: "
            f"{type(e).__name__}: {e}") from e
    if fmt != FORMAT_VERSION:
        raise ValueError(
            f"unsupported checkpoint format {fmt!r} "
            f"in {path} (expected {FORMAT_VERSION})")
    space = CellularSpace(
        values, meta["dim_x"], meta["dim_y"], meta["x_init"], meta["y_init"],
        meta["global_dim_x"], meta["global_dim_y"])
    return Checkpoint(space=space, step=meta["step"], extra=meta["extra"])


class CheckpointManager:
    """Periodic checkpoints in one directory, pruned to the newest ``keep``.

    File layout: ``{prefix}_{step:010d}.npz`` (dense, the default), a
    ``{prefix}_{step:010d}.ckpt`` directory (``layout="sharded"``, the
    O(shard) per-process format — ``io.sharded``), or the incremental
    delta chain (``layout="delta"`` — ``io.delta``: periodic keyframes
    + dirty-tile delta records linked by a chain manifest; restore
    replays the chain, so a snapshot costs O(dirty tiles), not
    O(grid)). The step counter is the checkpoint identity, so
    ``latest()`` is a filename sort, not a mtime race; ``restore``
    auto-detects the layout on disk, so a run can switch layouts and
    still resume.

    ``keyframe_every`` (delta layout) bounds a chain segment to that
    many records (1 keyframe + N-1 deltas); ``delta_tile`` overrides
    the delta records' tile dims (default: the active engine's
    128²-preferred grid).
    """

    def __init__(self, directory: str, keep: int = 3, prefix: str = "ckpt",
                 layout: str = "full", async_writes: bool = False,
                 keyframe_every: int = 8,
                 delta_tile: Optional[tuple] = None):
        if layout not in ("full", "sharded", "delta"):
            raise ValueError(
                f"layout must be 'full', 'sharded' or 'delta': {layout!r}")
        if async_writes and layout != "sharded":
            raise ValueError(
                "async_writes requires layout='sharded' (the staged "
                "write/deferred-manifest protocol is the sharded format's)")
        if keyframe_every < 1:
            raise ValueError(
                f"keyframe_every must be >= 1, got {keyframe_every}")
        self.directory = directory
        self.keep = int(keep)
        self.prefix = prefix
        self.layout = layout
        self.keyframe_every = int(keyframe_every)
        self.delta_tile = delta_tile
        self._chain_obj = None
        #: overlap shard-file writes with the next compute chunk: save()
        #: snapshots device shards to host and returns immediately; a
        #: background thread writes the file and the COMMIT (barrier +
        #: master manifest) happens at the next save()/flush(). The
        #: uncommitted step is invisible to steps()/latest() until then.
        #: Multi-process: every process must make the same save/flush
        #: call sequence (true for supervised_run's SPMD cadence).
        self.async_writes = bool(async_writes)
        self._pending = None  # (thread, err_box, staged)
        os.makedirs(directory, exist_ok=True)

    @property
    def _chain(self):
        """The delta chain bound to this directory/prefix (io.delta) —
        built lazily so non-delta managers never import the module."""
        if self._chain_obj is None:
            from .delta import DeltaChain

            self._chain_obj = DeltaChain(
                self.directory, prefix=self.prefix,
                keyframe_every=self.keyframe_every, tile=self.delta_tile)
        return self._chain_obj

    def path_for(self, step: int, layout: Optional[str] = None) -> str:
        layout = layout or self.layout
        if layout == "delta":
            # advisory: the kind on disk wins (a chain step is a
            # keyframe or a delta record); default to the keyframe name
            dp = self._chain.record_path(step, "delta")
            kp = self._chain.record_path(step, "keyframe")
            return dp if (os.path.exists(dp)
                          and not os.path.exists(kp)) else kp
        suffix = ".ckpt" if layout == "sharded" else ".npz"
        return os.path.join(
            self.directory, f"{self.prefix}_{step:010d}{suffix}")

    def _exists(self, step: int, layout: str) -> bool:
        if layout == "delta":
            return self._chain.has_step(step)
        return os.path.exists(self.path_for(step, layout))

    def _layout_on_disk(self, step: int) -> str:
        """The layout that actually holds ``step`` — preferring the one
        this manager was CONFIGURED with when several exist (a run that
        switched layouts and re-saved the same step leaves the other
        layout's file stale; picking it silently would restore old
        state — round-4 ADVICE)."""
        order = [self.layout] + [ly for ly in ("full", "sharded", "delta")
                                 if ly != self.layout]
        if self.layout != "delta" and not self._scan_files()[1]:
            # chain-free directory: a full/sharded manager never pays
            # the chain's manifest/listdir probe (the lazy contract —
            # latest() calls here once per fallback step)
            order.remove("delta")
        found = [ly for ly in order if self._exists(step, ly)]
        if not found:
            raise FileNotFoundError(
                f"no checkpoint for step {step} in {self.directory}")
        if len(found) > 1:
            warnings.warn(
                f"step {step} exists in BOTH layouts "
                f"({' and '.join(found)}); restoring the manager's "
                f"configured layout {found[0]!r} — the other file may "
                "be stale", stacklevel=3)
        return found[0]

    def _file_steps(self) -> list[int]:
        """Steps present as full/sharded per-step files (the delta
        chain's committed steps are the chain's to report — and to
        prune, since a chain record is never individually deletable)."""
        return self._scan_files()[0]

    def _scan_files(self) -> tuple[list[int], bool]:
        """(full/sharded steps on disk, whether any delta-chain
        artifact was seen) in one directory pass."""
        from .sharded import is_sharded_checkpoint

        out = set()
        saw_chain = os.path.exists(os.path.join(
            self.directory, f"{self.prefix}_chain.json"))
        for fn in os.listdir(self.directory):
            if not fn.startswith(self.prefix + "_"):
                continue
            if fn.endswith(".kf.npz") or fn.endswith(".d.npz"):
                saw_chain = True
                continue
            stem, ext = os.path.splitext(fn)
            if ext not in (".npz", ".ckpt"):
                continue
            if ext == ".ckpt" and not is_sharded_checkpoint(
                    os.path.join(self.directory, fn)):
                # manifest-less = crashed mid-save: not a checkpoint (the
                # commit record is the manifest) — resume must fall back
                # to the previous COMPLETE one, not die on this husk
                continue
            try:
                out.add(int(stem[len(self.prefix) + 1:]))
            except ValueError:
                continue
        return sorted(out), saw_chain

    def steps(self) -> list[int]:
        file_steps, saw_chain = self._scan_files()
        if self.layout != "delta" and not saw_chain:
            # a full/sharded manager in a chain-free directory never
            # pays the chain's manifest read (the lazy contract)
            return file_steps
        return sorted(set(file_steps) | set(self._chain.steps()))

    def save(self, space: CellularSpace, step: int,
             extra: Optional[dict] = None, *,
             dirty_tiles: Optional[dict] = None) -> str:
        """``dirty_tiles`` (delta layout only) is the active engine's
        dirty-tile export for the interval since the LAST save — the
        activity-sourced dirtiness that lets the delta writer skip its
        full-grid diff; other layouts ignore it."""
        if self.layout == "delta":
            path = self._chain.save(space, step, extra,
                                    dirty_tiles=dirty_tiles)
            self._prune(keep_path=path)
            return path
        if self.async_writes:
            import threading

            from .sharded import stage_checkpoint_sharded

            self.flush()  # commit the previous step first
            staged = stage_checkpoint_sharded(
                self.path_for(step), space, step, extra)
            err_box: list = []

            def _write():
                try:
                    staged.write()
                # analysis: ignore[broad-except] — async-writer
                # boundary: the thread must never die silently; every
                # failure is boxed and re-raised at the next drain
                except BaseException as e:
                    err_box.append(e)

            t = threading.Thread(target=_write, daemon=True,
                                 name=f"ckpt-write-{step}")
            t.start()
            self._pending = (t, err_box, staged)
            return staged.path

        if self.layout == "sharded":
            from .sharded import save_checkpoint_sharded

            path = save_checkpoint_sharded(
                self.path_for(step), space, step, extra)
        else:
            path = save_checkpoint(self.path_for(step), space, step, extra)
        self._prune(keep_path=path)
        return path

    def flush(self) -> None:
        """Commit any pending async save: join the writer thread, barrier,
        publish the manifest, prune. No-op when nothing is pending. Call
        at end of run (``supervised_run`` does) or before reading
        ``latest()`` when the newest step must be visible."""
        if self._pending is None:
            return
        t, err_box, staged = self._pending
        self._pending = None
        t.join()
        from .sharded import commit_checkpoint_sharded, vote_writes_or_raise

        # collective vote BEFORE the commit barrier: if any process's
        # write failed, every process raises here together — nobody is
        # stranded in sync waiting for a peer that already raised. The
        # failed step is simply not committed (its dir stays a
        # manifest-less husk the next prune sweeps); resume falls back
        # to the previous durable checkpoint.
        vote_writes_or_raise(err_box[0] if err_box else None,
                             staged.manifest["step"])
        commit_checkpoint_sharded(staged)
        self._prune(keep_path=staged.path)

    def _prune(self, keep_path: str) -> None:
        from ..parallel.multihost import master_only

        with master_only("checkpoint-prune") as master:
            if master and self.keep > 0:  # one pruner per cluster
                import shutil

                from .sharded import is_sharded_checkpoint

                if self.layout == "delta":
                    # chain retention is the chain's own job: keep-N
                    # respecting segment integrity (a keyframe that
                    # live deltas replay from is never deleted)
                    self._chain.prune(self.keep)
                for old in self._file_steps()[:-self.keep]:
                    # a layout-switch run can leave one step in BOTH
                    # layouts; prune must clear both (removing only the
                    # configured one would resurrect the stale other
                    # file as that step's sole checkpoint)
                    for layout in ("full", "sharded"):
                        p = self.path_for(old, layout)
                        if not os.path.exists(p):
                            continue
                        shutil.rmtree(p) if os.path.isdir(p) else os.unlink(p)
                # incomplete (manifest-less) sharded dirs are crash husks
                # invisible to steps(); clear them now that a newer
                # checkpoint is durable. Prune never overlaps a pending
                # async write: flush() clears _pending (and joins the
                # writer) before calling here, and save() prunes only on
                # the synchronous path.
                for fn in os.listdir(self.directory):
                    p = os.path.join(self.directory, fn)
                    if (fn.startswith(self.prefix + "_")
                            and fn.endswith(".ckpt") and os.path.isdir(p)
                            and not is_sharded_checkpoint(p)
                            and os.path.abspath(p)
                            != os.path.abspath(keep_path)):
                        shutil.rmtree(p, ignore_errors=True)

    def latest(self, *, mesh=None, spec=None) -> Optional[Checkpoint]:
        """The newest checkpoint that VERIFIES. A torn/corrupt newest
        step (``CheckpointCorruptionError`` — failed CRC32, unreadable
        archive, incomplete shard coverage) falls back to the next-older
        step instead of crashing resume — the commit-by-vote discipline
        extended to integrity, not just presence. None when the
        directory holds no checkpoints; raises when every step on disk
        fails verification (resuming from nothing would silently discard
        the run's durable history)."""
        steps = self.steps()
        if not steps:
            return None
        last_err: Optional[CheckpointCorruptionError] = None
        for step in reversed(steps):
            try:
                return self.restore(step, mesh=mesh, spec=spec)
            except CheckpointCorruptionError as e:
                warnings.warn(
                    f"checkpoint step {step} failed verification ({e}); "
                    "falling back to the previous verified checkpoint",
                    RuntimeWarning, stacklevel=2)
                last_err = e
        raise CheckpointCorruptionError(
            f"no verifiable checkpoint in {self.directory}: all "
            f"{len(steps)} step(s) on disk failed verification "
            f"(newest error: {last_err})") from last_err

    def restore(self, step: int, *, mesh=None, spec=None) -> Checkpoint:
        layout = self._layout_on_disk(step)
        if layout == "delta":
            # chain replay assembles full host arrays (the dense
            # layout's restore semantics; re-sharding is the executor's
            # job on the next run, so mesh/spec do not apply)
            return self._chain.restore(step)
        path = self.path_for(step, layout)
        if layout == "sharded":
            from .sharded import load_checkpoint_sharded

            return load_checkpoint_sharded(path, mesh=mesh, spec=spec)
        return load_checkpoint(path)


def run_checkpointed(model, space: CellularSpace, manager: CheckpointManager,
                     *, steps: Optional[int] = None, every: int = 1,
                     executor=None, check_conservation: bool = True,
                     tolerance: float = 1e-3, rtol: Optional[float] = None):
    """Run ``model`` for ``steps`` (default ``model.num_steps``), saving a
    checkpoint every ``every`` steps and RESUMING from ``manager.latest()``
    when one exists. Restarting after any interruption continues from the
    last saved step and yields state bit-identical to an uninterrupted
    run (proven in tests/test_io.py).

    This is ``resilience.supervised_run`` with recovery disabled
    (``max_failures=0``): the same resume/chunk driver, so checkpoints
    written here carry the run's conservation baseline and interoperate
    with supervised runs. ``check_conservation`` maps onto the
    supervisor's in-band health checks (drift is bounded against the
    RUN-global initial totals, and a violation surfaces as
    ``SimulationFailure`` wrapping the health report)."""
    from ..resilience import SimulationFailure, supervised_run

    try:
        res = supervised_run(model, space, manager, steps=steps,
                             every=every, max_failures=0, executor=executor,
                             health_checks=check_conservation,
                             tolerance=tolerance, rtol=rtol)
    except SimulationFailure as e:
        # with recovery disabled there is exactly one underlying failure;
        # surface it with its original type (callers catch e.g.
        # ConservationError/HealthError, not the supervisor's wrapper)
        if e.__cause__ is not None:
            raise e.__cause__
        raise
    return res.space, res.step, res.report
