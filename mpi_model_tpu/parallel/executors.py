"""Sharded executors: the distributed runtime of the framework.

Rebuild of the reference's distributed ``Model::execute<R>`` orchestration
(``/root/reference/src/Model.hpp:53-262``) — minus the master rank, the
string wire protocol and the hand-rolled collectives. Two strategies:

- ``AutoShardedExecutor`` — the *idiomatic XLA* path: the same global-array
  step the serial path runs, jitted with ``NamedSharding`` on its inputs;
  XLA's SPMD partitioner inserts the halo exchanges for the stencil shifts
  automatically. Zero re-expression of the model.

- ``ShardMapExecutor`` — the *explicit* path mirroring the reference's
  architecture: per-shard code with hand-placed ``ppermute`` halo exchanges
  (``parallel.halo``), scan inside ``shard_map`` so the whole time loop +
  halo traffic compiles into one XLA program over ICI. This is the path
  that extends to Pallas kernels and custom collective schedules.

Both reproduce the serial semantics exactly (tests golden-compare all three
paths); the conservation contract holds because shares crossing shard
boundaries are delivered via halos, and true grid edges see ppermute's
zero-fill (non-periodic boundary).

Point flows are SPARSE per-shard scatters (the serial path's
``point_flow_step`` economics): the owner test (``Model.hpp:176,189``)
becomes a mask instead of a rank branch, so a source sitting on a shard's
last row (the reference's deliberate default: cell (19,3) on rank 1's
stripe edge, ``Main.cpp:33``) needs no special case — its neighbor-share
rides the ordinary halo.

Every runner takes the step count as a TRACED scalar (dynamic trip
count), so supervisor chunks of any size — including the remainder chunk
— and step-count sweeps reuse one compilation per model/space geometry.
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map
from ..core.cellular_space import CellularSpace
from ..ops.flow import PointFlow
from ..resilience import inject
from .halo import gather_from_padded, pad_with_halo_1d, pad_with_halo_2d
from .mesh import grid_spec, put_global

Values = dict[str, jax.Array]


def _check_divisible(space: CellularSpace, mesh: Mesh) -> None:
    dims = (space.dim_x, space.dim_y)
    for axis_idx, name in enumerate(mesh.axis_names[:2]):
        n = mesh.shape[name]
        if dims[axis_idx] % n != 0:
            raise ValueError(
                f"grid dim {dims[axis_idx]} along '{name}' not divisible by "
                f"mesh extent {n} (the reference's PROC_DIMX=DIMX/NWORKERS "
                f"divisibility requirement, Defines.hpp:8)")


class AutoShardedExecutor:
    """GSPMD path: global step + sharding annotations; XLA inserts halos."""

    def __init__(self, mesh: Mesh, spec: Optional[P] = None):
        self.mesh = mesh
        self.spec = grid_spec(mesh) if spec is None else spec
        #: "xla" (the GSPMD global step) or "point" (the point-subsystem
        #: fast path for all-point-flow models) — reported by CLI/bench
        self.last_impl: Optional[str] = "xla"
        self._cache: dict = {}

    @property
    def comm_size(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def run_model(self, model, space: CellularSpace, num_steps: int) -> Values:
        _check_divisible(space, self.mesh)
        # all-point-flow models take the point-subsystem fast path the
        # other executors already have (round-4 VERDICT weak #3): the
        # ≤9k involved cells step in a tiny compiled loop on the global
        # view (GSPMD's global-array semantics make dynamic amounts fine
        # here, unlike shard_map), and the result is scattered onto the
        # mesh once per run
        if (num_steps > 0 and model.flows
                and all(isinstance(f, PointFlow) for f in model.flows)):
            from ..ops.point_kernel import (build_point_plans,
                                            serial_point_runner)

            key = ("pointmini", space.shape, space.global_shape,
                   (space.x_init, space.y_init), str(space.dtype),
                   model.offsets,
                   tuple(f.fingerprint() for f in model.flows))
            runner = self._cache.get(key)
            if runner is None:
                plans = build_point_plans(model.flows, space, model.offsets)
                runner = (jax.jit(serial_point_runner(
                    plans, jnp.dtype(space.dtype)))
                    if plans is not None else False)
                self._cache[key] = runner
            if runner:
                self.last_impl = "point"
                # shard FIRST: the runner's gather/scatter touch only the
                # ~9k involved cells, so running it on the sharded global
                # arrays lets XLA partition those tiny ops — the grid is
                # never materialized on one device (it may not fit there;
                # the mesh's aggregate memory is the point of GSPMD)
                sharding = NamedSharding(self.mesh, self.spec)
                values = {k: put_global(v, sharding)
                          for k, v in space.values.items()}
                return runner(values, jnp.int32(num_steps))
        self.last_impl = "xla"
        step = model.make_step(space)
        runner = self._cache.get(step)
        if runner is None:
            sharding = NamedSharding(self.mesh, self.spec)

            def _run(v, n):
                def body(i, c):
                    out = step(c)
                    # keep the carry pinned to the mesh layout across steps
                    return {k: jax.lax.with_sharding_constraint(a, sharding)
                            for k, a in out.items()}
                # n is a TRACED scalar: one compile serves any step count
                return jax.lax.fori_loop(0, n, body, v)

            runner = jax.jit(_run)
            self._cache[step] = runner
        values = {k: put_global(v, NamedSharding(self.mesh, self.spec))
                  for k, v in space.values.items()}
        return runner(values, jnp.int32(num_steps))


class ShardMapExecutor:
    """Explicit SPMD path: shard_map + ppermute halo exchange per step.

    Field flows run per shard according to their declared
    ``Flow.footprint``: ``"pointwise"`` outflows are evaluated on the bare
    shard, ``"ring1"`` outflows get one-cell halo-padded channels (their
    ``outflow_padded``), and undeclared footprints raise instead of
    silently miscomputing. Point flows of any kind are lifted to dense
    one-hot fields sharded with the grid. User flows needing global
    coordinates should precompute coordinate fields as extra attribute
    channels.

    ``step_impl`` selects the per-shard field-flow kernel, mirroring
    ``SerialExecutor``: ``"xla"`` (pad→gather stencil, works for every
    flow), ``"pallas"`` (the fused halo-mode kernels consuming the
    ppermute ghost ring — the specialized Diffusion kernel when every
    flow is a plain ``Diffusion``, else the general multi-channel field
    kernel for any POINTWISE flows (Coupled/user); requires no point
    flows and an f32/bf16 non-partition grid, raises otherwise),
    ``"composed"`` (the composed k-step filter consuming the
    ``halo_depth``-deep ring: interior tiles run ONE
    ``(2·halo_depth+1)²`` tap pass per exchange instead of
    ``halo_depth`` iterated steps — all-Diffusion models only, raises
    otherwise; see ``ops.composed_stencil``), ``"active"`` /
    ``"active_fused"`` (shard-local active-tile stepping — the XLA
    engine or the fused Pallas kernel over the same ghost-padded
    windows; ``_build_active_runner``), or ``"auto"`` (pallas when
    eligible and its compile succeeds, else xla).
    """

    def __init__(self, mesh: Mesh, step_impl: str = "xla",
                 halo_mode: str = "exchange", halo_depth: int = 1,
                 compute_dtype=None):
        if len(mesh.axis_names) not in (1, 2):
            raise ValueError("ShardMapExecutor needs a 1-D or 2-D mesh")
        if step_impl not in ("xla", "pallas", "auto", "composed", "active",
                             "active_fused"):
            raise ValueError(f"unknown step impl {step_impl!r}")
        if halo_mode not in ("exchange", "zero"):
            raise ValueError(f"unknown halo mode {halo_mode!r}")
        if int(halo_depth) < 1:
            raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")
        if step_impl in ("active", "active_fused") and int(halo_depth) != 1:
            raise ValueError(
                f"step_impl={step_impl!r} exchanges a one-cell ghost ring "
                f"per step; halo_depth={halo_depth} is not supported (the "
                "active set would need depth-d frontier dilation)")
        self.mesh = mesh
        self.step_impl = step_impl
        #: DIAGNOSTIC knob for measuring halo cost (benchmarks/ladder.py's
        #: halo-exchange wallclock share): "zero" replaces every ppermute
        #: ghost exchange with zero padding — identical compute shape, NO
        #: inter-shard traffic, WRONG results at shard boundaries. Never
        #: use for real runs.
        self.halo_mode = halo_mode
        #: halo_depth > 1 = DEEP-HALO execution: each collective round
        #: exchanges a depth-d ghost ring, then d local steps run on it —
        #: collective rounds drop d-fold. On the XLA path the padded
        #: shard shrinks one ring per step (any pointwise flows); on the
        #: Pallas path the ring feeds d FUSED kernel steps (one
        #: collective round and one HBM round-trip per d steps —
        #: Diffusion-only). Point flows need halo_depth=1 (they must
        #: fire between steps).
        self.halo_depth = int(halo_depth)
        #: interior-tile window math dtype for the Pallas halo kernels
        #: (None → f32; the near-ring exact path stays f32 — the same
        #: knob as ``Model.make_step(compute_dtype=...)``); the XLA
        #: shard step ignores it
        self.compute_dtype = compute_dtype
        #: kernel the last ``run_model`` actually used ("pallas"/"xla"),
        #: after any "auto" fallback — reported by the CLI/bench
        self.last_impl: Optional[str] = None
        #: per-run report detail (Report.backend_report); None until a
        #: run records one
        self.last_backend_report: Optional[dict] = None
        self._cache: dict = {}

    @property
    def comm_size(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    # -- execution ---------------------------------------------------------

    def _pallas_plan(self, model, space: CellularSpace):
        """Which fused halo kernel applies: ``("diffusion", rates)`` when
        every flow is a plain Diffusion (the specialized kernel with the
        closed-form interior fast path), ``("composed", rates)`` for the
        same shape under ``step_impl="composed"`` (interior tiles run
        ONE composed (2·depth+1)² tap pass per depth-deep exchange,
        ``ops.composed_stencil``), ``("field", flows)`` when every
        field flow is pointwise (the general multi-channel kernel —
        Coupled/user flows), or None → the XLA shard step. Raises for an
        explicit ``step_impl='pallas'``/``'composed'`` that can't be
        honored."""
        if self.step_impl == "xla":
            return None
        has_point = any(isinstance(f, PointFlow) for f in model.flows)
        # f64 shards stay on the XLA shard step: the halo kernels compute
        # in f32 internally (no silent precision downgrade under "auto");
        # point flows must fire between steps, which the fused kernels
        # cannot interleave
        base_ok = (not has_point and not space.is_partition
                   and model.pallas_dtype_ok(space))
        if self.step_impl == "composed":
            rates = model.pallas_rates() if base_ok else None
            if rates and any(r != 0.0 for r in rates.values()):
                return ("composed", rates)
            if rates is not None and not any(r != 0.0
                                             for r in rates.values()):
                raise ValueError(
                    "step_impl='composed' has nothing to compose: every "
                    "Diffusion rate is 0.0 (no field transport). Use "
                    "'xla' or 'auto' for a no-op field step.")
            raise ValueError(
                "step_impl='composed' requires all field flows to be "
                "plain Diffusion (a uniform rate is what composes into "
                "an explicit tap table) on a full (non-partition) "
                "f32/bf16 grid with no point flows; got "
                f"flows={[type(f).__name__ for f in model.flows]}, "
                f"is_partition={space.is_partition}, "
                f"dtype={space.dtype}. Use 'xla', 'pallas' or 'auto'.")
        if base_ok:
            rates = model.pallas_rates()
            # empty/all-zero rates = no field transport: nothing for the
            # kernel to do — don't claim "pallas" ran (see make_step).
            # The general field kernel applies only when some flow NEEDS
            # it (rates is None — a non-Diffusion pointwise flow), never
            # as a no-op fallback for zero-rate Diffusions.
            if rates is not None:
                if rates and any(r != 0.0 for r in rates.values()):
                    return ("diffusion", rates)
            else:
                field_flows = tuple(f for f in model.flows
                                    if not isinstance(f, PointFlow))
                if field_flows and all(
                        getattr(f, "footprint", "unknown") == "pointwise"
                        for f in field_flows):
                    return ("field", field_flows)
        if self.step_impl == "pallas":
            raise ValueError(
                "step_impl='pallas' requires all field flows to be "
                "POINTWISE (Diffusion/Coupled/...) on a full "
                "(non-partition) f32/bf16 grid with no point flows (the "
                "kernels compute in f32; f64 runs the XLA shard step); "
                "got "
                f"flows={[type(f).__name__ for f in model.flows]}, "
                f"is_partition={space.is_partition}, "
                f"dtype={space.dtype}. Use 'xla' or 'auto'.")
        return None

    def run_model(self, model, space: CellularSpace, num_steps: int) -> Values:
        # chaos seam (resilience.inject): one module-global read when no
        # plan is armed; "halo" arms the trace-time ghost-ring
        # perturbation for exactly this chunk (the perturbed runner is
        # cached under a distinct build token, so the clean cache is
        # never poisoned)
        st = inject.active()
        if st is None:
            return self._run_inner(model, space, num_steps)
        idx = st.bump("executor")
        fault = st.take("executor", idx, kinds=("exc", "nan", "halo"))
        if fault is None:
            return self._run_inner(model, space, num_steps)
        if fault.kind == "exc":
            # call index in the message: distinct signatures per
            # injection (see SerialExecutor.run_model)
            raise inject.InjectedFault(
                f"injected executor fault on call {idx} (sharded "
                f"{num_steps}-step chunk)")
        if fault.kind == "halo":
            with st.halo_window(fault):
                return self._run_inner(model, space, num_steps)
        out = self._run_inner(model, space, num_steps)
        return inject.poison_values(out, fault, st.plan)

    def _run_inner(self, model, space: CellularSpace,
                   num_steps: int) -> Values:
        _check_divisible(space, self.mesh)
        #: per-run report detail (Report.backend_report) — reset so a
        #: previous run's composed record never leaks forward
        self.last_backend_report = None
        # origin is part of the identity: the compiled runners bake
        # row0/col0 and the boundary mask from it, so two same-shaped
        # partitions at different origins must not share a runner. The
        # STEP COUNT is deliberately NOT part of it: runners take the
        # count as a traced scalar (dynamic trip count), so a supervisor
        # sweeping chunk sizes or a remainder chunk reuses one compile.
        # the trailing inject.build_token() is None except while a halo
        # fault is armed — a perturbed build lives under its own key and
        # can never serve (or be served by) a clean chunk
        key = (space.shape, space.global_shape,
               (space.x_init, space.y_init), str(space.dtype),
               tuple(space.values), model.offsets,
               tuple(f.fingerprint() for f in model.flows),
               inject.build_token())
        spec = grid_spec(self.mesh)
        sharding = NamedSharding(self.mesh, spec)
        put = partial(put_global, sharding=sharding)
        values = {k: put(v) for k, v in space.values.items()}
        n = jnp.int32(num_steps)

        from ..utils.tracing import get_tracer

        # nonlinear Flow IR models (ISSUE 11): the general registered
        # lowering in its ghost-ring context — the model's max term
        # FOOTPRINT drives the required halo depth (1 for the current
        # grammar: transport reads the Moore ring), instead of trusting
        # a hand-set knob. Linear IR models never reach this branch
        # (their Diffusion flows view rides every specialized path
        # below, bitwise).
        if (getattr(model, "ir_terms", None) is not None
                and not model.ir_linear):
            if self.step_impl not in ("xla", "auto"):
                raise ValueError(
                    f"step_impl={self.step_impl!r} is a linear-stencil "
                    "engine; this model's nonlinear IR terms "
                    f"({[t.name for t in model.ir_terms]}) run the "
                    "general lowering — use step_impl='xla'/'auto'.")
            from ..ir.lower import max_footprint

            need = max(1, max_footprint(model.ir_terms))
            if self.halo_depth != need:
                raise ValueError(
                    f"this model's terms read a footprint-"
                    f"{max_footprint(model.ir_terms)} stencil: the "
                    f"required halo depth is {need}, got halo_depth="
                    f"{self.halo_depth} (nonlinear terms do not compose "
                    "into deep-halo chunks — the tap table is a linear "
                    "object)")
            # the flow-based key's fingerprint component is EMPTY for a
            # nonlinear IR model (flows=[]), and the runner bakes the
            # term rates concretely — the terms' own fingerprints must
            # be part of the identity or two models sharing a geometry
            # would silently share one compiled physics
            ikey = ("ir", model._term_fingerprints()) + key
            runner = self._cache.get(ikey)
            if runner is None:
                with get_tracer().span("shardmap.build", impl="ir"):
                    runner = self._build_ir_runner(model, space)
                self._cache[ikey] = runner
            self.last_impl = "xla"
            return runner(values, n)

        # all-FROZEN-point-flow models (the reference's live workload)
        # step only the ≤9k involved cells per shard — constant per-step
        # deltas mean NO halo traffic at all; owned entries scatter back
        # once per run. Bitwise equal to the halo path.
        if (self.halo_depth == 1
                and self.step_impl in ("xla", "auto", "active",
                                       "active_fused")
                and model.flows
                and all(isinstance(f, PointFlow) for f in model.flows)):
            mkey = ("pointmini",) + key
            runner = self._cache.get(mkey)
            if runner is None:
                from ..ops.point_kernel import build_point_plans

                plans = build_point_plans(model.flows, space, model.offsets)
                if plans is not None and all(p.frozen_only
                                             for p in plans.values()):
                    with get_tracer().span("shardmap.build",
                                           impl="point-subsystem"):
                        runner = self._build_point_runner(space, plans)
                else:
                    # cache the ineligible verdict too: a dynamic point
                    # flow must not re-pay plan construction every chunk
                    runner = False
                self._cache[mkey] = runner
            if runner:
                # "point" = the zero-collective subsystem path; distinct
                # from "xla" so its liveness is assertable (dryrun/tests)
                self.last_impl = "point"
                return runner(values, n)

        # shard-local active sets (ISSUE 3): each shard tracks its OWN
        # tile activity — the one-cell ppermute ghost ring both feeds
        # the tile windows and activates edge tiles (ghost_flags), so
        # cross-shard frontier arrival is seen one step early, exactly
        # like the interior dilation. The per-shard dense fallback
        # consumes the same exchanged ring (the exchange sits OUTSIDE
        # the cond: collectives must run on every shard every step).
        if self.step_impl in ("active", "active_fused"):
            fused = self.step_impl == "active_fused"
            akey = (self.step_impl, key)
            entry = self._cache.get(akey)
            if entry is None:
                with get_tracer().span("shardmap.build",
                                       impl=self.step_impl):
                    entry = self._build_active_runner(model, space,
                                                      fused=fused)
                self._cache[akey] = entry
            runner, plan, nattr, nshards = entry
            out, stats = runner(values, n)
            if fused:
                fb, at, ff = stats
            else:
                (fb, at), ff = stats, None
            self.last_impl = self.step_impl
            ntiles = plan.ntiles * nshards
            self.last_backend_report = {
                "impl": self.step_impl,
                "steps": int(num_steps),
                "shards": nshards,
                #: (shard, attr, step) triples that ran the per-shard
                #: dense fallback — psum'd, so an all-shards-dense run
                #: reads steps*nattr*nshards, not a silent "active"
                "fallback_steps": int(fb),
                "tile": list(plan.tile),
                "tiles": ntiles,
                "tiles_per_shard": plan.ntiles,
                "capacity": plan.capacity,
                "fallback_tiles": plan.fallback_tiles,
                "mean_active_fraction": (
                    float(at) / (num_steps * nattr * ntiles)
                    if num_steps and nattr else None),
            }
            if fused:
                #: (shard, attr, step) triples whose flags came out of
                #: the kernel (psum'd) — fallbacks recompute flags in
                #: XLA, so flags_fused + fallback_steps == the triple
                #: total (the observability satellite's counter)
                self.last_backend_report["flags_fused"] = int(ff)
            return out

        # one probe/build/cache protocol for both depths: the fused
        # Pallas kernel is tried first (deep halos compose with it — a
        # depth-d ring feeds d fused steps per exchange: one collective
        # round AND one HBM round-trip per d steps), else the XLA
        # shard step (deep or pad-gather) is built
        deep = self.halo_depth > 1
        entry = self._cache.get(key)
        if entry is None:
            kind, prunner, out = self._probe_pallas(
                model, space, num_steps, values,
                label="pallas-deep" if deep else "pallas",
                fallback_name=("the XLA deep-halo path" if deep
                               else "the XLA pad-gather path"))
            if prunner is not None:
                self._cache[key] = (kind, prunner)
                self.last_impl = kind
                self._record_backend_report(kind, num_steps)
                return out
            with get_tracer().span("shardmap.build",
                                   impl="deep-halo" if deep else "xla",
                                   depth=self.halo_depth):
                runner = (self._build_deep_runner(model, space) if deep
                          else self._build_runner(model, space))
            entry = ("xla", runner)
            self._cache[key] = entry
        kind, runner = entry
        #: the kernel the last run actually used (after any "auto"
        #: fallback) — the CLI/bench report it so a user never believes
        #: they measured a configuration that never ran
        self.last_impl = kind
        self._record_backend_report(kind, num_steps)
        return runner(values, n)

    def _record_backend_report(self, kind: str, num_steps: int) -> None:
        """Composed auto-k visibility (ISSUE 3 satellite): the sharded
        composed path's k IS ``halo_depth`` and the remainder chunk
        (``num_steps % k``) composes at its own depth — both recorded
        in ``Report.backend_report`` so a depth that buys no
        composition is observable."""
        if kind != "composed":
            return
        d = self.halo_depth
        self.last_backend_report = {
            "impl": "composed",
            "composed_k": d,
            "full_chunks": num_steps // d,
            "remainder_chunk_depth": num_steps % d,
        }

    def _probe_pallas(self, model, space, num_steps, values, *, label,
                      fallback_name):
        """Build + first-run the Pallas runner under one guard (BUILD-time
        validation errors — e.g. a ring deeper than the slab capacity —
        and compile/device faults degrade identically). Returns
        ``(kind, runner, first_output)`` on success — ``kind`` is the
        honest ``last_impl`` label ("pallas" or "composed") —
        ``(None, None, None)`` when ineligible or when ``"auto"`` should
        fall back; re-raises under explicit ``step_impl="pallas"`` /
        ``"composed"``. ``block_until_ready`` makes async device faults
        surface HERE, not in the caller after a broken runner got
        cached."""
        from ..utils.tracing import get_tracer

        plan = self._pallas_plan(model, space)
        if plan is None:
            return None, None, None
        kind = "composed" if plan[0] == "composed" else "pallas"
        if kind == "composed":
            label = f"composed-depth{self.halo_depth}"
        tracer = get_tracer()
        try:
            with tracer.span("shardmap.build", impl=label,
                             depth=self.halo_depth):
                prunner = self._build_pallas_runner(model, space, plan)
            with tracer.span("shardmap.compile+first_run", impl=label):
                out = jax.block_until_ready(
                    prunner(values, jnp.int32(num_steps)))
        # analysis: ignore[broad-except] — compile-probe boundary: the
        # sharded pallas/composed build+first-run may fail with any
        # Mosaic/XLA/device error; explicit impls re-raise, auto falls
        # back to the XLA shard step
        except Exception as e:
            if self.step_impl in ("pallas", "composed"):
                raise
            warnings.warn(
                f"{label} step failed ({e!r}); falling back to "
                f"{fallback_name}", RuntimeWarning)
            return None, None, None
        return kind, prunner, out

    def _shard_geometry(self, space: CellularSpace):
        """(names, nx, ny, local_h, local_w): this mesh's axis names,
        extents, and per-shard block dims (1-D meshes: ny = 1, columns
        un-split) — the geometry every runner builder needs."""
        names = self.mesh.axis_names
        nx = self.mesh.shape[names[0]]
        ny = self.mesh.shape[names[1]] if len(names) > 1 else 1
        return names, nx, ny, space.dim_x // nx, space.dim_y // ny

    def _build_point_runner(self, space: CellularSpace, plans):
        """shard_map wrapper for the frozen point-subsystem runner: each
        shard derives its window offset from ``axis_index`` and updates
        only the involved cells it owns — zero collectives."""
        from jax import lax

        from ..ops.point_kernel import shard_point_runner

        mesh = self.mesh
        names, nx, ny, local_h, local_w = self._shard_geometry(space)
        spec = grid_spec(mesh)
        run = shard_point_runner(plans, jnp.dtype(space.dtype),
                                 local_h, local_w)

        def shard_fn(values, n):
            off_x = lax.axis_index(names[0]) * np.int32(local_h)
            off_y = (lax.axis_index(names[1]) * np.int32(local_w)
                     if len(names) > 1 else jnp.int32(0))
            return run(values, off_x, off_y, n)

        sharded = shard_map(shard_fn, mesh=mesh, in_specs=(spec, P()),
                                out_specs=spec)
        return jax.jit(sharded)

    def _build_deep_runner(self, model, space: CellularSpace):
        """Deep-halo execution: one depth-d ghost exchange per d local
        steps, for ANY pointwise field flows (Diffusion, Coupled, user
        flows). All channels are padded; each step evaluates every flow's
        own ``outflow()`` on the pre-step padded values (summed-outflow
        semantics), masks outflows to the partition (affine flows must
        not manufacture mass on ghost cells), and applies the exact
        per-cell-count transport on a region shrinking one ring per step
        — mirroring ``ops.stencil.transport``'s expression term-for-term.
        All-Diffusion models reproduce the serial path BITWISE (the
        uniform-rate expression compiles to the same contraction);
        general flows match to ~1 ULP (XLA FMA grouping of the summed
        outflow differs between compilations). Collective rounds (the
        0.64-0.81 halo share measured in BASELINE configs 2-3) drop
        d-fold."""
        from jax import lax

        depth = self.halo_depth
        field_flows = [f for f in model.flows
                       if not isinstance(f, PointFlow)]
        has_point = any(isinstance(f, PointFlow) for f in model.flows)
        all_pointwise = bool(field_flows) and all(
            getattr(f, "footprint", "unknown") == "pointwise"
            for f in field_flows)
        if not all_pointwise or has_point:
            raise ValueError(
                "halo_depth > 1 requires POINTWISE field flows and no "
                "point flows (a point flow must fire between steps, "
                "which deep-halo chunks cannot interleave); got "
                f"flows={[type(f).__name__ for f in model.flows]}. "
                "Use halo_depth=1 for general flows.")
        # all-Diffusion models take the uniform-rate expression whose
        # compiled graph matches the serial path BITWISE; general
        # pointwise flows take the summed-outflow form, which XLA's FMA
        # contraction may round differently by ~1 ULP
        uniform_rates = model.pallas_rates()
        # the general chunk pads and MASKS every channel in the flow
        # dtype, which would silently float-ify int/bool storage
        # channels (e.g. a land-water mask); the uniform chunk touches
        # only its own rate-carrying float attrs, so bystanders are
        # fine there
        if uniform_rates is None:
            nonfloat = sorted(
                k for k, v in space.values.items()
                if not jnp.issubdtype(v.dtype, jnp.floating))
            if nonfloat:
                raise ValueError(
                    f"halo_depth > 1 with general pointwise flows pads/"
                    f"masks every channel in the flow dtype; non-float "
                    f"channels {nonfloat} are not supported on this path "
                    "— use halo_depth=1 (or an all-Diffusion model)")

        mesh = self.mesh
        names, nx, ny, local_h, local_w = self._shard_geometry(space)
        # only EXCHANGED dimensions bound the depth — on a 1-D mesh the
        # columns are zero-padded, not shipped, so any width is fine
        exchanged_min = local_h if len(names) == 1 else min(local_h, local_w)
        if depth > exchanged_min:
            raise ValueError(
                f"halo_depth={depth} exceeds the shard extent "
                f"({local_h}x{local_w}) — the exchanged slab cannot be "
                "deeper than the shard")
        offsets = model.offsets
        gshape = space.global_shape
        x_init, y_init = space.x_init, space.y_init
        dtype = space.dtype
        D = depth
        spec = grid_spec(mesh)

        if self.halo_mode == "zero":
            def pad_deep(z, d):  # diagnostic: no traffic (see __init__)
                return jnp.pad(z, d)
        elif len(names) == 1:
            def pad_deep(z, d):
                return pad_with_halo_1d(z, names[0], nx, depth=d)
        else:
            def pad_deep(z, d):
                return pad_with_halo_2d(z, names[0], names[1], nx, ny,
                                        depth=d)

        def shard_fn(values, n):
            row0 = np.int32(x_init) + lax.axis_index(names[0]) * np.int32(
                local_h)
            col0 = (np.int32(y_init)
                    + lax.axis_index(names[1]) * np.int32(local_w)
                    if len(names) > 1 else jnp.int32(y_init))
            # mask and true neighbor counts over the DEPTH-padded region,
            # from global coords (hoisted: one computation per compile,
            # sliced per chunk/step). The mask is the PARTITION bounds,
            # not the grid bounds: a standalone partition drops shares at
            # its interior edges EVERY step (reference-worker semantics,
            # see Model.execute), so ghost cells beyond the partition
            # must be re-zeroed each sub-step; for a full grid the two
            # coincide. Counts stay global-true (grid-edge topology).
            PH, PW = local_h + 2 * D, local_w + 2 * D
            rowg = (row0 - np.int32(D)) + lax.broadcasted_iota(
                jnp.int32, (PH, PW), 0)
            colg = (col0 - np.int32(D)) + lax.broadcasted_iota(
                jnp.int32, (PH, PW), 1)
            maskD_b = ((rowg >= np.int32(x_init))
                       & (rowg < np.int32(x_init) + np.int32(space.dim_x))
                       & (colg >= np.int32(y_init))
                       & (colg < np.int32(y_init) + np.int32(space.dim_y)))
            maskD = maskD_b.astype(dtype)
            from ..ops.stencil import neighbor_counts_traced
            cntD = jnp.maximum(
                neighbor_counts_traced(
                    (PH, PW), offsets,
                    (row0 - np.int32(D), col0 - np.int32(D)), gshape, dtype),
                jnp.asarray(1, dtype))

            def transport_step(cur, of, cnt_s, m, s, hs, ws):
                share = of / cnt_s
                inflow = None
                for dx, dy in offsets:
                    t = share[1 + dx:hs - 1 + dx, 1 + dy:ws - 1 + dy]
                    inflow = t if inflow is None else inflow + t
                return ((cur[1:hs - 1, 1:ws - 1]
                         - of[1:hs - 1, 1:ws - 1] + inflow)
                        * m[s + 1:s + hs - 1, s + 1:s + ws - 1])

            def chunk_uniform(c, d):
                """All-Diffusion: per-attr uniform-rate expression —
                compiles to the serial path's exact contraction (BITWISE
                parity); flow-less channels are never padded/exchanged."""
                off = D - d
                m = maskD[off:PH - off, off:PW - off]
                cnt = cntD[off:PH - off, off:PW - off]
                new = dict(c)
                for attr, rate in uniform_rates.items():
                    if rate == 0.0:
                        continue
                    cur = pad_deep(c[attr], d) * m
                    for s in range(d):
                        hs, ws = cur.shape
                        cur = transport_step(cur, rate * cur,
                                             cnt[s:s + hs, s:s + ws],
                                             m, s, hs, ws)
                    new[attr] = cur
                return new

            def chunk_general(c, d):
                """General pointwise flows: every channel rides the
                padded region (modulators are read by other flows'
                outflows at the shrinking shapes); ~1 ULP vs serial
                (XLA FMA grouping of the summed outflow)."""
                off = D - d
                m = maskD[off:PH - off, off:PW - off]
                mb = maskD_b[off:PH - off, off:PW - off]
                cnt = cntD[off:PH - off, off:PW - off]
                cur = {k: pad_deep(v, d) * m for k, v in c.items()}
                for s in range(d):
                    hs, ws = next(iter(cur.values())).shape
                    cnt_s = cnt[s:s + hs, s:s + ws]
                    mb_s = mb[s:s + hs, s:s + ws]
                    # the region's [0,0] sits d-s cells before the shard
                    # origin — origin-reading pointwise flows need it
                    org_s = (row0 - np.int32(d - s), col0 - np.int32(d - s))
                    # all outflows read the PRE-step values; the
                    # where-SELECT (bitwise passthrough in-partition)
                    # masks ghost cells so affine outflow(0) != 0 flows
                    # don't manufacture mass there
                    outflows = {}
                    for f in field_flows:
                        o = jnp.where(mb_s, f.outflow(cur, org_s),
                                      jnp.asarray(0, dtype))
                        outflows[f.attr] = (outflows[f.attr] + o
                                            if f.attr in outflows else o)
                    new = {}
                    for k2, cw in cur.items():
                        of = outflows.get(k2)
                        if of is None:
                            new[k2] = cw[1:hs - 1, 1:ws - 1]
                            continue
                        new[k2] = transport_step(cw, of, cnt_s, m, s,
                                                 hs, ws)
                    cur = new
                return cur

            chunk = (chunk_uniform if uniform_rates is not None
                     else chunk_general)

            # n is a TRACED scalar (dynamic trip count): one compile
            # serves every step count. q full-depth chunks, then a
            # lax.switch over the D possible remainder depths.
            q = n // D
            out = lax.fori_loop(0, q, lambda i, c: chunk(c, D), values)
            if D > 1:
                branches = [lambda c: c] + [
                    (lambda d: lambda c: chunk(c, d))(d)
                    for d in range(1, D)]
                out = lax.switch(n - q * D, branches, out)
            return out

        sharded = shard_map(shard_fn, mesh=mesh, in_specs=(spec, P()),
                                out_specs=spec)
        return jax.jit(sharded)

    def _build_pallas_runner(self, model, space: CellularSpace, plan: tuple):
        """Per-shard fused Pallas kernel fed by the ppermute ghost ring —
        the config-5 architecture (SURVEY §7 'Pallas at 16384²'): the
        fast kernel and the distributed runtime in one compiled step.
        ``plan`` selects the kernel (``_pallas_plan``): ``"diffusion"``
        runs the specialized per-channel kernel, ``"field"`` the general
        multi-channel kernel (Coupled/user pointwise flows — ALL
        channels exchange rings, since outflows read modulators on ghost
        cells). With ``halo_depth = d > 1`` the ring is exchanged d
        cells deep and the kernel fuses d flow steps per invocation —
        one collective round AND one HBM round-trip per d steps."""
        from jax import lax

        from ..ops.pallas_stencil import (
            mesh_interpret, pallas_field_halo_step, pallas_halo_step,
        )
        from .halo import exchange_ring, zero_ring

        kind, payload = plan
        mesh = self.mesh
        # resolve interpret from the MESH platform, not ambient config:
        # inside shard_map the values are tracers, and the default
        # backend/device can disagree with where the mesh actually runs
        # (round-3 VERDICT weak #1 — both failure directions)
        interpret = mesh_interpret(mesh)
        names, nx, ny, local_h, local_w = self._shard_geometry(space)
        ax = names[0]
        ay = names[1] if len(names) > 1 else None
        gshape = (space.dim_x, space.dim_y)
        offsets = model.offsets
        spec = grid_spec(mesh)
        depth = self.halo_depth
        if depth > (local_h if ay is None else min(local_h, local_w)):
            raise ValueError(
                f"halo_depth={depth} exceeds the shard extent "
                f"({local_h}x{local_w})")

        def ring_of(z, ns):
            return (zero_ring(z, ns) if self.halo_mode == "zero"
                    else exchange_ring(z, ax, nx, ay, ny, depth=ns))

        def shard_fn(values, n):
            row0 = lax.axis_index(ax) * np.int32(local_h)
            col0 = (lax.axis_index(ay) * np.int32(local_w) if ay
                    else jnp.int32(0))
            origin = jnp.stack([row0, col0]).astype(jnp.int32)

            cdt = self.compute_dtype
            if kind == "diffusion":
                def chunk(c, ns):
                    """ns fused steps after one depth-``ns`` exchange
                    (the remainder chunk ships only the rings it
                    consumes); flow-less channels never exchange."""
                    new = dict(c)
                    for attr, rate in payload.items():
                        if rate == 0.0:
                            continue
                        new[attr] = pallas_halo_step(
                            c[attr], ring_of(c[attr], ns), origin, gshape,
                            rate, offsets, interpret=interpret, nsteps=ns,
                            compute_dtype=cdt)
                    return new
            elif kind == "composed":
                from ..ops.composed_stencil import composed_halo_step

                def chunk(c, ns):
                    """ns flow steps as ONE composed (2·ns+1)² pass per
                    depth-``ns`` exchange — interior tiles run the tap
                    filter, near-global-edge tiles the exact iterated
                    path (remainder chunks compose at their own ns)."""
                    new = dict(c)
                    for attr, rate in payload.items():
                        if rate == 0.0:
                            continue
                        new[attr] = composed_halo_step(
                            c[attr], ring_of(c[attr], ns), origin, gshape,
                            rate, ns, offsets, interpret=interpret,
                            compute_dtype=cdt)
                    return new
            else:
                def chunk(c, ns):
                    """One depth-``ns`` exchange of EVERY channel, then
                    ns fused multi-channel steps in one kernel call."""
                    rings = {k: ring_of(v, ns) for k, v in c.items()}
                    return pallas_field_halo_step(
                        c, rings, origin, gshape, payload, offsets,
                        interpret=interpret, nsteps=ns, compute_dtype=cdt)

            # dynamic trip count (n traced): q full-depth fused chunks,
            # then a switch over the possible remainder depths — each
            # branch instantiates the kernel at its own (static) nsteps
            q = n // depth
            out = lax.fori_loop(0, q, lambda i, c: chunk(c, depth), values)
            if depth > 1:
                branches = [lambda c: c] + [
                    (lambda d: lambda c: chunk(c, d))(d)
                    for d in range(1, depth)]
                out = lax.switch(n - q * depth, branches, out)
            return out

        # check_vma=False: pallas_call's out_shape carries no
        # varying-mesh-axes metadata, which the checker would demand
        sharded = shard_map(shard_fn, mesh=mesh, in_specs=(spec, P()),
                                out_specs=spec, check_vma=False)
        return jax.jit(sharded)

    def _build_active_runner(self, model, space: CellularSpace,
                             fused: bool = False):
        """Shard-local active-tile stepping (``ops.active``): per shard,
        per step — one ppermute value exchange (the ghost ring), tile
        activity = ring-1 dilation of the shard's nonzero-tile map OR'd
        with ghost-strip activations, then either the compacted
        active-set pass (windows read the padded shard, counts from
        GLOBAL coordinates) or, above the capacity/activity threshold,
        the per-shard dense step consuming the same ring. Exchanging
        VALUES instead of shares keeps the result bitwise equal to the
        share-exchanging XLA shard step: a ghost cell's share is
        recomputed here from the same operands with the same expression
        the owning shard uses.

        ``fused=True`` (``step_impl="active_fused"``, ISSUE 8) swaps the
        XLA gather/compute for the scalar-prefetched Pallas pass
        (``ops.pallas_active.fused_active_pass``): windows stream the
        SAME ghost-padded shard — ghost-flag activation, counts-from-
        global-coordinates and the value-exchange bitwise argument all
        carry over unchanged — and the next tile map comes from the
        kernel's in-VMEM flags.

        Returns ``(runner, plan, nattr, nshards)``; the runner yields
        ``(values, (fallback_events, active_tiles_total))`` — plus a
        ``flags_fused`` counter under ``fused`` — with the counters
        psum'd across shards (one cheap collective per run), mirroring
        the serial runner's stats so a sharded run that dense-fell-back
        every step is visible in ``Report.backend_report``, not
        silently labeled "active"."""
        from jax import lax

        from ..ops import active as act
        from ..ops.stencil import neighbor_counts_traced

        impl_name = "active_fused" if fused else "active"
        rates = model.pallas_rates()
        live = {a: r for a, r in (rates or {}).items() if r != 0.0}
        has_point = any(isinstance(f, PointFlow) for f in model.flows)
        if rates is None or not live or has_point:
            raise ValueError(
                f"step_impl={impl_name!r} requires all field flows to be "
                "plain Diffusion with a nonzero rate and no point flows "
                "(the tile-skip rule is only bitwise-exact for "
                "uniform-rate linear flows); got "
                f"flows={[type(f).__name__ for f in model.flows]}. "
                "Use step_impl='xla' or 'auto'.")
        for a in live:
            adt = space.values[a].dtype
            if not jnp.issubdtype(adt, jnp.floating):
                raise TypeError(
                    f"flow transport requires a floating dtype, got "
                    f"{adt} for channel {a!r}")
            if adt != jnp.dtype(space.dtype):
                raise ValueError(
                    f"step_impl={impl_name!r} computes every flow channel "
                    f"in the space dtype ({jnp.dtype(space.dtype).name}); "
                    f"channel {a!r} is {adt}. Use step_impl='xla'.")
        mesh = self.mesh
        names, nx, ny, local_h, local_w = self._shard_geometry(space)
        plan = act.plan_for((local_h, local_w))
        th, tw = plan.tile
        offsets = model.offsets
        gshape = space.global_shape
        x_init, y_init = space.x_init, space.y_init
        dtype = space.dtype
        spec = grid_spec(mesh)

        if self.halo_mode == "zero":
            def pad(z):  # diagnostic: no inter-shard traffic
                return jnp.pad(z, 1)
        elif len(names) == 1:
            def pad(z):
                return pad_with_halo_1d(z, names[0], nx)
        else:
            def pad(z):
                return pad_with_halo_2d(z, names[0], names[1], nx, ny)

        if fused:
            from ..ops.pallas_active import fused_active_pass
            from ..ops.pallas_stencil import mesh_interpret
            interp = mesh_interpret(mesh)

        def shard_fn(values, n):
            row0 = np.int32(x_init) + lax.axis_index(names[0]) * np.int32(
                local_h)
            col0 = (np.int32(y_init)
                    + lax.axis_index(names[1]) * np.int32(local_w)
                    if len(names) > 1 else jnp.int32(y_init))
            # true neighbor counts over the PADDED shard from global
            # coords (hoisted per compile); off-grid ghosts clamp to 1 —
            # their value is ppermute's zero fill anyway
            counts_pad = jnp.maximum(
                neighbor_counts_traced(
                    (local_h + 2, local_w + 2), offsets,
                    (row0 - np.int32(1), col0 - np.int32(1)), gshape,
                    dtype),
                jnp.asarray(1, dtype))

            def step_attr(vals_a, tmap, upd, rate):
                # per-step cond here (unlike the serial runner's
                # while-nest): the ghost exchange is a collective that
                # must run on every shard every step, so consecutive
                # active steps cannot be batched past it — the cond's
                # buffer-copy tax is paid on the (smaller) per-shard
                # arrays and accepted. The tile map is CARRIED, not
                # rebuilt from the shard values (the serial runner's
                # measured lesson: a full-array nonzero reduction per
                # step costs a third of the step); the active branch
                # derives the exact next map from its own per-lane
                # flags, the dense branch re-scans only on fallback
                # EVENTS.
                padded = pad(vals_a)  # collective — OUTSIDE the cond
                flags = (act.dilate_tile_map(tmap)
                         | act.ghost_flags(padded, plan))
                count = jnp.sum(flags, dtype=jnp.int32)
                pred = count > np.int32(plan.fallback_tiles)

                def dense_branch(args):
                    p, u = args
                    new = act.dense_from_ghost_padded(
                        p, rate, counts_pad, offsets, dtype)
                    return (new, act.tile_nonzero_map(new, plan), u,
                            jnp.zeros((), jnp.int32))

                def active_branch(args, _tmap=tmap):
                    p, u = args
                    ids, cnt = act.compact_tile_ids(flags, plan)
                    if fused:
                        # the scalar-prefetched kernel pass: same
                        # ghost-padded windows, flags computed in-VMEM
                        selfnz = _tmap.reshape(-1)[ids].astype(jnp.int32)
                        origin_vec = jnp.stack([row0, col0]).astype(
                            jnp.int32)
                        p2, anyf = fused_active_pass(
                            p, ids, cnt, selfnz, rate, plan, origin_vec,
                            gshape, offsets, dtype, k=1, ring=1,
                            taps=None, interpret=interp)
                        u2 = u
                    else:
                        p2, u2, anyf = act.active_pass(
                            p, u, ids, cnt, rate, plan, (row0, col0),
                            gshape, offsets, dtype)
                    return (p2[1:-1, 1:-1],
                            act.next_tile_map(anyf, ids, cnt, plan), u2,
                            jnp.ones((), jnp.int32))

                nv, ntm, nu, fs = lax.cond(pred, dense_branch,
                                           active_branch, (padded, upd))
                return nv, ntm, nu, pred, count, fs

            # the fused branch scatters in-kernel and never touches the
            # carried update buffer — a scalar placeholder keeps the
            # cond/loop carries shape-shared without allocating the
            # [capacity, th, tw] buffer (~64 MB/attr at bench scale)
            # the XLA branch actually needs
            upd0 = {a: (jnp.zeros((), dtype) if fused
                        else jnp.zeros((plan.capacity, th, tw), dtype))
                    for a in live}
            # one full-shard nonzero scan per RUN seeds the carried maps
            tmap0 = {a: act.tile_nonzero_map(values[a], plan)
                     for a in live}

            def body(i, carry):
                vals, tmaps, upds, fb, at, ff = carry
                new_v, new_t, new_u = dict(vals), dict(tmaps), dict(upds)
                for a, r in live.items():
                    (new_v[a], new_t[a], new_u[a], p, c, fs) = step_attr(
                        vals[a], tmaps[a], upds[a], r)
                    # serial-runner stats semantics (ops.active): fb
                    # counts dense-fallback EVENTS, at sums the dilated
                    # active-tile counts, ff the kernel-flagged steps —
                    # here per (shard, attr, step)
                    fb = fb + p.astype(jnp.int32)
                    at = at + c.astype(jnp.float32)
                    ff = ff + fs
                return new_v, new_t, new_u, fb, at, ff

            # n is a TRACED scalar: one compile serves every step count
            out, _, _, fb, at, ff = lax.fori_loop(
                0, n, body, (values, tmap0, upd0, jnp.int32(0),
                             jnp.float32(0), jnp.int32(0)))
            # one collective for all counters (psum over the tuple)
            fb, at, ff = lax.psum((fb, at, ff), names)
            if fused:
                return out, (fb, at, ff)
            return out, (fb, at)

        stat_spec = (P(), P(), P()) if fused else (P(), P())
        sharded = shard_map(shard_fn, mesh=mesh, in_specs=(spec, P()),
                            out_specs=(spec, stat_spec),
                            check_vma=False if fused else None)
        return jax.jit(sharded), plan, len(live), nx * ny

    def _build_ir_runner(self, model, space: CellularSpace):
        """Per-shard runner for nonlinear Flow IR models: one ppermute
        VALUE exchange per step for the channels some ring-1 term reads
        (the term footprints say which — budget/pointwise channels never
        ship), then the SAME registered lowering the serial dense step
        runs, in its ghost-padded context (``ir.lower.padded_apply``).
        Value-exchange keeps the result bitwise equal to the serial
        step: a ghost cell's outflow/share is recomputed here from the
        same operands with the same expression the owning shard uses
        (the ``ops.active`` discipline), and ghost cells beyond the
        partition are masked to zero — the serial zero-pad semantics."""
        from jax import lax

        from ..ir.lower import StepMeta, involved_channels, padded_apply
        from ..ops.stencil import neighbor_counts_traced

        model._validate_space(space)
        terms = model.ir_terms
        rates = model.term_rates()
        missing = sorted(involved_channels(terms) - set(space.values))
        if missing:
            raise ValueError(f"space is missing IR channels {missing}")
        mesh = self.mesh
        names, nx, ny, local_h, local_w = self._shard_geometry(space)
        offsets = tuple(model.offsets)
        gshape = space.global_shape
        x_init, y_init = space.x_init, space.y_init
        dtype = space.dtype
        spec = grid_spec(mesh)
        ring_chs = sorted(set().union(
            *(t.reads() for t in terms if t.footprint >= 1)) or set())

        if self.halo_mode == "zero":
            def pad(z):  # diagnostic: no inter-shard traffic
                return jnp.pad(z, 1)
        elif len(names) == 1:
            def pad(z):
                return pad_with_halo_1d(z, names[0], nx)
        else:
            def pad(z):
                return pad_with_halo_2d(z, names[0], names[1], nx, ny)

        def shard_fn(values, n):
            row0 = np.int32(x_init) + lax.axis_index(names[0]) * np.int32(
                local_h)
            col0 = (np.int32(y_init)
                    + lax.axis_index(names[1]) * np.int32(local_w)
                    if len(names) > 1 else jnp.int32(y_init))
            meta = StepMeta(shape=(local_h, local_w), origin=(row0, col0),
                            global_shape=gshape, dtype=dtype,
                            offsets=offsets)
            PH, PW = local_h + 2, local_w + 2
            # partition-bounds mask over the padded shard (ghosts beyond
            # the true grid/partition shed nothing — the serial
            # zero-pad's bitwise twin) + global-true clamped counts
            rowg = (row0 - np.int32(1)) + lax.broadcasted_iota(
                jnp.int32, (PH, PW), 0)
            colg = (col0 - np.int32(1)) + lax.broadcasted_iota(
                jnp.int32, (PH, PW), 1)
            mask_pb = ((rowg >= np.int32(x_init))
                       & (rowg < np.int32(x_init) + np.int32(space.dim_x))
                       & (colg >= np.int32(y_init))
                       & (colg < np.int32(y_init) + np.int32(space.dim_y)))
            counts_pad = jnp.maximum(
                neighbor_counts_traced(
                    (PH, PW), offsets,
                    (row0 - np.int32(1), col0 - np.int32(1)), gshape,
                    dtype),
                jnp.asarray(1, dtype))

            def body(i, c):
                padded = {k: pad(c[k]) for k in ring_chs}
                return padded_apply(terms, c, padded, rates, meta,
                                    counts_pad, mask_pb)

            # n is a TRACED scalar: one compile serves every step count
            return lax.fori_loop(0, n, body, values)

        sharded = shard_map(shard_fn, mesh=mesh, in_specs=(spec, P()),
                            out_specs=spec)
        return jax.jit(sharded)

    def _build_runner(self, model, space: CellularSpace):
        mesh = self.mesh
        names, nx, ny, local_h, local_w = self._shard_geometry(space)
        offsets = model.offsets
        field_flows = [f for f in model.flows if not isinstance(f, PointFlow)]
        spec = grid_spec(mesh)

        # Footprint enforcement (round-2 VERDICT weak #4): a flow whose
        # outflow reads neighbors would silently miscompute per shard, so
        # undeclared footprints are refused here, and declared ring1 flows
        # get halo-padded channels instead.
        undeclared = sorted({type(f).__name__ for f in field_flows
                             if f.footprint not in ("pointwise", "ring1")})
        if undeclared:
            raise ValueError(
                f"ShardMapExecutor cannot prove flows {undeclared} are "
                "shardable: declare footprint='pointwise' (outflow reads "
                "only the cell's own channels) or footprint='ring1' + "
                "outflow_padded (reads the 3x3 neighborhood; inputs are "
                "halo-exchanged). Undeclared flows run correctly under "
                "SerialExecutor and AutoShardedExecutor.")
        any_ring1 = any(f.footprint == "ring1" for f in field_flows)

        if self.halo_mode == "zero":
            def pad(z):  # diagnostic: no inter-shard traffic (see __init__)
                return jnp.pad(z, 1)
        elif len(names) == 1:
            def pad(z):
                return pad_with_halo_1d(z, names[0], nx)
        else:
            def pad(z):
                return pad_with_halo_2d(z, names[0], names[1], nx, ny)

        # global bounds / origin: the sharded space may itself be a
        # partition of a larger grid — boundary topology follows the TRUE
        # grid edges, exactly like the numpy counts did
        gshape = space.global_shape
        x_init, y_init = space.x_init, space.y_init
        dtype = space.dtype

        point_flows = [f for f in model.flows if isinstance(f, PointFlow)]

        def point_outflows(outflows, values, row0, col0):
            """SPARSE per-shard point-flow outflows: one O(1) scatter per
            flow into the shard owning the source (everyone else's masked
            amount is 0), replacing the former dense one-hot rate fields
            — no O(grid) extra operand, no per-step field multiply (the
            serial path's ``point_flow_step`` economics, sharded). The
            owner test (``Model.hpp:176``) is the ``inside`` mask;
            cross-shard delivery still rides the ordinary share halo."""
            for f in point_flows:
                x, y = f.source_xy  # static global coords
                lx = jnp.int32(x) - row0
                ly = jnp.int32(y) - col0
                inside = ((lx >= 0) & (lx < local_h)
                          & (ly >= 0) & (ly < local_w))
                lxc = jnp.clip(lx, 0, local_h - 1)
                lyc = jnp.clip(ly, 0, local_w - 1)
                if f.frozen_source_value is not None:
                    amt = jnp.asarray(f.flow_rate * f.frozen_source_value,
                                      dtype=dtype)
                else:
                    amt = jnp.asarray(f.flow_rate, dtype=dtype) \
                        * values[f.attr][lxc, lyc]
                amt = jnp.where(inside, amt, jnp.zeros((), dtype))
                base = outflows.get(f.attr)
                if base is None:
                    base = jnp.zeros((local_h, local_w), dtype)
                outflows[f.attr] = base.at[lxc, lyc].add(amt)
            return outflows

        def local_step(values, counts, row0, col0):
            new = dict(values)
            origin = (row0, col0)
            padded_vals = (
                {k: pad(v) for k, v in values.items()} if any_ring1 else None)
            outflows: dict[str, jax.Array] = {}
            for f in field_flows:
                if f.footprint == "ring1":
                    o = f.outflow_padded(padded_vals, origin)
                else:
                    # origin is the shard's global offset (traced) — the
                    # serial path passes the space's origin the same way
                    o = f.outflow(values, origin)
                outflows[f.attr] = outflows.get(f.attr, 0.0) + o
            outflows = point_outflows(outflows, values, row0, col0)
            for attr, outflow in outflows.items():
                share = outflow / counts
                # analysis: ignore[hardcoded-physics] — the legacy
                # share-exchanging flow shard step (general flows +
                # point scatters); nonlinear IR models run
                # _build_ir_runner's registered lowering instead
                inflow = gather_from_padded(pad(share), offsets)
                new[attr] = values[attr] - outflow + inflow
            return new

        def shard_fn(values, n):
            from jax import lax

            from ..ops.stencil import neighbor_counts_traced
            row0 = np.int32(x_init) + lax.axis_index(names[0]) * np.int32(local_h)
            col0 = (np.int32(y_init) + lax.axis_index(names[1]) * np.int32(local_w)
                    if len(names) > 1 else jnp.int32(y_init))
            # per-shard counts as traced iota arithmetic — no O(grid)
            # host array, no extra sharded operand (mirrors make_step)
            counts = neighbor_counts_traced((local_h, local_w), offsets,
                                            (row0, col0), gshape, dtype)

            # n is a TRACED scalar: every step count runs one compile
            return lax.fori_loop(
                0, n, lambda i, c: local_step(c, counts, row0, col0), values)

        sharded = shard_map(
            shard_fn, mesh=mesh,
            in_specs=(spec, P()),
            out_specs=spec)
        return jax.jit(sharded)
