"""Collectives: typed replacements for the reference's hand-rolled loops.

The reference implements every multi-rank pattern from blocking
``MPI_Send``/``MPI_Recv``: pseudo-scatter (``Model.hpp:70-76``),
pseudo-bcast (``:84-86``), pseudo-reduce (``:88-92``), pseudo-gather
(``:110-130``) — no MPI collectives anywhere (SURVEY §2). Here each becomes
the real XLA collective over ICI:

- scatter  → ``parallel.mesh.shard_space`` (device_put with NamedSharding)
- bcast    → replicated pytree args under jit (flow params are traced
  scalars; no control messages exist)
- reduce   → ``global_sum`` (``psum`` inside shard_map, or plain ``jnp.sum``
  on a sharded array, which XLA lowers to an all-reduce)
- gather   → ``gather_to_host`` (process-0 host fetch of the global array)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def global_sum(local: jax.Array, axis_names) -> jax.Array:
    """Shard-local sum + psum over mesh axes: the conservation reduction
    (``Model.hpp:88-95,238-243``) as one collective."""
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    s = jnp.sum(local)
    for ax in axis_names:
        s = lax.psum(s, ax)
    return s


def gather_to_host(x: jax.Array) -> np.ndarray:
    """Fetch a (possibly sharded) global array to host memory — the typed
    equivalent of the reference's per-rank file merge (``Model.hpp:110-131``).
    Cross-host shardings route through the multi-host gather."""
    if isinstance(x, jax.Array) and jax.process_count() > 1:
        from .multihost import gather_global
        return gather_global(x)
    return np.asarray(jax.device_get(x))
