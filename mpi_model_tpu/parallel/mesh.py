"""Device meshes and grid sharding: the decomposition layer.

Rebuild of the reference's process-grid decomposition — 1-D row striping
(``/root/reference/src/Model.hpp:62-76``, ``Defines.hpp:8``) and the 2-D
``LINES_REC × COLUMNS_REC`` block grid (``ModelRectangular.hpp:69-80``,
``DefinesRectangular.hpp:7-8``) — as ``jax.sharding.Mesh`` construction plus
``NamedSharding`` placement. ``shard_space`` is the live realization of the
reference's *intended* ``CellularSpace::Scatter`` (dead code at
``CellularSpace.hpp:36-79``): distribution as an operation on the data
structure, not string messages inlined in the model. There is no master
rank holding metadata only — every device holds a block of the one global
``jax.Array``, and XLA moves data over ICI.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.cellular_space import CellularSpace


def _devices(devices=None):
    if devices is not None:
        return list(devices)
    # Honor an explicitly pinned default device (e.g. tests pin "cpu" while
    # the image force-registers a TPU backend).
    dd = jax.config.jax_default_device
    if dd is not None:
        platform = dd if isinstance(dd, str) else dd.platform
        return jax.devices(platform)
    return jax.devices()


def make_mesh(n: Optional[int] = None, axis: str = "x",
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``n`` devices — the row-striping decomposition
    (the reference's NWORKERS stripes, ``Defines.hpp:7-8``)."""
    devs = _devices(devices)
    n = len(devs) if n is None else n
    return Mesh(np.array(devs[:n]), (axis,))


def factor2d(n: int) -> tuple[int, int]:
    """Most-square (lines, columns) factorization of n devices."""
    best = (1, n)
    for a in range(1, int(math.isqrt(n)) + 1):
        if n % a == 0:
            best = (a, n // a)
    return best


def resolve_grid2d(lines: Optional[int], columns: Optional[int],
                   n: int) -> tuple[int, int]:
    """The (lines, columns) a 2-D decomposition of ``n`` devices resolves
    to: most-square factorization when both are None, ``n // given``
    one-sided. THE single source of this defaulting — ``make_mesh_2d``
    and ``ModelRectangular``'s partition geometry both call it, so the
    owner/output block map can never diverge from the mesh."""
    if lines is None and columns is None:
        return factor2d(n)
    if lines is None:
        return n // columns, columns
    if columns is None:
        return lines, n // lines
    return lines, columns


def make_mesh_2d(lines: Optional[int] = None, columns: Optional[int] = None,
                 axes: tuple[str, str] = ("x", "y"),
                 devices: Optional[Sequence] = None) -> Mesh:
    """2-D mesh — the block decomposition (``DefinesRectangular.hpp:7-8``:
    LINES_REC × COLUMNS_REC). Defaults to the most-square factorization of
    the available device count."""
    devs = _devices(devices)
    lines, columns = resolve_grid2d(lines, columns, len(devs))
    n = lines * columns
    if n == 0 or n > len(devs):
        raise ValueError(
            f"mesh {lines}x{columns} needs {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(lines, columns), axes)


def grid_spec(mesh: Mesh) -> P:
    """PartitionSpec sharding grid rows over the first mesh axis and (for
    2-D meshes) columns over the second."""
    names = mesh.axis_names
    return P(names[0], names[1] if len(names) > 1 else None)


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh includes devices owned by other processes
    (multi-host: a 'rank' is a process, SURVEY §5)."""
    pidx = jax.process_index()
    return any(d.process_index != pidx for d in mesh.devices.flat)


def put_global(value, sharding) -> jax.Array:
    """``device_put`` that also works when the sharding spans other
    processes' devices: each process supplies its addressable shards from
    its (identical) host copy — the multi-host scatter. Single-process
    shardings take the plain device_put path; values ALREADY sharded
    across processes are kept on-device (resharded via a compiled
    identity when the layout differs) instead of a crashing device_get."""
    mesh = getattr(sharding, "mesh", None)
    if mesh is None or isinstance(mesh, Mesh) and not mesh_spans_processes(mesh):
        return jax.device_put(value, sharding)
    if isinstance(value, jax.Array) and not value.is_fully_addressable:
        if value.sharding == sharding:
            return value
        return jax.jit(lambda x: x, out_shardings=sharding)(value)
    npv = np.asarray(jax.device_get(value))
    return jax.make_array_from_callback(npv.shape, sharding,
                                        lambda idx: npv[idx])


def shard_space(space: CellularSpace, mesh: Mesh,
                spec: Optional[P] = None) -> CellularSpace:
    """Place the space's channels onto the mesh (the live ``Scatter``).

    Requires dims divisible by the mesh extent along each sharded axis
    (XLA's tiled sharding), which generalizes the reference's compile-time
    ``PROC_DIMX = DIMX/NWORKERS`` divisibility assumption.
    """
    spec = grid_spec(mesh) if spec is None else spec
    sharding = NamedSharding(mesh, spec)
    vals = {k: put_global(v, sharding) for k, v in space.values.items()}
    return space.with_values(vals)
