"""Multi-host scaffolding: the DCN control plane (BASELINE config 5).

The reference scales across nodes with ``mpirun`` + per-rank
``MPI_Init``/``MPI_Comm_rank`` (``/root/reference/src/Main.cpp:21-23``)
and funnels every result through the master rank. TPU-native equivalent
(SURVEY §5 "distributed communication backend"): one Python process per
host, linked by ``jax.distributed`` — after ``initialize()`` every
process sees the GLOBAL device set, a ``Mesh`` spans hosts, ``shard_map``
collectives ride ICI within a slice and DCN across slices, and process 0
plays the master for host-side gather/report/output.

Testable without hardware: two local processes with virtual CPU devices
form a real 2-process jax.distributed cluster (``dryrun_two_process``,
exercised by tests/test_multihost.py).
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

import numpy as np


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids=None) -> None:
    """Join (or form) the multi-process cluster.

    Thin wrapper over ``jax.distributed.initialize`` that is a NO-OP when
    the cluster is already initialized or when nothing indicates a
    multi-process launch (no args, no ``JAX_COORDINATOR_ADDRESS`` /
    TPU-pod metadata) — so single-host runs can call it unconditionally,
    the way the reference always calls ``MPI_Init``.
    """
    import jax

    try:
        already = jax.distributed.is_initialized()
    except AttributeError:  # very old jax without the public probe
        already = getattr(jax._src.distributed.global_state, "client",
                          None) is not None
    if already:
        return
    env_coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None and env_coord is None \
            and num_processes is None:
        return  # single-process run
    jax.distributed.initialize(
        coordinator_address=coordinator_address or env_coord,
        num_processes=num_processes, process_id=process_id,
        local_device_ids=local_device_ids)


def process_index() -> int:
    """This process's rank (the reference's ``comm_rank``)."""
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def is_master() -> bool:
    """Process 0 — the reference's MASTER rank (``Defines.hpp:10``)."""
    return process_index() == 0


def host_local_to_global(local_np, mesh, spec):
    """Assemble per-host shards into one global sharded array (the typed
    replacement for the reference's descriptor-scatter, ``Model.hpp:62-76``)."""
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(
        local_np, mesh, spec)


def gather_global(x) -> np.ndarray:
    """Fetch a (possibly cross-host sharded) array to every host as
    numpy — the master-side merge (``Model.hpp:110-131``). For
    single-process runs this is a plain device_get."""
    import jax
    if jax.process_count() == 1:
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P
    mesh = getattr(getattr(x, "sharding", None), "mesh", None)
    if mesh is not None and getattr(mesh, "devices", None) is not None:
        # global sharded array → fully-replicated host-local copy
        return np.asarray(multihost_utils.global_array_to_host_local_array(
            x, mesh, P(*([None] * x.ndim))))
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def all_agree(flag: bool) -> bool:
    """True iff EVERY process passes True — a collective vote (allgather
    + min; single-process: identity). Use before a cluster-wide commit
    whose per-process preparation can fail: raising on one process while
    the others enter a barrier strands them until the heartbeat kills
    the job, whereas a vote lets every process raise (or commit)
    together."""
    import jax
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils
    out = multihost_utils.process_allgather(
        np.asarray([1 if flag else 0], np.int32))
    return bool(np.asarray(out).min())


def sync(name: str = "barrier") -> None:
    """Cross-process barrier (no-op single-process)."""
    import jax
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


import contextlib as _contextlib  # noqa: E402


@_contextlib.contextmanager
def master_only(barrier_name: str):
    """Master-write-with-barrier idiom, encoded once: the body runs on
    process 0 only, and EVERY process reaches the barrier even when the
    master's body raises — a disk error on the master propagates instead
    of stranding workers in ``sync`` until the cluster heartbeat kills
    them. Usage::

        with master_only("checkpoint-save") as master:
            if master:
                ...write files...
    """
    try:
        yield is_master()
    finally:
        sync(barrier_name)


def broadcast_str(s: str, max_len: int = 256) -> str:
    """Process 0's string, delivered to every process (single-process:
    identity). Used for values that must agree cluster-wide but are
    derived from per-process state — e.g. a wall-clock-stamped output
    filename."""
    import jax
    raw = s.encode("utf-8")
    if len(raw) > max_len:
        # truncating would silently corrupt a cluster-wide value (e.g. a
        # long output path used by every process)
        raise ValueError(
            f"broadcast_str: string is {len(raw)} bytes UTF-8, exceeding "
            f"max_len={max_len}; pass a larger max_len")
    if jax.process_count() == 1:
        return s
    from jax.experimental import multihost_utils
    buf = np.zeros(max_len, np.uint8)
    buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    return bytes(np.asarray(out)).rstrip(b"\x00").decode("utf-8")


# -- two-local-process CPU dryrun (the hardware-free config-5 rig) -----------

_WORKER = r"""
import sys
sys.path.insert(0, {root!r})
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize overrides the env var
from mpi_model_tpu.parallel import multihost
multihost.initialize("127.0.0.1:{port}", num_processes=2,
                     process_id={pid})
import numpy as np
from jax.sharding import Mesh
from mpi_model_tpu import CellularSpace, Diffusion, Model, PointFlow
from mpi_model_tpu.parallel import ShardMapExecutor
from mpi_model_tpu.parallel.collectives import gather_to_host

assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 8, devs  # 4 virtual CPU devices per process
mesh = Mesh(np.array(devs).reshape(2, 4), ("x", "y"))

h, w = 16, 32
space = CellularSpace.create(h, w, 1.0, dtype="float32")
# a point source on a block edge: its share crosses a process boundary
model = Model([Diffusion(0.2), PointFlow(source=(7, 15), flow_rate=0.5)],
              3.0, 1.0)
# the REAL product path: Model.execute with its conservation contract,
# over a mesh spanning both processes (SPMD: identical program each rank)
out, report = model.execute(space, ShardMapExecutor(mesh))
assert report.comm_size == 8, report
full = gather_to_host(out.values["value"])
assert full.shape == (h, w)
assert np.isfinite(full).all()

# multihost checkpoint: every process participates in the gather, only
# process 0 writes, all barrier — then both processes restore and see
# identical bytes (shared filesystem on one host)
import os as _os
from mpi_model_tpu.io import load_checkpoint, save_checkpoint
ckpt_path = _os.path.join({ckpt_dir!r}, "mh_ckpt.npz")
save_checkpoint(ckpt_path, out, step=3)
assert _os.path.exists(ckpt_path), "checkpoint missing after save barrier"
ck = load_checkpoint(ckpt_path)
assert ck.step == 3
np.testing.assert_array_equal(np.asarray(ck.space.values["value"]), full)

# SHARDED checkpoint: each process writes only its addressable shards (no
# full-grid gather anywhere on the save path), restore re-shards onto the
# same mesh and every local shard must match bitwise — O(shard) both ways
from mpi_model_tpu.io import load_checkpoint_sharded, save_checkpoint_sharded
sck_path = _os.path.join({ckpt_dir!r}, "mh_sharded.ckpt")
save_checkpoint_sharded(sck_path, out, step=3)
sck = load_checkpoint_sharded(sck_path, mesh=mesh)
assert sck.step == 3
def _by_index(arr):
    return {{tuple((sl.start, sl.stop) for sl in s.index): np.asarray(s.data)
             for s in arr.addressable_shards}}
orig_shards = _by_index(out.values["value"])
rest_shards = _by_index(sck.space.values["value"])
assert orig_shards.keys() == rest_shards.keys(), "local shard layout differs"
for idx in orig_shards:
    np.testing.assert_array_equal(orig_shards[idx], rest_shards[idx])

# ASYNC sharded checkpoints across the process boundary: staged writes,
# deferred commit at the next save/flush, commit-by-vote — the staged
# step must be invisible cluster-wide until committed
from mpi_model_tpu.io import CheckpointManager
amgr = CheckpointManager(_os.path.join({ckpt_dir!r}, "amgr"),
                         layout="sharded", async_writes=True)
amgr.save(out, step=3)
assert amgr.steps() == [], amgr.steps()   # staged, uncommitted
amgr.save(out, step=6)                    # commits 3
assert amgr.steps() == [3], amgr.steps()
amgr.flush()
assert amgr.steps() == [3, 6], amgr.steps()
ack = amgr.latest(mesh=mesh)
def _shards_match(a, b):
    for idx in _by_index(a):
        np.testing.assert_array_equal(_by_index(a)[idx], _by_index(b)[idx])
_shards_match(out.values["value"], ack.space.values["value"])

# the full config-5 software stack across the process boundary: fused
# Pallas shard step (interpret resolved from the CPU mesh) + depth-2 deep
# halos, golden-compared against the XLA shard step over DCN
pal_model = Model(Diffusion(0.25), 4.0, 1.0)
pal_exec = ShardMapExecutor(mesh, step_impl="pallas", halo_depth=2)
pal_out, _ = pal_model.execute(space, pal_exec)
assert pal_exec.last_impl == "pallas", pal_exec.last_impl
xla_exec = ShardMapExecutor(mesh, step_impl="xla", halo_depth=2)
xla_out, _ = pal_model.execute(space, xla_exec)
pal_full = gather_to_host(pal_out.values["value"])
xla_full = gather_to_host(xla_out.values["value"])
np.testing.assert_allclose(pal_full, xla_full, atol=1e-5, rtol=1e-5)

# output pipeline: filename is the MASTER's (broadcast — wall clocks may
# skew across hosts), process 0 writes, all barrier; every process must
# see the same existing file
from mpi_model_tpu.io import write_output
merged = write_output({ckpt_dir!r}, out, comm_size=2)
assert _os.path.exists(merged), merged

multihost.sync("after-run")
if multihost.is_master():
    # master-side conservation report (Model.hpp:88-95)
    print(f"MASTER ok: procs={{jax.process_count()}} "
          f"total={{float(full.sum())}} "
          f"conservation_err={{report.conservation_error():.3e}} "
          f"ckpt=saved sharded_ckpt=ok async_ckpt=ok "
          f"pallas_deep_halo=ok", flush=True)
else:
    print(f"worker {{multihost.process_index()}} done", flush=True)
"""


_SUPKILL_WORKER = r"""
import os, sys
sys.path.insert(0, {root!r})
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize overrides the env var
jax.config.update("jax_enable_x64", True)  # real f64: bitwise resume proof
from mpi_model_tpu.parallel import multihost
multihost.initialize("127.0.0.1:{port}", num_processes={nprocs},
                     process_id={pid})
import numpy as np
from jax.sharding import Mesh
from mpi_model_tpu import CellularSpace, Diffusion, Model, PointFlow
from mpi_model_tpu.io import CheckpointManager
from mpi_model_tpu.io.checkpoint import run_checkpointed
from mpi_model_tpu.parallel import ShardMapExecutor
from mpi_model_tpu.parallel.collectives import gather_to_host

N = {nprocs}
assert jax.process_count() == N, jax.process_count()
devs = jax.devices()
assert len(devs) == 2 * N, devs  # 2 virtual CPU devices per process
mesh = Mesh(np.array(devs).reshape(N, 2), ("x", "y"))

h, w = 4 * N, 32
space = CellularSpace.create(h, w, 1.0, dtype="float64")
# the point source sits on a shard corner: its Moore shares cross BOTH
# mesh axes (and hence process boundaries) every step
model = Model([Diffusion(0.2), PointFlow(source=(h // 2 - 1, 15),
                                         flow_rate=0.5)], 10.0, 1.0)
mgr = CheckpointManager({ckpt_dir!r}, layout="sharded")
ex = ShardMapExecutor(mesh)

if {phase} == 1:
    class CrashingExecutor:
        '''Rank {kill_rank} dies HARD after computing the third chunk
        (steps 5-6) but BEFORE its checkpoint commits — real work is
        lost past the last durable step. Peers stop at the same logical
        point with a distinct status (the cluster manager's teardown of
        a job that lost a rank; detection itself is covered by the
        supervisor health checks and the native RecvTimeout).'''

        def __init__(self, inner):
            self._inner = inner
            self._steps = 0

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def run_model(self, model, space, n):
            out = self._inner.run_model(model, space, n)
            self._steps += n
            if self._steps >= 6:
                jax.block_until_ready(out)
                if multihost.process_index() == {kill_rank}:
                    print("rank {kill_rank} dying mid-run", flush=True)
                    os._exit(17)
                # survivors LINGER so the victim's death is what ends the
                # job: jax's coordination service then aborts them (its
                # failure propagation working as designed) or, if its
                # reaction is slow, they stop cleanly — either way the
                # rank-death teardown is the cluster runtime's, not a
                # choreographed simultaneous exit (which raced the
                # watchdog and could kill the victim before ITS exit)
                import time as _t
                _t.sleep(6.0)
                print(f"survivor {{multihost.process_index()}} torn down",
                      flush=True)
                os._exit(0)
            return out

    run_checkpointed(model, space, mgr, steps=10, every=2,
                     executor=CrashingExecutor(ex))
    raise AssertionError("phase 1 must die inside the crash chunk")

# ---- phase 2: a fresh cluster resumes the SAME checkpoint directory ----
committed = mgr.steps()
assert committed == [0, 2, 4], committed  # step 6 died before commit
out, step, report = run_checkpointed(model, space, mgr, steps=10, every=2,
                                     executor=ex)
assert step == 10, step
full = gather_to_host(out.values["value"])

# ground truth: the SAME run uninterrupted (chunked identically), fresh
ex_ref = ShardMapExecutor(mesh)
ref_space = CellularSpace.create(h, w, 1.0, dtype="float64")
cur = ref_space
for s in range(0, 10, 2):
    cur, _ = model.execute(cur, ex_ref, steps=2, check_conservation=False)
ref_full = gather_to_host(cur.values["value"])
np.testing.assert_array_equal(full, ref_full)  # resume is BITWISE exact

multihost.sync("after-resume")
if multihost.is_master():
    err = abs(float(full.sum()) - float(h * w))
    assert err < 1e-9, err
    print(f"MASTER ok: procs={{N}} resumed_from={{committed[-1]}} "
          f"final_step={{step}} conservation_err={{err:.3e}} "
          f"bitwise_resume=ok", flush=True)
else:
    print(f"worker {{multihost.process_index()}} done", flush=True)
"""


def probe_free_port() -> int:
    """A coordinator port the OS just proved bindable: bind to port 0,
    read the assignment, close. Replaces the old pid-derived arithmetic
    (``30100 + pid % 350``), whose collisions across suite runs /
    TIME_WAIT remnants the tests had to paper over with retries
    (round-5 VERDICT weak #3). The close→reuse window is a benign race:
    nothing else on the rig is grabbing ephemeral ports at this rate,
    and a genuine collision still surfaces as the cluster-formation
    error it always was instead of being masked by a hardcoded retry."""
    import socket

    # analysis: ignore[raw-transport] — a bind-probe for a free
    # coordinator port (open, bind :0, read, close); no bytes are
    # exchanged, so there is nothing for the wire codec to frame
    s = socket.socket()
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return int(s.getsockname()[1])
    finally:
        s.close()


def _launch_workers(codes: list, timeout: int, devices_per_proc: int = 4):
    """Spawn one subprocess per code string (virtual-CPU jax rig); return
    [(rc, stdout, stderr), ...] in order."""
    procs = []
    for code in codes:
        env = dict(os.environ)
        env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{devices_per_proc}")
        env.pop("JAX_PLATFORMS", None)
        # analysis: ignore[raw-transport] — the multihost DRYRUN rig:
        # workers talk through jax's own distributed runtime, not the
        # fleet wire; the rig predates (and is orthogonal to) serving
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return outs


def dryrun_supervised_kill(nprocs: int = 4, kill_rank: int = 2,
                           port: Optional[int] = None,
                           timeout: int = 300) -> str:
    """Failure injection across REAL process boundaries (round-4 VERDICT
    task 7): an ``nprocs``-process jax.distributed cluster runs a
    supervised, sharded-checkpointed simulation; rank ``kill_rank`` dies
    hard mid-run AFTER computing steps past the last durable checkpoint
    (that work is genuinely lost); then a fresh cluster resumes the same
    checkpoint directory via ``run_checkpointed`` and must complete with
    BITWISE-identical state to an uninterrupted run — the full
    resilience story where ranks actually die, not just clean-path
    save/restore. Returns the phase-2 master's report line."""
    import tempfile

    if nprocs < 2:
        raise ValueError("dryrun_supervised_kill needs >= 2 processes")
    if not 0 <= kill_rank < nprocs:
        raise ValueError(f"kill_rank {kill_rank} outside 0..{nprocs - 1}")
    explicit_port = port is not None
    if port is None:
        port = probe_free_port()
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    ckpt_dir = tempfile.mkdtemp(prefix="mmtpu_supkill_")
    try:
        def codes(phase, prt):
            return [_SUPKILL_WORKER.format(
                root=root, port=prt, pid=pid, nprocs=nprocs,
                kill_rank=kill_rank, ckpt_dir=ckpt_dir, phase=phase)
                for pid in range(nprocs)]

        # phase 1: the crash run. The victim's rc=17 proves the injection
        # fired; the SURVIVORS' exit status is deliberately unasserted —
        # they die however the cluster runtime reacts to a dead rank
        # (jax's coordination service aborts them, or they reach their
        # lingering clean stop first; both are legitimate teardowns and
        # the choice is timing-dependent under load).
        outs = _launch_workers(codes(1, port), timeout, devices_per_proc=2)
        rc_victim = outs[kill_rank][0]
        if rc_victim != 17 or "dying mid-run" not in outs[kill_rank][1]:
            raise RuntimeError(
                f"victim rank {kill_rank}: rc={rc_victim}, expected 17 "
                f"with the crash marker:\n{outs[kill_rank][1][-2000:]}\n"
                f"{outs[kill_rank][2][-2000:]}")

        # phase 2: fresh cluster on a freshly-probed port (phase 1's
        # port may sit in TIME_WAIT — the victim died hard), same
        # checkpoint directory. An explicit caller port keeps the old
        # deterministic port+1 so rigs that pin firewalls still can.
        port2 = (port + 1) if explicit_port else probe_free_port()
        outs = _launch_workers(codes(2, port2), timeout,
                               devices_per_proc=2)
        for pid, (rc, out, err) in enumerate(outs):
            if rc != 0:
                raise RuntimeError(
                    f"phase-2 rank {pid} failed (rc={rc}):\n"
                    f"{out[-2000:]}\n{err[-2000:]}")
        master_out = outs[0][1]
        if "MASTER ok" not in master_out:
            raise RuntimeError(f"no master report in: {master_out!r}")
        return master_out.strip().splitlines()[-1]
    finally:
        import shutil

        shutil.rmtree(ckpt_dir, ignore_errors=True)


def dryrun_two_process(port: Optional[int] = None, timeout: int = 300) -> str:
    """Launch a real 2-process jax.distributed cluster on this host (4
    virtual CPU devices each → one 2x4 global mesh), run a sharded step
    spanning both processes, and return the master's report line."""
    import tempfile

    if port is None:
        port = probe_free_port()  # bind-probed; see probe_free_port
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    ckpt_dir = tempfile.mkdtemp(prefix="mmtpu_mh_")
    try:
        procs = []
        for pid in (0, 1):
            env = dict(os.environ)
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            env.pop("JAX_PLATFORMS", None)
            code = _WORKER.format(root=root, port=port, pid=pid,
                                  ckpt_dir=ckpt_dir)
            # analysis: ignore[raw-transport] — the rank-death/resume
            # dryrun rig (see _launch_workers): jax distributed
            # runtime workers, not fleet members
            procs.append(subprocess.Popen(
                [sys.executable, "-c", code], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=timeout)
                outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        for rc, out, err in outs:
            if rc != 0:
                raise RuntimeError(
                    f"multihost dryrun worker failed (rc={rc}):\n"
                    f"{out[-2000:]}\n{err[-2000:]}")
        master_out = outs[0][1]
        if "MASTER ok" not in master_out:
            raise RuntimeError(f"no master report in: {master_out!r}")
        return master_out.strip().splitlines()[-1]
    finally:
        import shutil

        shutil.rmtree(ckpt_dir, ignore_errors=True)
