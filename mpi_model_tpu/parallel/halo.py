"""Halo (ghost-cell) exchange over the device mesh.

Rebuild of the reference's cross-rank ghost update — the owner of a
boundary source sends ``(count, value, y)`` to ``rank+1`` in three blocking
``MPI_Send``s and the neighbor adds into its first-row cells
(``/root/reference/src/Model.hpp:189-235``). TPU-native design: inside a
``shard_map``ped step, each shard ships its *edge rows/columns* to mesh
neighbors with ``jax.lax.ppermute`` over ICI — the same neighbor-shift
topology ring attention uses (SURVEY §5 long-context note). Non-periodic
boundaries fall out of ppermute's semantics: a device no permutation pair
targets receives **zeros**, which is exactly the zero-padding the stencil
expects at true grid edges.

The Moore (8-neighbor) corner problem on a 2-D mesh is solved with the
standard two-stage exchange: first swap edge *columns* along the y-axis,
then swap edge *rows of the column-augmented array* along the x-axis — the
corner cells ride along in the second stage, so no diagonal permutes are
needed (SURVEY §7 'hard parts').
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax


def _fwd_perm(n: int) -> list[tuple[int, int]]:
    """Pairs shipping shard i's data to shard i+1 (no wraparound)."""
    return [(i, i + 1) for i in range(n - 1)]


def _bwd_perm(n: int) -> list[tuple[int, int]]:
    """Pairs shipping shard i's data to shard i-1 (no wraparound)."""
    return [(i + 1, i) for i in range(n - 1)]


def exchange_halo_1d(local: jax.Array, axis_name: str, axis_size: int,
                     axis: int = 0, depth: int = 1
                     ) -> tuple[jax.Array, jax.Array]:
    """Return (before_halo, after_halo) slabs for a 1-D sharded dimension.

    ``before_halo`` is the neighbor-below's last ``depth`` rows (what the
    reference's rank r receives from r-1), ``after_halo`` the
    neighbor-above's first ``depth``. Edge shards receive zeros
    (non-periodic grid). ``depth > 1`` is the deep-halo exchange: one
    collective round supplies enough ghost cells for ``depth`` local
    steps (see ``ShardMapExecutor(halo_depth=...)``).
    """
    n = axis_size
    sz = local.shape[axis]
    last = lax.slice_in_dim(local, sz - depth, sz, axis=axis)
    first = lax.slice_in_dim(local, 0, depth, axis=axis)
    before = lax.ppermute(last, axis_name, _fwd_perm(n))
    after = lax.ppermute(first, axis_name, _bwd_perm(n))
    return before, after


def _chaos_ring(padded: jax.Array, depth: int) -> jax.Array:
    """Fault-injection seam (``resilience.inject``): while a halo fault
    is armed, perturb the received ghost rows of a freshly padded shard
    — the deterministic stand-in for a corrupted ppermute payload.
    Consulted at TRACE time only; unarmed it returns its input
    untouched, so the built jaxpr is identical to an uninstrumented one
    (asserted in tests/test_chaos.py)."""
    from ..resilience import inject

    eps = inject.halo_perturbation()
    if eps is None:
        return padded
    return padded.at[:depth, :].add(jnp.asarray(eps, padded.dtype))


def pad_with_halo_1d(local: jax.Array, axis_name: str, axis_size: int,
                     depth: int = 1) -> jax.Array:
    """[h, w] shard → [h+2d, w+2d]: row slabs exchanged with mesh
    neighbors via ppermute, columns zero-padded (unsharded dimension)."""
    before, after = exchange_halo_1d(local, axis_name, axis_size, axis=0,
                                     depth=depth)
    padded_rows = jnp.concatenate([before, local, after], axis=0)
    return _chaos_ring(jnp.pad(padded_rows, ((0, 0), (depth, depth))),
                       depth)


def pad_with_halo_2d(local: jax.Array, ax_name: str, ay_name: str,
                     nx: int, ny: int, depth: int = 1) -> jax.Array:
    """[h, w] shard → [h+2d, w+2d] with a full 8-neighbor (edge + corner)
    halo from the 2-D mesh: column slabs along ``ay`` first, then row
    slabs of the augmented array along ``ax`` so the d×d corner blocks
    ride along."""
    left, right = exchange_halo_1d(local, ay_name, ny, axis=1, depth=depth)
    aug = jnp.concatenate([left, local, right], axis=1)          # [h, w+2d]
    top, bottom = exchange_halo_1d(aug, ax_name, nx, axis=0,     # [d, w+2d]
                                   depth=depth)
    return _chaos_ring(
        jnp.concatenate([top, aug, bottom], axis=0), depth)      # [h+2d, w+2d]


def exchange_ring(local: jax.Array, ax_name: str, nx: int,
                  ay_name: str = None, ny: int = 1,
                  depth: int = 1) -> dict:
    """Depth-``d`` ghost ring for a shard as SEPARATE thin arrays (for
    the Pallas halo kernel, which needs aligned DMA sources, not a
    concatenated padded copy): ``n``/``s`` [d, w], ``w``/``e`` [h, d],
    corners [d, d]. Zeros at true grid edges (ppermute zero-fill / no
    mesh axis). Corner blocks ride the standard two-stage exchange: the
    column halos are swapped first, then row slabs *augmented with those
    columns' end strips* are swapped, so the diagonal neighbor's d×d
    corner arrives without diagonal permutes. ``depth > 1`` funds
    multi-step fusion inside the per-shard kernel (one exchange per
    ``depth`` fused steps)."""
    h, w = local.shape
    d = depth
    if ay_name is not None and ny > 1:
        left, right = exchange_halo_1d(local, ay_name, ny, axis=1, depth=d)
    else:
        left = jnp.zeros((h, d), local.dtype)
        right = jnp.zeros((h, d), local.dtype)
    top_strip = jnp.concatenate(
        [left[:d], local[:d], right[:d]], axis=1)       # [d, w+2d]
    bot_strip = jnp.concatenate(
        [left[-d:], local[-d:], right[-d:]], axis=1)
    if nx > 1:
        nfull = lax.ppermute(bot_strip, ax_name, _fwd_perm(nx))
        sfull = lax.ppermute(top_strip, ax_name, _bwd_perm(nx))
    else:
        nfull = jnp.zeros_like(top_strip)
        sfull = jnp.zeros_like(bot_strip)
    return {
        "n": nfull[:, d:w + d], "s": sfull[:, d:w + d],
        "w": left, "e": right,
        "nw": nfull[:, 0:d], "ne": nfull[:, w + d:w + 2 * d],
        "sw": sfull[:, 0:d], "se": sfull[:, w + d:w + 2 * d],
    }


def zero_ring(local: jax.Array, depth: int = 1) -> dict:
    """An all-zero ghost ring shaped like ``exchange_ring``'s output —
    the no-traffic stand-in used when measuring halo cost (and the
    boundary condition of a standalone full grid)."""
    h, w = local.shape
    d = depth

    def z(s):
        return jnp.zeros(s, local.dtype)

    return {"n": z((d, w)), "s": z((d, w)), "w": z((h, d)), "e": z((h, d)),
            "nw": z((d, d)), "ne": z((d, d)), "sw": z((d, d)),
            "se": z((d, d))}


def gather_from_padded(padded: jax.Array,
                       offsets: Sequence[tuple[int, int]]) -> jax.Array:
    """inflow[i, j] = Σ_d padded[1+i+dx, 1+j+dy] for an [h+2, w+2] padded
    share array — the shard-local form of ``ops.stencil.gather_neighbors``."""
    h, w = padded.shape[0] - 2, padded.shape[1] - 2
    inflow = None
    for dx, dy in offsets:
        piece = lax.slice(padded, (1 + dx, 1 + dy), (1 + dx + h, 1 + dy + w))
        inflow = piece if inflow is None else inflow + piece
    return inflow
