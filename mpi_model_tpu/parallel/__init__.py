from .mesh import make_mesh, make_mesh_2d, shard_space
from .halo import exchange_halo_1d, pad_with_halo_1d, pad_with_halo_2d
from .collectives import global_sum
from .executors import AutoShardedExecutor, ShardMapExecutor

__all__ = [
    "make_mesh",
    "make_mesh_2d",
    "shard_space",
    "exchange_halo_1d",
    "pad_with_halo_1d",
    "pad_with_halo_2d",
    "global_sum",
    "AutoShardedExecutor",
    "ShardMapExecutor",
]
