"""The ONE registered lowering from Flow IR terms to executable steps.

Every step engine — the dense XLA step, the composed path (linear terms
only; nonlinear forces k=1), the active-tile engine and the sharded
per-shard step — consumes terms through THIS module. The engines differ
only in the **context** they construct (how arrays are stored, padded
and gathered); the per-term physics is written exactly once, in the
``@register_lowering`` entry for that term kind, and composed out of
the context's three primitives:

- ``transport_update(channel, rate, weights)`` — the ring-1
  mass-conserving redistribution (``ops.stencil.transport``'s
  expression, term for term, in every context — the cross-impl
  bitwise-at-f64 gates in ``tests/test_ir.py`` pin this);
- ``apply_amount(channel, amount, sign)`` — a pointwise add/subtract;
- ``add_budget(channel, amount, sign)`` — integrate a declared
  source/sink's signed contribution into its hidden budget channel.

The registry is machine-checked: the jaxpr auditor's
``jaxpr-term-registry`` rule asserts every term kind has exactly one
lowering and that it lives HERE (no impl-private term branches), and
the astlint ``hardcoded-physics`` rule warns on new transport-shaped
arithmetic growing outside ``ir/``/``ops/`` — the four-way hand-
mirroring that motivated this subsystem cannot silently return.

All reads are PRE-STEP: every term's amounts are evaluated against the
step's input values, then applied sequentially in term order — the
summed-outflow discipline of the hand-written step, generalized.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..compat import optimization_barrier
from ..ops.stencil import (neighbor_counts_traced, shift2d, transport,
                           weighted_counts_traced)
from .expr import evaluate
from .terms import Sink, Source, Term, Transfer, Transport

# -- the registry -------------------------------------------------------------

#: term kind -> lowering (the audited single-lowering map)
LOWERINGS: dict[type, object] = {}


def register_lowering(term_cls: type):
    """Register the one lowering for ``term_cls``; a second registration
    is an error (the no-shadow half of the ``jaxpr-term-registry``
    contract)."""
    def deco(obj):
        if term_cls in LOWERINGS:
            raise ValueError(
                f"term kind {term_cls.__name__} already has a registered "
                f"lowering ({LOWERINGS[term_cls]!r}); every kind gets "
                "exactly one")
        LOWERINGS[term_cls] = obj
        return obj
    return deco


def lowering_for(term: Term):
    low = LOWERINGS.get(type(term))
    if low is None:
        raise TypeError(
            f"no registered lowering for term kind "
            f"{type(term).__name__} (register one in ir.lower — the "
            "jaxpr-term-registry rule audits this map)")
    return low


# -- step metadata ------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class StepMeta:
    """Static geometry the lowering closes over (the same identity the
    hand-written step builders key their caches with)."""

    shape: tuple[int, int]
    origin: tuple[int, int]
    global_shape: tuple[int, int]
    dtype: object
    offsets: tuple[tuple[int, int], ...]


def _opposite_weights(offsets, weights) -> list[float]:
    """weights reindexed by NEGATED offset: the tap a cell RECEIVES along
    ``d`` is the tap its neighbor SENDS along ``-d``. Weighted transport
    therefore needs a symmetric offset set (Moore/von Neumann are)."""
    idx = {(dx, dy): i for i, (dx, dy) in enumerate(offsets)}
    out = []
    for dx, dy in offsets:
        j = idx.get((-dx, -dy))
        if j is None:
            raise ValueError(
                f"weighted Transport needs a symmetric offset set; "
                f"offset ({dx}, {dy}) has no opposite in {tuple(offsets)}")
        out.append(weights[j])
    return out


# -- contexts -----------------------------------------------------------------

class _Ctx:
    """Shared context machinery: pre-step reads, sequential current-value
    accumulation, the pointwise primitives. Subclasses provide the
    geometry-specific ``transport_update``."""

    def __init__(self, pre: dict, meta: StepMeta):
        self.pre = pre          # channel -> pre-step array (interior view)
        self.cur = dict(pre)    # accumulates term applications in order
        self.meta = meta
        self.dtype = jnp.dtype(meta.dtype)

    def env(self) -> dict:
        return self.pre

    def apply_amount(self, channel: str, amount, sign: int) -> None:
        if sign >= 0:
            self.cur[channel] = self.cur[channel] + amount
        else:
            self.cur[channel] = self.cur[channel] - amount

    def add_budget(self, channel: str, amount, sign: int) -> None:
        if channel not in self.cur:
            raise ValueError(
                f"budget channel {channel!r} missing from the space — "
                "build IR spaces with FlowIRModel.create_space (or "
                "with_budget_channels) so declared sources/sinks have "
                "their integration accumulator")
        self.apply_amount(channel, amount, sign)

    def transport_update(self, channel, rate_c, weights) -> None:
        raise NotImplementedError


class DenseCtx(_Ctx):
    """Full-grid arrays; uniform transport IS the hand-written
    ``ops.stencil.transport`` call — the bitwise single-source-of-truth
    anchor the diffusion re-expression gate checks."""

    def __init__(self, pre: dict, meta: StepMeta, counts):
        super().__init__(pre, meta)
        self.counts = counts

    def transport_update(self, channel, rate_c, weights) -> None:
        # the barrier materializes the outflow once: its VALUE already
        # equals the hand-written step's (outflow has two consumers —
        # the subtraction and the share division — so it was never fma-
        # contracted), but pinning it keeps the value stable when this
        # same lowering compiles inside other fusion contexts (the
        # active engine's lax.cond fallback, the vmapped ensemble step)
        outflow = optimization_barrier(rate_c * self.pre[channel])
        if weights is None:
            self.cur[channel] = transport(self.cur[channel], outflow,
                                          self.counts, self.meta.offsets)
            return
        offsets = self.meta.offsets
        wcnt = weighted_counts_traced(
            self.meta.shape, offsets, weights, self.meta.origin,
            self.meta.global_shape, self.dtype)
        # a STRANDED cell (every in-bounds tap has zero weight, e.g. a
        # one-direction weight set at the boundary) has nowhere to
        # shed: it sheds NOTHING — masking before the clamped divide
        # keeps the term conserving and finite (an unclamped divide
        # would spread inf/NaN; a clamped-but-unmasked one leaks mass).
        # The padded/window contexts apply the identical rule.
        shed = jnp.where(wcnt > 0, outflow, jnp.asarray(0, self.dtype))
        share = shed / jnp.maximum(wcnt, jnp.asarray(1, self.dtype))
        w_opp = _opposite_weights(offsets, weights)
        inflow = jnp.zeros_like(share)
        for w, (dx, dy) in zip(w_opp, offsets):
            inflow = inflow + jnp.asarray(w, self.dtype) * shift2d(
                share, dx, dy)
        self.cur[channel] = self.cur[channel] - shed + inflow


class PaddedCtx(_Ctx):
    """Per-shard ghost-ring context (ShardMapExecutor): transport
    channels arrive one-cell padded with REAL neighbor-shard values
    (zeros beyond the true grid); outflow is computed on the padded
    array and masked to the partition, so a ghost cell's share equals
    the value the owning shard computes — the value-exchange bitwise
    argument of the active engine (``ops.active``)."""

    def __init__(self, pre: dict, meta: StepMeta, padded: dict,
                 counts_pad, wcounts_pad: Callable, mask_pb):
        super().__init__(pre, meta)
        self.padded = padded          # channel -> [h+2, w+2] pre values
        self.counts_pad = counts_pad  # clamped >= 1
        self._wcounts_pad = wcounts_pad  # weights -> padded weighted counts
        self.mask_pb = mask_pb        # bool [h+2, w+2]: inside partition

    def _transport(self, channel, rate_c, counts_p, weights):
        h, w = self.meta.shape
        p = self.padded[channel]
        zero = jnp.asarray(0, self.dtype)
        of_p = jnp.where(self.mask_pb, rate_c * p, zero)
        if weights is not None:
            # a stranded cell sheds nothing (DenseCtx's identical rule —
            # counts_p is RAW here so the mask sees true zeros)
            of_p = jnp.where(counts_p > 0, of_p, zero)
            counts_p = jnp.maximum(counts_p, jnp.asarray(1, self.dtype))
        share_p = of_p / counts_p
        offsets = self.meta.offsets
        taps = ([1.0] * len(offsets) if weights is None
                else _opposite_weights(offsets, weights))
        inflow = jnp.zeros((h, w), self.dtype)
        for wt, (dx, dy) in zip(taps, offsets):
            s = lax.slice(share_p, (1 + dx, 1 + dy),
                          (1 + dx + h, 1 + dy + w))
            inflow = inflow + (s if weights is None
                               else jnp.asarray(wt, self.dtype) * s)
        return ((self.cur[channel] - of_p[1:-1, 1:-1]) + inflow)

    def transport_update(self, channel, rate_c, weights) -> None:
        counts_p = (self.counts_pad if weights is None
                    else self._wcounts_pad(weights))
        self.cur[channel] = self._transport(channel, rate_c, counts_p,
                                            weights)


class WindowCtx(_Ctx):
    """Per-active-tile window context (the active engine): arrays are
    ``[th+2, tw+2]`` windows gathered from the padded grid; neighbor
    counts come from the window's GLOBAL coordinates; the outflow is
    pinned behind an ``optimization_barrier`` exactly like
    ``ops.active.active_pass`` (the anti-FMA-contraction discipline the
    bitwise gates exist to catch)."""

    def __init__(self, pre_int: dict, meta: StepMeta, wins: dict,
                 counts_win, wcounts_win: Callable):
        super().__init__(pre_int, meta)
        self.wins = wins              # channel -> [th+2, tw+2] pre window
        self.counts_win = counts_win  # clamped >= 1
        self._wcounts_win = wcounts_win

    def transport_update(self, channel, rate_c, weights) -> None:
        win = self.wins[channel]
        th = win.shape[0] - 2
        tw = win.shape[1] - 2
        outflow = optimization_barrier(rate_c * win)
        if weights is None:
            counts = self.counts_win
        else:
            counts = self._wcounts_win(weights)  # RAW weighted counts
            # stranded cells shed nothing (the shared weighted rule)
            outflow = jnp.where(counts > 0, outflow,
                                jnp.asarray(0, self.dtype))
            counts = jnp.maximum(counts, jnp.asarray(1, self.dtype))
        share = outflow / counts
        offsets = self.meta.offsets
        taps = ([1.0] * len(offsets) if weights is None
                else _opposite_weights(offsets, weights))
        inflow = jnp.zeros((th, tw), self.dtype)
        for wt, (dx, dy) in zip(taps, offsets):
            s = lax.slice(share, (1 + dx, 1 + dy),
                          (1 + dx + th, 1 + dy + tw))
            inflow = inflow + (s if weights is None
                               else jnp.asarray(wt, self.dtype) * s)
        self.cur[channel] = ((self.cur[channel] - outflow[1:-1, 1:-1])
                             + inflow)


# -- the per-term lowerings (one per kind; composed from ctx primitives) ------

@register_lowering(Transport)
class _LowerTransport:
    @staticmethod
    def apply(term: Transport, ctx: _Ctx, rate_c) -> None:
        ctx.transport_update(term.channel, rate_c, term.weights)


def _amount(term, ctx: _Ctx, rate_c):
    """``rate * expr``, materialized behind ``optimization_barrier``s:
    without the outer one, XLA's per-consumer recompute inside fusions
    hands LLVM single-use multiply-add chains whose fma contraction
    depends on whether the rate is a baked CONSTANT (serial) or a
    traced lane (ensemble) — a 1-ulp drift the cross-impl
    bitwise-at-f64 gates exist to catch (the discipline of
    ``ops.active.active_pass``). The inner barrier pins the SCALAR:
    a concrete rate of exactly 1.0 otherwise lets the algebraic
    simplifier delete the multiply and re-fuse the expression chain
    differently from the traced-lane compile (measured: Gray-Scott's
    unit-rate reaction term, 1 ulp over 10 steps). A CONCRETE unit rate
    skips the multiply outright — deterministically, in Python — since
    XLA folds a baked ``* 1.0`` anyway but does so inconsistently
    across fusion contexts; ``x * 1.0`` is IEEE-exact, so the traced-
    lane path (which cannot skip) still produces bitwise-equal values."""
    amt = evaluate(term.expr, ctx.env(), ctx.dtype)
    try:
        unit = float(rate_c) == 1.0  # concrete scalars only
    except (TypeError, jax.errors.TracerArrayConversionError):
        unit = False  # a traced lane: keep the (exact) multiply
    if unit:
        return optimization_barrier(amt)
    return optimization_barrier(optimization_barrier(rate_c) * amt)


@register_lowering(Transfer)
class _LowerTransfer:
    @staticmethod
    def apply(term: Transfer, ctx: _Ctx, rate_c) -> None:
        amt = _amount(term, ctx, rate_c)
        ctx.apply_amount(term.src, amt, -1)
        ctx.apply_amount(term.dst, amt, +1)


@register_lowering(Source)
class _LowerSource:
    @staticmethod
    def apply(term: Source, ctx: _Ctx, rate_c) -> None:
        amt = _amount(term, ctx, rate_c)
        ctx.apply_amount(term.channel, amt, +1)
        ctx.add_budget(term.budget_channel, amt, +1)


@register_lowering(Sink)
class _LowerSink:
    @staticmethod
    def apply(term: Sink, ctx: _Ctx, rate_c) -> None:
        amt = _amount(term, ctx, rate_c)
        ctx.apply_amount(term.channel, amt, -1)
        ctx.add_budget(term.budget_channel, amt, -1)


def apply_terms(terms: Sequence[Term], ctx: _Ctx,
                rates: Sequence, pin: Optional[bool] = None) -> dict:
    """Run every term's registered lowering against ``ctx`` in order;
    returns the accumulated values. ``rates`` aligns with ``terms`` —
    concrete floats (serial) or traced scalars (ensemble lanes).

    ``pin`` (default: on exactly for nonlinear term sets) materializes
    each term's written channels behind an ``optimization_barrier``
    after applying it: XLA contracts a fused nonlinear term CHAIN
    differently across compile contexts (a flat jit, a fori_loop body,
    a vmapped lane, a lax.cond branch — measured at 1 ulp/step on
    Gray-Scott), and per-term pinning is what makes every engine
    compute the identical bits. Linear all-Transport models skip it:
    their fusion is measured stable and they are the bandwidth-bound
    bench path."""
    if pin is None:
        pin = uniform_rates(terms) is None
    for term, rate in zip(terms, rates):
        rate_c = jnp.asarray(rate, ctx.dtype)
        lowering_for(term).apply(term, ctx, rate_c)
        if pin:
            wrote = set(term.writes())
            if term.budget_channel is not None:
                wrote.add(term.budget_channel)
            for ch in sorted(wrote):
                ctx.cur[ch] = optimization_barrier(ctx.cur[ch])
    return ctx.cur


# -- term-set introspection ---------------------------------------------------

def involved_channels(terms: Sequence[Term]) -> frozenset[str]:
    out: set[str] = set()
    for t in terms:
        out |= t.reads() | t.writes()
        if t.budget_channel is not None:
            out.add(t.budget_channel)
    return frozenset(out)


def budget_channels(terms: Sequence[Term]) -> dict[str, Term]:
    """budget channel -> owning source/sink term."""
    return {t.budget_channel: t for t in terms
            if t.budget_channel is not None}


def max_footprint(terms: Sequence[Term]) -> int:
    """The stencil depth the model's terms read — what drives the
    sharded executors' required halo depth."""
    return max((t.footprint for t in terms), default=0)


def uniform_rates(terms: Sequence[Term]) -> Optional[dict[str, float]]:
    """attr -> summed rate when EVERY term is a uniform (unweighted)
    Transport — the shape the composed/pallas/active fast engines
    accept; None otherwise (the general lowering applies)."""
    rates: dict[str, float] = {}
    for t in terms:
        if not (isinstance(t, Transport) and t.is_uniform):
            return None
        rates[t.channel] = rates.get(t.channel, 0.0) + t.rate
    return rates


@dataclasses.dataclass(frozen=True)
class ActivitySpec:
    """Term-derived activity predicate of one model: a tile is active
    iff ANY term may contribute on it. ``probes`` are ``(channel, ref,
    dilate)`` triples — the term acts where ``channel != ref``, with
    ring-1 tile dilation when its footprint reaches the ring (frontier
    tiles activate one step before flux arrives, exactly the hard-coded
    any-nonzero rule this generalizes). ``always`` = some term offered
    no predicate; the engine then runs every tile (honest dense
    fallback, visible in the run's fallback counters)."""

    probes: tuple[tuple[str, float, bool], ...]
    always: bool


def activity_spec(terms: Sequence[Term]) -> ActivitySpec:
    probes = []
    always = False
    for t in terms:
        p = t.activity()
        if p is None:
            always = True
            continue
        ch, ref = p
        probes.append((ch, float(ref), t.footprint >= 1))
    # dedupe (several terms often share a probe, e.g. two SIR terms on I)
    seen: dict = {}
    for pr in probes:
        seen.setdefault(pr, None)
    return ActivitySpec(tuple(seen), always)


def diffusion_terms(field_flows) -> Optional[tuple[Transport, ...]]:
    """Convert a plain-``Diffusion`` flow list to IR Transport terms —
    the hook that makes this lowering the single source of truth for
    ``Model.make_step``'s dense path. None when any flow is not a plain
    Diffusion or an attr carries several (two same-attr Diffusions sum
    OUTFLOWS in the hand-written step, which is not bitwise-identical
    to one summed-rate Transport — that corner keeps the legacy path)."""
    from ..ops.flow import Diffusion

    seen: set[str] = set()
    out = []
    for f in field_flows:
        if type(f) is not Diffusion or f.attr in seen:
            return None
        seen.add(f.attr)
        out.append(Transport(f.attr, rate=f.flow_rate))
    return tuple(out) if out else None


# -- step builders ------------------------------------------------------------

def maybe_pin(terms, values: dict) -> dict:
    """Pin a NONLINEAR step's input state behind a barrier: inside a
    ``fori_loop`` XLA fuses one iteration's tail into the next's
    expression chains, and the resulting contraction makes the looped
    program drift 1 ulp from the same step compiled alone (measured:
    Gray-Scott's Transfer term) — which would break the cross-engine
    bitwise-at-f64 matrix. Linear all-Transport models skip the pin:
    their looped fusion is measured stable, and they are the
    bandwidth-bound bench path where a barrier could cost real ns."""
    if uniform_rates(terms) is not None:
        return values
    return {k: optimization_barrier(v) for k, v in values.items()}


def dense_apply(terms, values: dict, rates, meta: StepMeta,
                counts) -> dict:
    """One dense step over full-grid arrays (the XLA engine's body —
    also what ``Model.make_step`` delegates its all-Diffusion dense
    path to, making this lowering the single source of truth for the
    hand-written transport step it replaced)."""
    values = maybe_pin(terms, values)
    return maybe_pin(
        terms, apply_terms(terms, DenseCtx(dict(values), meta, counts),
                           rates))


def build_dense_step(terms, meta: StepMeta, rates) -> Callable:
    """``step(values) -> values`` for the serial dense engine."""
    terms = tuple(terms)
    rates = tuple(rates)

    def step(values: dict) -> dict:
        counts = neighbor_counts_traced(
            meta.shape, meta.offsets, meta.origin, meta.global_shape,
            meta.dtype)
        return dense_apply(terms, values, rates, meta, counts)

    return step


def padded_apply(terms, values: dict, padded: dict, rates,
                 meta: StepMeta, counts_pad, mask_pb) -> dict:
    """One per-shard step from ghost-exchanged padded transport
    channels (ShardMapExecutor's IR runner body). ``padded`` needs only
    the channels some ring-1 term reads; ``counts_pad`` is the clamped
    global-true neighbor-count grid over the padded shard; ``mask_pb``
    bounds the partition (ghost outflow beyond it is zeroed, matching
    the serial zero-pad semantics bitwise)."""
    def wcounts_pad(weights):
        # RAW weighted counts: the ctx masks stranded cells against the
        # true zeros, then clamps for the divide
        h, w = meta.shape
        ox, oy = meta.origin
        return weighted_counts_traced(
            (h + 2, w + 2), meta.offsets, weights,
            (ox - 1, oy - 1), meta.global_shape, meta.dtype)

    values = maybe_pin(terms, values)
    padded = maybe_pin(terms, padded)
    ctx = PaddedCtx(dict(values), meta, padded, counts_pad, wcounts_pad,
                    mask_pb)
    return maybe_pin(terms, apply_terms(terms, ctx, rates))


def build_active_step(terms, meta: StepMeta, rates, plan,
                      dense_step: Callable) -> Callable:
    """The generic active-tile step for IR models: the term-derived
    ``ActivitySpec`` replaces the hard-coded any-nonzero rule, the
    compacted active tiles run every term's windowed lowering (two
    phases — all reads before all writes, the ``ops.active`` invariant)
    and the dense fallback is the SAME lowered dense step above the
    capacity/activity threshold. Linear all-Transport models never get
    here (they route to the specialized bitwise active engines via the
    flows view); this is the path that serves nonlinear physics."""
    from ..ops import active as act

    terms = tuple(terms)
    rates = tuple(rates)
    if plan.ntiles == 1:
        # a one-tile plan cannot skip anything: the window IS the grid,
        # so the active machinery is pure overhead — and the dense step
        # is the bitwise anchor every other engine matches
        return dense_step
    spec = activity_spec(terms)
    dtype = jnp.dtype(meta.dtype)
    th, tw = plan.tile
    gi, gj = plan.grid
    H, W = meta.global_shape
    ox, oy = meta.origin
    chans = sorted(involved_channels(terms))
    written = sorted(
        set().union(*(t.writes() for t in terms))
        | set(budget_channels(terms)))

    def tile_flags(values):
        if spec.always:
            return jnp.ones((gi, gj), bool)
        flags = jnp.zeros((gi, gj), bool)
        for ch, ref, dilate in spec.probes:
            tm = jnp.any(
                (values[ch] != jnp.asarray(ref, values[ch].dtype)
                 ).reshape(gi, th, gj, tw), axis=(1, 3))
            flags = flags | (act.dilate_tile_map(tm) if dilate else tm)
        return flags

    # all-active → dense: computing EVERY tile through gathered windows
    # is strictly more work than the dense step, and the dense step is
    # the bitwise anchor (a model whose predicate lights the whole grid
    # — e.g. Gray-Scott's u≈1 background — honestly runs dense)
    thresh = np.int32(min(plan.fallback_tiles, plan.ntiles - 1))

    def step(values: dict) -> dict:
        values = maybe_pin(terms, values)
        flags = tile_flags(values)
        count = jnp.sum(flags, dtype=jnp.int32)
        pred = count > thresh

        def dense_branch(vals):
            return dense_step(vals)

        def active_branch(vals):
            padded = {c: jnp.pad(vals[c], 1) for c in chans}
            ids, cnt = act.compact_tile_ids(flags, plan)
            cmin = jnp.minimum(cnt, np.int32(plan.capacity))
            upd = {c: jnp.zeros((plan.capacity, th, tw), vals[c].dtype)
                   for c in written}

            def rc_of(i):
                return (i // gj) * th, (i % gj) * tw

            def compute_body(lane, u):
                r, c = rc_of(ids[lane])
                wins = {ch: lax.dynamic_slice(padded[ch], (r, c),
                                              (th + 2, tw + 2))
                        for ch in chans}
                counts_win = jnp.maximum(
                    neighbor_counts_traced(
                        (th + 2, tw + 2), meta.offsets,
                        (ox + r - 1, oy + c - 1), (H, W), dtype),
                    jnp.asarray(1, dtype))

                def wcounts_win(weights):
                    # RAW (the ctx masks stranded cells, then clamps)
                    return weighted_counts_traced(
                        (th + 2, tw + 2), meta.offsets, weights,
                        (ox + r - 1, oy + c - 1), (H, W), dtype)

                pre_int = {ch: w[1:-1, 1:-1] for ch, w in wins.items()}
                ctx = WindowCtx(pre_int, meta, wins, counts_win,
                                wcounts_win)
                cur = apply_terms(terms, ctx, rates)
                return {c2: lax.dynamic_update_index_in_dim(
                            u[c2], cur[c2], lane, 0)
                        for c2 in u}

            upd = lax.fori_loop(0, cmin, compute_body, upd)

            def scatter_body(lane, p):
                r, c = rc_of(ids[lane])
                return {c2: lax.dynamic_update_slice(
                            p[c2], upd[c2][lane], (r + 1, c + 1))
                        for c2 in p}

            out_p = lax.fori_loop(
                0, cmin, scatter_body, {c2: padded[c2] for c2 in written})
            out = dict(vals)
            for c2 in written:
                out[c2] = out_p[c2][1:-1, 1:-1]
            return out

        return lax.cond(pred, dense_branch, active_branch, values)

    return step
