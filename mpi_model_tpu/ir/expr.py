"""The Flow IR's pointwise expression mini-language (ISSUE 11).

A term's *amount* — what a reaction transfers, a source injects, a sink
drains — is a tiny declarative expression tree over a **whitelisted
primitive set**: channel reads, constants, and the arithmetic below.
Nothing else exists in the grammar, so a model cannot smuggle host
callbacks, reductions, data-dependent shapes or un-shardable reads into
a step: every expression is pointwise by construction (a cell's value
depends only on that cell's own channel values), which is what lets ONE
registered lowering (``ir.lower``) serve the dense, composed, active
and sharded engines from the same tree.

Grammar::

    expr := Const(float) | Chan(name)
          | expr + expr | expr - expr | expr * expr | expr / expr
          | -expr | expr ** k (integer k >= 1)
          | exp(expr) | abs_(expr) | minimum(a, b) | maximum(a, b)

Python operators are overloaded on ``Expr``, so model code reads like
the math: ``Chan("u") * Chan("v") ** 2`` is the Gray-Scott reaction
amount. Numeric parameters that vary PER SCENARIO do not live here —
each term carries exactly one ``rate`` scalar that multiplies its
amount and rides the ensemble's traced ``[B, F]`` parameter lanes
(``ir.terms``); everything inside the expression is structural.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp

#: the whitelisted primitive set: op name -> arity. ``evaluate`` refuses
#: anything else by construction (there is no node type to carry it),
#: and defensively by name (a hand-built node with an unknown op raises
#: naming the op, never silently evaluates).
PRIMITIVES = {
    "add": 2, "sub": 2, "mul": 2, "div": 2,
    "min": 2, "max": 2,
    "neg": 1, "exp": 1, "abs": 1,
}


class Expr:
    """Base node; operator overloads build trees out of the whitelist."""

    def __add__(self, o): return Binary("add", self, as_expr(o))
    def __radd__(self, o): return Binary("add", as_expr(o), self)
    def __sub__(self, o): return Binary("sub", self, as_expr(o))
    def __rsub__(self, o): return Binary("sub", as_expr(o), self)
    def __mul__(self, o): return Binary("mul", self, as_expr(o))
    def __rmul__(self, o): return Binary("mul", as_expr(o), self)
    def __truediv__(self, o): return Binary("div", self, as_expr(o))
    def __rtruediv__(self, o): return Binary("div", as_expr(o), self)
    def __neg__(self): return Unary("neg", self)

    def __pow__(self, k):
        if not isinstance(k, int) or k < 1:
            raise TypeError(
                f"Expr ** k needs an integer exponent >= 1, got {k!r} "
                "(the whitelist has no general pow — square/cube by "
                "repeated multiplication)")
        return Power(self, k)


@dataclasses.dataclass(frozen=True)
class Const(Expr):
    """A structural numeric constant (baked into the compiled step; a
    per-scenario number belongs in the owning term's ``rate``)."""

    value: float

    def __post_init__(self):
        object.__setattr__(self, "value", float(self.value))


@dataclasses.dataclass(frozen=True)
class Chan(Expr):
    """Read of one attribute channel at the cell itself (pointwise)."""

    name: str


@dataclasses.dataclass(frozen=True)
class Unary(Expr):
    op: str
    a: Expr


@dataclasses.dataclass(frozen=True)
class Binary(Expr):
    op: str
    a: Expr
    b: Expr


@dataclasses.dataclass(frozen=True)
class Power(Expr):
    """Integer power, lowered as repeated multiplication (deterministic
    op sequence — the cross-impl bitwise gates depend on it)."""

    a: Expr
    n: int


def as_expr(x: Union[Expr, float, int]) -> Expr:
    if isinstance(x, Expr):
        return x
    if isinstance(x, (int, float)):
        return Const(float(x))
    raise TypeError(f"cannot use {type(x).__name__} in an IR expression "
                    "(whitelist: Expr nodes and numbers)")


def exp(a) -> Expr:
    return Unary("exp", as_expr(a))


def abs_(a) -> Expr:
    return Unary("abs", as_expr(a))


def minimum(a, b) -> Expr:
    return Binary("min", as_expr(a), as_expr(b))


def maximum(a, b) -> Expr:
    return Binary("max", as_expr(a), as_expr(b))


_UNARY_FNS = {"neg": lambda x: -x, "exp": jnp.exp, "abs": jnp.abs}
_BINARY_FNS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "min": jnp.minimum,
    "max": jnp.maximum,
}


def evaluate(e: Expr, env: dict[str, jax.Array], dtype) -> jax.Array:
    """Evaluate ``e`` against channel arrays ``env``; constants are cast
    to ``dtype`` (the space's flow dtype) so the tree's arithmetic never
    silently promotes. Every engine's context calls THIS function — the
    one evaluator is part of the single-lowering contract."""
    if isinstance(e, Const):
        return jnp.asarray(e.value, dtype)
    if isinstance(e, Chan):
        v = env.get(e.name)
        if v is None:
            raise KeyError(
                f"expression reads channel {e.name!r} which the space "
                f"does not carry (has {tuple(env)})")
        return v
    if isinstance(e, Power):
        base = evaluate(e.a, env, dtype)
        acc = base
        for _ in range(e.n - 1):
            acc = acc * base
        return acc
    if isinstance(e, Unary):
        fn = _UNARY_FNS.get(e.op)
        if fn is None or e.op not in PRIMITIVES:
            raise ValueError(f"unknown/unwhitelisted unary op {e.op!r}")
        return fn(evaluate(e.a, env, dtype))
    if isinstance(e, Binary):
        fn = _BINARY_FNS.get(e.op)
        if fn is None or e.op not in PRIMITIVES:
            raise ValueError(f"unknown/unwhitelisted binary op {e.op!r}")
        return fn(evaluate(e.a, env, dtype), evaluate(e.b, env, dtype))
    raise TypeError(f"not an IR expression node: {type(e).__name__}")


def channels(e: Expr) -> frozenset[str]:
    """The set of channels the expression reads."""
    if isinstance(e, Chan):
        return frozenset((e.name,))
    if isinstance(e, Const):
        return frozenset()
    if isinstance(e, (Unary, Power)):
        return channels(e.a)
    if isinstance(e, Binary):
        return channels(e.a) | channels(e.b)
    raise TypeError(f"not an IR expression node: {type(e).__name__}")


def fingerprint(e: Expr) -> tuple:
    """Hashable structural identity (constants INCLUDED — they are baked
    into the compiled step, so differing constants are different
    programs; only the per-term ``rate`` is a traced parameter)."""
    if isinstance(e, Const):
        return ("const", e.value)
    if isinstance(e, Chan):
        return ("chan", e.name)
    if isinstance(e, Power):
        return ("pow", fingerprint(e.a), e.n)
    if isinstance(e, Unary):
        return (e.op, fingerprint(e.a))
    if isinstance(e, Binary):
        return (e.op, fingerprint(e.a), fingerprint(e.b))
    raise TypeError(f"not an IR expression node: {type(e).__name__}")


def zero_point(e: Expr) -> Optional[tuple[str, float]]:
    """A ``(channel, ref)`` pair such that the expression is provably
    zero wherever ``channel == ref`` — the symbolic root the active
    engine derives a term's ACTIVITY PREDICATE from (a tile where every
    term is provably zero can be skipped). Conservative: ``None`` means
    "no such proof" and the term keeps every tile active.

    Rules: ``Chan(c)`` is zero at ``c == 0``; a product is zero where
    either factor is; powers/negation preserve roots; ``k - Chan(c)``
    is zero at ``c == k``."""
    if isinstance(e, Chan):
        return (e.name, 0.0)
    if isinstance(e, Power):
        return zero_point(e.a)
    if isinstance(e, Unary) and e.op == "neg":
        return zero_point(e.a)
    if isinstance(e, Binary) and e.op == "mul":
        return zero_point(e.a) or zero_point(e.b)
    if isinstance(e, Binary) and e.op == "sub":
        if isinstance(e.a, Const) and isinstance(e.b, Chan):
            return (e.b.name, e.a.value)
        if isinstance(e.a, Chan) and isinstance(e.b, Const):
            return (e.a.name, e.b.value)
    return None
