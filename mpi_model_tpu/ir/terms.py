"""Flow IR terms: the declarative units a model is a pytree of (ISSUE 11).

A **term** is one physical process over the grid's channels. Each term
declares, as data the engines can reason about:

- the channels it **reads** and **writes**;
- its stencil **footprint** (0 = pointwise, 1 = the Moore ring — the
  sharded executors derive their required halo depth from the model's
  max footprint instead of trusting hand-set knobs);
- its **conservation contract**: ``"conserving"`` (moves mass, never
  creates or destroys it — transport, transfers), ``"source"``
  (declared mass injection) or ``"sink"`` (declared mass removal).
  Declared sources/sinks are *integrated* during the run into a hidden
  per-term budget channel (``budget_channel``) and *reconciled* against
  the observed total-mass drift — a violated contract raises
  ``ConservationError`` naming the term, instead of the drift being
  asserted away (the generalization of the reference's ``Model.hpp:95``
  global-sum assert);
- exactly one numeric **rate** — THE per-scenario parameter. Every
  term's contribution is ``rate * amount``; the ensemble engine batches
  scenarios whose terms differ only in rates, shipping them as traced
  ``[B, F]`` lanes (a zero rate vector is a provable no-op, which is
  what makes the scheduler's zero-padding lanes inert for ANY physics).

The reference's ``Flow``/``Exponencial`` hierarchy (PAPER.md: a rate
equation attached to the space, executed then redistributed to Moore
neighbors) is the one-term instance ``Transport(channel, rate)``.

Terms carry NO compute. Their lowerings live in ``ir.lower`` under a
registry the jaxpr auditor checks (`jaxpr-term-registry`): one audited
lowering per term kind, shared by every step engine.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .expr import Chan, Const, Expr, as_expr, channels, fingerprint

#: prefix of the hidden per-term budget accumulator channels. They ride
#: the space like any float channel (stacked, sharded, checkpointed),
#: start at zero, and integrate a source/sink term's signed mass
#: contribution — conservation reconciliation reads their totals.
BUDGET_PREFIX = "_b_"

CONSERVING = "conserving"
SOURCE = "source"
SINK = "sink"


class Term:
    """Base of the term grammar. Concrete terms are frozen dataclasses;
    the common surface is the declaration API the engines consume."""

    name: str
    rate: float

    #: conservation contract (CONSERVING / SOURCE / SINK)
    conservation: str = CONSERVING
    #: stencil footprint: 0 pointwise, 1 = reads/writes the Moore ring
    footprint: int = 0

    # -- declarations --------------------------------------------------------

    def reads(self) -> frozenset[str]:
        raise NotImplementedError

    def writes(self) -> frozenset[str]:
        raise NotImplementedError

    @property
    def budget_channel(self) -> Optional[str]:
        """Hidden accumulator channel for declared sources/sinks (None
        for conserving terms — their net contribution is identically
        zero by construction of their lowering)."""
        if self.conservation in (SOURCE, SINK):
            return BUDGET_PREFIX + self.name
        return None

    def structure(self) -> tuple:
        """Hashable structural identity EXCLUDING the rate (the rate is
        the per-scenario parameter lane) — the ensemble batch-
        compatibility key component."""
        raise NotImplementedError

    def with_rate(self, rate: float) -> "Term":
        return dataclasses.replace(self, rate=float(rate))

    def activity(self) -> Optional[tuple[str, float]]:
        """``(channel, ref)`` such that this term provably contributes
        nothing wherever ``channel == ref`` — the term-derived activity
        predicate of the active engines. None = always active."""
        return None

    def _check_name(self):
        if not self.name or not self.name.replace("_", "a").isalnum():
            raise ValueError(
                f"term name {self.name!r} must be a non-empty "
                "identifier-like string (it names budget channels and "
                "conservation errors)")
        if self.name.startswith(BUDGET_PREFIX):
            raise ValueError(
                f"term name {self.name!r} collides with the "
                f"{BUDGET_PREFIX}* budget-channel namespace")


@dataclasses.dataclass(frozen=True)
class Transport(Term):
    """The linear stencil term: every cell sheds ``rate * value`` and
    distributes it to its in-bounds Moore neighbors — the reference's
    flow step generalized with optional per-tap ``weights`` (one weight
    per model offset; ``None`` = uniform, the classic counts-divided
    redistribution, bitwise-identical to the hand-written
    ``ops.stencil.transport``). Conserving by construction: what a cell
    emits is exactly what its neighbors receive.

    With ``weights=None`` and a concrete rate this is the shape every
    accelerated engine composes/fuses (the k-step tap table, the fused
    Pallas active kernel); weighted taps run the general lowering."""

    channel: str
    rate: float = 0.1
    weights: Optional[tuple[float, ...]] = None
    name: str = ""

    conservation = CONSERVING
    footprint = 1

    def __post_init__(self):
        object.__setattr__(self, "rate", float(self.rate))
        if self.weights is not None:
            w = tuple(float(x) for x in self.weights)
            if any(x < 0 for x in w) or not any(x > 0 for x in w):
                raise ValueError(
                    f"Transport weights must be non-negative with at "
                    f"least one positive tap, got {w}")
            object.__setattr__(self, "weights", w)
        if not self.name:
            object.__setattr__(self, "name", f"transport_{self.channel}")
        self._check_name()

    def reads(self) -> frozenset[str]:
        return frozenset((self.channel,))

    def writes(self) -> frozenset[str]:
        return frozenset((self.channel,))

    def structure(self) -> tuple:
        return ("Transport", self.name, self.channel, self.weights)

    def activity(self) -> Optional[tuple[str, float]]:
        # zero stays zero under linear transport: the active engines'
        # exact skip rule (ops.active module docstring)
        return (self.channel, 0.0)

    @property
    def is_uniform(self) -> bool:
        """True when this term is the uniform-rate shape the composed/
        pallas/active fast engines accept (``Diffusion`` equivalent)."""
        return self.weights is None


@dataclasses.dataclass(frozen=True)
class Transfer(Term):
    """Pointwise cross-channel coupling: ``rate * expr`` moves from
    ``src`` to ``dst`` at each cell — conserving across the pair by
    construction (one amount, subtracted and added). SIR's infection
    (``S -> I`` at ``beta * S * I``) and Gray-Scott's autocatalysis
    (``u -> v`` at ``u * v**2``) are Transfers."""

    src: str
    dst: str
    expr: Expr
    rate: float = 1.0
    name: str = ""

    conservation = CONSERVING
    footprint = 0

    def __post_init__(self):
        object.__setattr__(self, "rate", float(self.rate))
        object.__setattr__(self, "expr", as_expr(self.expr))
        if self.src == self.dst:
            raise ValueError(
                f"Transfer src and dst are both {self.src!r}: a "
                "self-transfer is a no-op — use Source/Sink for a net "
                "change, or drop the term")
        if not self.name:
            object.__setattr__(self, "name",
                               f"transfer_{self.src}_{self.dst}")
        self._check_name()

    def reads(self) -> frozenset[str]:
        return channels(self.expr) | {self.src, self.dst}

    def writes(self) -> frozenset[str]:
        return frozenset((self.src, self.dst))

    def structure(self) -> tuple:
        return ("Transfer", self.name, self.src, self.dst,
                fingerprint(self.expr))

    def activity(self) -> Optional[tuple[str, float]]:
        from .expr import zero_point
        return zero_point(self.expr)


@dataclasses.dataclass(frozen=True)
class Source(Term):
    """Declared mass injection: ``rate * expr`` is ADDED to ``channel``
    at each cell, and the same signed amount is integrated into the
    term's budget channel. ``expr`` may read a mask channel (masked
    sources) or a clock channel (time-varying sources — see ``Clock``).
    The contract: the integrated budget must be non-negative; the
    reconciliation gate raises naming this term otherwise."""

    channel: str
    expr: Expr
    rate: float = 1.0
    name: str = ""

    conservation = SOURCE
    footprint = 0

    def __post_init__(self):
        object.__setattr__(self, "rate", float(self.rate))
        object.__setattr__(self, "expr", as_expr(self.expr))
        if not self.name:
            object.__setattr__(self, "name", f"source_{self.channel}")
        self._check_name()

    def reads(self) -> frozenset[str]:
        return channels(self.expr) | {self.channel}

    def writes(self) -> frozenset[str]:
        return frozenset((self.channel,))

    def structure(self) -> tuple:
        return ("Source", self.name, self.channel, fingerprint(self.expr))

    def activity(self) -> Optional[tuple[str, float]]:
        from .expr import zero_point
        return zero_point(self.expr)


@dataclasses.dataclass(frozen=True)
class Sink(Term):
    """Declared mass removal: ``rate * expr`` is SUBTRACTED from
    ``channel``; the integrated budget must be non-positive (the
    reconciliation gate raises naming this term otherwise)."""

    channel: str
    expr: Expr
    rate: float = 1.0
    name: str = ""

    conservation = SINK
    footprint = 0

    def __post_init__(self):
        object.__setattr__(self, "rate", float(self.rate))
        object.__setattr__(self, "expr", as_expr(self.expr))
        if not self.name:
            object.__setattr__(self, "name", f"sink_{self.channel}")
        self._check_name()

    def reads(self) -> frozenset[str]:
        return channels(self.expr) | {self.channel}

    def writes(self) -> frozenset[str]:
        return frozenset((self.channel,))

    def structure(self) -> tuple:
        return ("Sink", self.name, self.channel, fingerprint(self.expr))

    def activity(self) -> Optional[tuple[str, float]]:
        from .expr import zero_point
        return zero_point(self.expr)


def Clock(channel: str = "t", name: str = "clock") -> Source:
    """A step counter as physics: a Source adding 1 to ``channel``
    everywhere each step (``rate=1``). Time-varying terms read
    ``Chan(channel)``; because the clock is a DECLARED source its
    growth reconciles exactly in the budget gate — no special-cased
    bookkeeping channel."""
    return Source(channel, Const(1.0), rate=1.0, name=name)


def validate_terms(terms) -> tuple[Term, ...]:
    """Shared construction-time validation: term types, unique names,
    and at least one term. Channel existence is checked against the
    space at lowering time (the step builder has the space)."""
    terms = tuple(terms)
    if not terms:
        raise ValueError("a Flow IR model needs at least one term")
    seen: set[str] = set()
    for t in terms:
        if not isinstance(t, Term):
            raise TypeError(
                f"{type(t).__name__} is not an IR Term (the grammar is "
                "Transport/Transfer/Source/Sink — see ir.terms)")
        if t.name in seen:
            raise ValueError(
                f"duplicate term name {t.name!r}: names key budget "
                "channels and conservation errors, so they must be "
                "unique within a model")
        seen.add(t.name)
    return terms
