"""FlowIRModel: a Model whose physics is a declarative term list.

Where ``Model`` holds the reference's ``Flow`` objects, ``FlowIRModel``
holds IR **terms** (``ir.terms``) and builds every step through the one
registered lowering (``ir.lower``). The executor/ensemble/serving
stack needs zero per-model step code:

- **linear models** (every term a uniform ``Transport``) expose an
  exact flows VIEW (one ``Diffusion`` per term), so the whole
  accelerated surface lights up unchanged — pallas, the composed k-step
  tap table, both active engines, the pipeline ensemble impl, sharded
  deep halos — and the dense XLA path they gate against is itself the
  IR Transport lowering (``Model.make_step`` delegates its
  all-Diffusion dense branch to ``ir.lower.dense_apply``), making the
  lowering the single source of truth the bitwise gate pins;
- **nonlinear models** (reactions, coupled channels, sources/sinks)
  lower to the dense step, the composed path at k=1 (a warning says the
  taps don't compose), and the generic active engine whose activity
  predicate is derived from the terms; ``active_fused``/``pallas``
  raise the documented incompatibility (their kernels are
  linear-stencil machines).

Conservation generalizes from "global sum is constant" to **per-term
budget reconciliation**: declared sources/sinks integrate their signed
contribution into hidden ``_b_<term>`` channels during the run, and the
gate checks (a) each budget's SIGN matches its contract and (b) the
observed total-mass drift equals the summed budgets — violations raise
``ConservationError`` naming the term instead of the drift being
asserted away.
"""

from __future__ import annotations

import math
import warnings
from typing import Callable, Mapping, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..core.cellular_space import CellularSpace
from ..models.model import ConservationError, Model, Report, \
    default_conservation_rtol
from ..ops.flow import Diffusion
from . import lower
from .terms import Term, Transport, validate_terms

Number = Union[float, np.ndarray]


class FlowIRModel(Model):
    """Orchestrates IR terms over a CellularSpace (see module docstring).

    ``terms`` is the model; each term's ``rate`` is its per-scenario
    parameter (``with_rates`` rebinds them — the ensemble engine ships
    differing rates as traced ``[B, F]`` lanes)."""

    def __init__(self, terms: Sequence[Term], time: float = 1.0,
                 time_step: float = 1.0, *,
                 offsets: Optional[Sequence[tuple[int, int]]] = None,
                 active_opts: Optional[dict] = None):
        self.ir_terms: tuple[Term, ...] = validate_terms(terms)
        #: generic active-engine plan knobs for nonlinear terms (keys
        #: ``tile``/``capacity``/``max_active_frac`` — ops.active
        #: .plan_for); the amortized linear engines take theirs from
        #: SerialExecutor(active_opts=...) as before
        self.active_opts = dict(active_opts) if active_opts else None
        # the exact flows view of a linear model: one Diffusion per
        # uniform Transport term — what routes linear IR models onto
        # every pre-existing accelerated engine with zero new code
        rates = lower.uniform_rates(self.ir_terms)
        flows = ([Diffusion(t.rate, attr=t.channel) for t in self.ir_terms]
                 if rates is not None else [])
        super().__init__(flows, time, time_step, offsets=offsets)

    # -- structure ----------------------------------------------------------

    @property
    def ir_linear(self) -> bool:
        """True when every term is a uniform Transport (the flows-view
        family served bitwise by the specialized engines)."""
        return bool(self.flows)

    def term_rates(self) -> tuple[float, ...]:
        return tuple(t.rate for t in self.ir_terms)

    def with_rates(self, rates: Sequence[float]) -> "FlowIRModel":
        """Same structure, new per-term rates (the per-scenario knob)."""
        rates = list(rates)
        if len(rates) != len(self.ir_terms):
            raise ValueError(
                f"{len(rates)} rates for {len(self.ir_terms)} terms")
        return FlowIRModel(
            [t.with_rate(r) for t, r in zip(self.ir_terms, rates)],
            self.time, self.time_step, offsets=self.offsets,
            active_opts=self.active_opts)

    def term_structure(self) -> tuple:
        """Hashable batch-compatibility identity: term structures (rates
        excluded — they are the traced parameter lanes) + offsets."""
        return (tuple(t.structure() for t in self.ir_terms),
                tuple(self.offsets))

    def _term_fingerprints(self) -> tuple:
        return tuple(t.structure() + (t.rate,) for t in self.ir_terms)

    def pallas_rates(self) -> Optional[dict[str, float]]:
        if self.ir_linear:
            return super().pallas_rates()
        return None  # nonlinear terms need the general lowering

    # -- spaces -------------------------------------------------------------

    def required_channels(self) -> frozenset[str]:
        return lower.involved_channels(self.ir_terms)

    def create_space(self, dim_x: int, dim_y: int,
                     attributes: Optional[Mapping] = None,
                     dtype=jnp.float32, **kw) -> CellularSpace:
        """``CellularSpace.create`` plus the model's hidden budget
        channels (zero-initialized accumulators for declared
        sources/sinks)."""
        attrs = dict(attributes) if attributes is not None else {
            ch: 0.0 for ch in sorted(self.required_channels())
            if not ch.startswith("_b_")}
        for b in lower.budget_channels(self.ir_terms):
            attrs.setdefault(b, 0.0)
        return CellularSpace.create(dim_x, dim_y, attrs, dtype=dtype, **kw)

    def with_budget_channels(self, space: CellularSpace) -> CellularSpace:
        """A copy of ``space`` with any missing budget channels added
        (zeroed, in the space dtype)."""
        vals = dict(space.values)
        for b in lower.budget_channels(self.ir_terms):
            if b not in vals:
                vals[b] = jnp.zeros(space.shape, space.dtype)
        return space.with_values(vals)

    def _validate_space(self, space: CellularSpace) -> None:
        missing = sorted(self.required_channels()
                         - set(space.values))
        if missing:
            raise ValueError(
                f"space is missing channels {missing} required by the "
                "model's terms (budget accumulators included) — build "
                "spaces with FlowIRModel.create_space, or add them via "
                "with_budget_channels")
        written = set().union(*(t.writes() for t in self.ir_terms))
        written |= set(lower.budget_channels(self.ir_terms))
        for ch in sorted(written):
            if not jnp.issubdtype(space.values[ch].dtype, jnp.floating):
                raise TypeError(
                    f"IR terms write channel {ch!r}, which requires a "
                    f"floating dtype (got {space.values[ch].dtype}); "
                    "int/bool channels are supported as read-only "
                    "masks/storage")

    def _meta(self, space: CellularSpace) -> lower.StepMeta:
        return lower.StepMeta(
            shape=space.shape, origin=(space.x_init, space.y_init),
            global_shape=space.global_shape, dtype=space.dtype,
            offsets=tuple(self.offsets))

    # -- step construction --------------------------------------------------

    def make_step(self, space: CellularSpace, impl: str = "xla",
                  substeps: int = 1, compute_dtype=None) -> Callable:
        if self.ir_linear:
            # linear family: the flows view runs the whole specialized
            # engine surface; its dense path is the IR Transport
            # lowering (Model.make_step delegates), so this is not a
            # second implementation
            return super().make_step(space, impl=impl, substeps=substeps,
                                     compute_dtype=compute_dtype)
        if impl in ("pallas", "active_fused", "pipeline"):
            raise ValueError(
                f"impl={impl!r} is a linear-stencil kernel; this model "
                "has nonlinear/coupled terms "
                f"({[t.name for t in self.ir_terms]}). Eligible impls: "
                "'xla'/'auto' (dense lowering), 'composed' (k forced "
                "to 1), 'active' (term-derived activity predicate).")
        if impl not in ("xla", "auto", "composed", "active"):
            raise ValueError(f"unknown step impl {impl!r}")
        substeps = int(substeps)
        if substeps < 1:
            raise ValueError(f"substeps must be >= 1, got {substeps}")
        self._validate_space(space)
        key = ("ir", space.shape, space.global_shape,
               (space.x_init, space.y_init), str(space.dtype),
               self.offsets, impl, substeps, self._term_fingerprints(),
               tuple(sorted((self.active_opts or {}).items())))
        cached = self._step_cache.get(key)
        if cached is not None:
            return cached

        meta = self._meta(space)
        rates = self.term_rates()
        single = lower.build_dense_step(self.ir_terms, meta, rates)
        if impl == "composed" and substeps > 1:
            # the documented degeneration: nonlinear terms do not
            # compose into a k-step tap table (the table is the
            # k-fold composition of a LINEAR operator), so every
            # "composed" call iterates k=1 passes
            warnings.warn(
                f"impl='composed' with nonlinear IR terms forces k=1 "
                f"for substeps={substeps}: each call runs iterated "
                "single passes, equaling the dense path. Only linear "
                "all-Transport models compose into the tap table.",
                RuntimeWarning)
        if impl == "active":
            from ..ops.active import plan_for
            opts = dict(self.active_opts or {})
            plan = plan_for(space.shape, tile=opts.get("tile"),
                            capacity=opts.get("capacity"),
                            max_active_frac=opts.get("max_active_frac",
                                                     0.25))
            single = lower.build_active_step(self.ir_terms, meta, rates,
                                             plan, single)

        if substeps == 1:
            step = single
        else:
            # compose via a TRACED loop, not Python unrolling: an
            # unrolled chain of nonlinear singles fuses across the seam
            # and XLA CPU's stripped-barrier fma contraction drifts it
            # 1 ulp from the serial fori(single) reference — the inner
            # fori body compiles as its own computation, matching the
            # executors' loop context exactly
            def step(values, _single=single):
                import jax

                return jax.lax.fori_loop(
                    0, substeps, lambda i, c: _single(c), values)

        step.impl = "active" if impl == "active" else (
            "composed" if impl == "composed" else "xla")
        step.substeps = substeps
        step.composed_k = 1 if impl == "composed" else None
        step.composed_passes = substeps if impl == "composed" else None
        self._step_cache[key] = step
        return step

    # -- conservation: per-term budget reconciliation -----------------------

    def conservation_view(self, totals: Mapping[str, Number]
                          ) -> dict[str, Number]:
        """Map raw per-channel totals to the quantities the IR contract
        checks (works on scalars and on the ensemble's ``[B]`` lanes):

        - ``"mass"``: summed non-budget totals MINUS the integrated
          budgets — constant for a correct model (what the conserving
          terms promise);
        - ``"term:<name>"`` per declared source/sink: the contract-
          violating part of its integrated budget (a source gone
          negative / a sink gone positive), zero when honest.

        All-Transport models return the totals unchanged (the classic
        per-channel contract, bitwise-identical behavior)."""
        buds = lower.budget_channels(self.ir_terms)
        if not buds and all(isinstance(t, Transport)
                            for t in self.ir_terms):
            return dict(totals)
        mass = None
        for k, v in totals.items():
            if k in buds:
                continue
            mass = v if mass is None else mass + v
        for b in buds:
            mass = mass - totals[b]
        out: dict[str, Number] = {"mass": mass}
        for b, t in buds.items():
            v = totals[b]
            out[f"term:{t.name}"] = (np.minimum(v, 0.0)
                                     if t.conservation == "source"
                                     else np.maximum(v, 0.0))
        return out

    def budget_totals(self, space: CellularSpace) -> dict[str, float]:
        """term name -> integrated budget (host floats) — the run's
        reconciled source/sink ledger, for reports and benches."""
        return {t.name: float(space.total(b))
                for b, t in lower.budget_channels(self.ir_terms).items()}

    def _raise_if_violated(self, space: CellularSpace,
                           initial: dict, final: dict,
                           tolerance: float, rtol: Optional[float]
                           ) -> None:
        vi = self.conservation_view(initial)
        vf = self.conservation_view(final)
        if rtol is None:
            rtol = default_conservation_rtol(space.shape, space.dtype)
        scale = max(abs(float(t)) for t in initial.values())
        thresh = tolerance + rtol * scale * max(len(initial), 1)
        worst_key, worst = None, -1.0
        for k in vi:
            err = abs(float(vf[k]) - float(vi[k]))
            if not math.isfinite(err):
                worst_key, worst = k, err
                break
            if err > worst:
                worst_key, worst = k, err
        if worst_key is None or (math.isfinite(worst)
                                 and worst <= thresh):
            return
        raise ConservationError(
            self.violation_message(worst_key, worst, thresh))

    def violation_message(self, key: str, err: float,
                          thresh: float) -> str:
        """The one place IR conservation violations are worded — the
        ensemble path reuses it so serial and batched runs name terms
        identically."""
        if key.startswith("term:"):
            name = key[len("term:"):]
            term = next(t for t in self.ir_terms if t.name == name)
            return (
                f"conservation contract violated by term {name!r}: the "
                f"declared {term.conservation}'s integrated budget ran "
                f"{'negative' if term.conservation == 'source' else 'positive'}"
                f" by {err:.3e} (> {thresh:.3e}) — a "
                f"{term.conservation} must only "
                f"{'add' if term.conservation == 'source' else 'remove'}"
                " mass")
        conserving = [t.name for t in self.ir_terms
                      if t.budget_channel is None]
        return (
            f"per-term budgets do not reconcile: |Δmass − Σ budgets| = "
            f"{err:.3e} > {thresh:.3e} — a conserving term "
            f"({conserving}) leaked mass, or a source/sink moved mass "
            "it did not declare")

    def report_conservation_error(self, report: Report) -> float:
        """``Report.conservation_error`` through the IR view (what the
        CLI/bench judge for --model runs: raw per-channel drift is
        EXPECTED physics for a model with declared sources/sinks)."""
        vi = self.conservation_view(report.initial_total)
        vf = self.conservation_view(report.final_total)
        return max(abs(float(vf[k]) - float(vi[k])) for k in vi)

    def conservation_threshold(self, space: CellularSpace,
                               tolerance: float = 1e-3,
                               rtol: Optional[float] = None,
                               initial_totals: Optional[dict] = None
                               ) -> float:
        thresh = super().conservation_threshold(
            space, tolerance, rtol, initial_totals=initial_totals)
        # the reconciliation sums C channel totals + T budgets: allow
        # each reduction its own rounding share
        n = len(space.values) if initial_totals is None \
            else len(initial_totals)
        return thresh * max(n, 1)
