"""Built-in Flow IR models: the registry behind ``--model`` (ISSUE 11).

Three nonlinear coupled-physics models prove the IR serves new
scenarios with zero per-model step code, plus the linear diffusion
model re-expressed as IR terms (the bitwise single-source-of-truth
gate). Every builder returns ``(FlowIRModel, CellularSpace)`` with a
deterministic initial condition; per-model keyword arguments override
the canonical coefficients (each becomes that term's per-scenario
``rate`` lane under the ensemble engine).

Numerical regimes are chosen for a redistribution-style discrete step
(``Transport`` sheds ``rate * value`` to the Moore ring, the
reference's flow semantics) — bounded over the step counts the tests
and benches run.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..core.cellular_space import CellularSpace
from .expr import Chan
from .model import FlowIRModel
from .terms import Sink, Source, Transfer, Transport


def _seeded_blob(dim_x: int, dim_y: int, value: float, frac: float = 8.0,
                 base: float = 0.0) -> np.ndarray:
    """Deterministic centered square patch — the wavefront seed."""
    a = np.full((dim_x, dim_y), base, np.float64)
    hx = max(1, int(dim_x // (2 * frac)))
    hy = max(1, int(dim_y // (2 * frac)))
    cx, cy = dim_x // 2, dim_y // 2
    a[cx - hx:cx + hx + 1, cy - hy:cy + hy + 1] = value
    return a


def diffusion(dim_x: int = 64, dim_y: Optional[int] = None, *,
              rate: float = 0.1, dtype=jnp.float32,
              time: float = 10.0, time_step: float = 1.0):
    """The existing linear model re-expressed as ONE IR term: the
    uniform-rate Moore-8 Transport every step engine hard-coded before
    this subsystem. Bitwise-at-f64 equal to ``Model([Diffusion(rate)])``
    on every impl and executor (``tests/test_ir.py`` gates it)."""
    dim_y = dim_x if dim_y is None else dim_y
    model = FlowIRModel([Transport("value", rate=rate)], time, time_step)
    space = CellularSpace.create(dim_x, dim_y, 0.0, dtype=dtype)
    space = space.with_values(
        {"value": jnp.asarray(_seeded_blob(dim_x, dim_y, 1.0), dtype)})
    return model, space


def gray_scott(dim_x: int = 64, dim_y: Optional[int] = None, *,
               Du: float = 0.16, Dv: float = 0.08, F: float = 0.035,
               k: float = 0.065, dtype=jnp.float32,
               time: float = 64.0, time_step: float = 1.0):
    """Gray-Scott reaction-diffusion: two coupled channels, a cubic
    autocatalytic transfer, a declared feed source and a declared kill
    sink — the canonical pattern-forming workload.

    Terms: ``du = Du·∇u − u·v² + F·(1−u)``, ``dv = Dv·∇v + u·v² −
    (F+k)·v`` with the Laplacian realized as the Moore Transport. The
    feed integrates a non-negative budget, the kill a non-positive one;
    the reconciliation gate checks both and that mass drift equals
    their sum."""
    dim_y = dim_x if dim_y is None else dim_y
    u, v = Chan("u"), Chan("v")
    model = FlowIRModel([
        Transport("u", rate=Du),
        Transport("v", rate=Dv),
        # v is the sparse channel: its factor leads the product so the
        # active engine's derived predicate keys on v's support
        Transfer("u", "v", v ** 2 * u, rate=1.0, name="reaction"),
        Source("u", 1.0 - u, rate=F, name="feed"),
        Sink("v", v, rate=F + k, name="kill"),
    ], time, time_step)
    ub = 1.0 - _seeded_blob(dim_x, dim_y, 0.5)
    vb = _seeded_blob(dim_x, dim_y, 0.25)
    space = model.create_space(dim_x, dim_y, {"u": 0.0, "v": 0.0},
                               dtype=dtype)
    space = space.with_values({**space.values,
                               "u": jnp.asarray(ub, dtype),
                               "v": jnp.asarray(vb, dtype)})
    return model, space


def sir(dim_x: int = 64, dim_y: Optional[int] = None, *,
        beta: float = 0.3, gamma: float = 0.05, Di: float = 0.1,
        dtype=jnp.float32, time: float = 32.0, time_step: float = 1.0):
    """Spatial SIR contagion: susceptible/infected/recovered channels,
    infection and recovery as conserving cross-channel Transfers,
    spatial spread as Transport of the infected channel. FULLY
    conserving (population is constant): the gate checks the summed
    S+I+R mass, not per-channel totals (which legitimately migrate).

    The infection amount leads with ``I`` so the active engine's
    term-derived predicate keys on the infected support — tiles far
    from the outbreak are skipped exactly."""
    dim_y = dim_x if dim_y is None else dim_y
    S, I = Chan("S"), Chan("I")
    model = FlowIRModel([
        Transfer("S", "I", I * S, rate=beta, name="infection"),
        Transfer("I", "R", I, rate=gamma, name="recovery"),
        Transport("I", rate=Di, name="mixing"),
    ], time, time_step)
    ib = _seeded_blob(dim_x, dim_y, 0.01, frac=16.0)
    sb = 1.0 - ib
    space = model.create_space(
        dim_x, dim_y, {"S": 0.0, "I": 0.0, "R": 0.0}, dtype=dtype)
    space = space.with_values({**space.values,
                               "S": jnp.asarray(sb, dtype),
                               "I": jnp.asarray(ib, dtype)})
    return model, space


def predator_prey(dim_x: int = 64, dim_y: Optional[int] = None, *,
                  alpha: float = 0.08, beta: float = 0.4,
                  delta: float = 0.2, gamma: float = 0.06,
                  Dx: float = 0.1, Dy: float = 0.05,
                  dtype=jnp.float32, time: float = 32.0,
                  time_step: float = 1.0):
    """Spatial Lotka-Volterra: prey growth (declared source), predation
    (declared sink on prey), predator reproduction (declared source fed
    by the same encounter product) and predator mortality (declared
    sink), both species diffusing via Transport. Four budget channels
    reconcile against the observed mass drift; a predation/reproduction
    imbalance is visible as budget signs, not silent drift."""
    dim_y = dim_x if dim_y is None else dim_y
    x, y = Chan("x"), Chan("y")
    model = FlowIRModel([
        Transport("x", rate=Dx, name="prey_mixing"),
        Transport("y", rate=Dy, name="pred_mixing"),
        Source("x", x, rate=alpha, name="growth"),
        Sink("x", x * y, rate=beta, name="predation"),
        Source("y", y * x, rate=delta, name="reproduction"),
        Sink("y", y, rate=gamma, name="mortality"),
    ], time, time_step)
    xb = _seeded_blob(dim_x, dim_y, 1.0, frac=6.0)
    # predators seeded OFF-center so the chase is visible
    yb = np.zeros((dim_x, dim_y), np.float64)
    qx, qy = dim_x // 4, dim_y // 4
    hx, hy = max(1, dim_x // 16), max(1, dim_y // 16)
    yb[qx - hx:qx + hx + 1, qy - hy:qy + hy + 1] = 0.5
    space = model.create_space(dim_x, dim_y, {"x": 0.0, "y": 0.0},
                               dtype=dtype)
    space = space.with_values({**space.values,
                               "x": jnp.asarray(xb, dtype),
                               "y": jnp.asarray(yb, dtype)})
    return model, space


#: the --model registry: name -> builder(dim_x, dim_y, dtype=..., **kw)
MODELS: dict[str, Callable] = {
    "diffusion": diffusion,
    "gray_scott": gray_scott,
    "sir": sir,
    "predator_prey": predator_prey,
}


def build_model(name: str, dim_x: int = 64, dim_y: Optional[int] = None,
                **kw):
    """Build a registered IR model + its seeded space; unknown names
    raise listing the registry (the CLI's flag-surface discipline)."""
    builder = MODELS.get(name)
    if builder is None:
        raise ValueError(
            f"unknown IR model {name!r} (registry: "
            f"{', '.join(sorted(MODELS))})")
    return builder(dim_x, dim_y, **kw)
