"""Flow IR (ISSUE 11): a declarative term language for nonlinear,
coupled physics, lowered by ONE registered lowering to every step
engine. See ``ir.terms`` (the grammar), ``ir.lower`` (the lowering +
engine contexts), ``ir.model`` (FlowIRModel: budgets, conservation
reconciliation), ``ir.library`` (the built-in model registry behind
``--model``)."""

from .expr import (Chan, Const, Expr, abs_, exp, maximum,  # noqa: F401
                   minimum)
from .library import MODELS, build_model  # noqa: F401
from .model import FlowIRModel  # noqa: F401
from .terms import (BUDGET_PREFIX, Clock, Sink, Source, Term,  # noqa: F401
                    Transfer, Transport)
