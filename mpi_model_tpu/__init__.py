"""mpi_model_tpu — a TPU-native cellular-space simulation framework.

Brand-new framework with the capabilities of daviidsilvaa/MPI-Model (a
TerraME-style MPI cellular simulator; see SURVEY.md): CellularSpace / Cell /
Attribute / Flow / Model, re-designed TPU-first — the grid is a sharded
``jax.Array`` on a device mesh, flow kernels are fused stencil ops (Pallas
for the large configs), and the halo exchange is ``shard_map`` + ``ppermute``
over ICI behind a backend-agnostic abstraction seam.

Layer map (mirrors SURVEY.md §1):
  L0 ``abstraction``     — backend-neutral dtype seam (Abstraction.hpp)
  L1 ``parallel``        — mesh/halo/collectives (MPIImpl + wire protocol)
  L2 ``core``            — Attribute/Cell/CellularSpace (data model)
  L3 ``ops``             — Flow/Exponencial + stencil/Pallas kernels
  L4 ``models``          — Model/ModelRectangular (orchestration)
  L5 ``native/`` + CLI   — C++ runtime & driver (Main.cpp)
  —  ``utils``, ``io``   — timing/metrics; checkpoint/restore + output
  —  ``resilience``      — failure detection + checkpoint-based recovery
"""

from .abstraction import DataType, get_abstraction_data_type
from .core import Attribute, Cell, CellularSpace, Partition
from .ops import Coupled, Diffusion, Exponencial, Flow, PointFlow
from .models import ConservationError, Model, ModelRectangular, Report
from .resilience import (
    FailureEvent,
    SimulationFailure,
    check_health,
    supervised_run,
)
from .ensemble import (
    AsyncEnsembleService,
    EnsembleConservationError,
    EnsembleExecutor,
    EnsembleScheduler,
    EnsembleService,
    EnsembleSpace,
    ServiceOverloaded,
    TicketExpired,
)
from .ir import (
    Chan,
    Clock,
    FlowIRModel,
    Sink,
    Source,
    Transfer,
    Transport,
    build_model,
)

__version__ = "0.1.0"

__all__ = [
    "DataType",
    "get_abstraction_data_type",
    "Attribute",
    "Cell",
    "CellularSpace",
    "Partition",
    "Flow",
    "Exponencial",
    "PointFlow",
    "Diffusion",
    "Coupled",
    "Model",
    "ModelRectangular",
    "Report",
    "ConservationError",
    "FailureEvent",
    "SimulationFailure",
    "check_health",
    "supervised_run",
    "AsyncEnsembleService",
    "EnsembleConservationError",
    "EnsembleExecutor",
    "EnsembleScheduler",
    "EnsembleService",
    "ServiceOverloaded",
    "TicketExpired",
    "EnsembleSpace",
    "Chan",
    "Clock",
    "FlowIRModel",
    "Sink",
    "Source",
    "Transfer",
    "Transport",
    "build_model",
    "__version__",
]
