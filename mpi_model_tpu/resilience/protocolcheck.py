"""Runtime protocol witness (ISSUE 19): validates LIVE journal streams
against the declared ticket-lifecycle machines
(``ensemble.lifecycle``), the way ``lockdep`` validates live lock
orders against the static acquisition graph.

The static protocol layer (``analysis.protocol``) proves the writer
and reader vocabularies agree with the declaration; this module
witnesses the transitions that actually happen — under the chaos
matrix — and catches what static analysis structurally cannot: the
ORDER of records on a live stream. An append site can be perfectly
declared and still emit a terminal twice, wake a ticket whose
hibernation never committed, or replay a transition out of a state the
machine forbids.

Same one-global-read-when-disarmed discipline as ``inject`` and
``lockdep``: ``TicketJournal.append`` calls :func:`journal_append`
after every durable write; while no witness is armed that is a single
module-global read and an immediate return — zero bookkeeping, no
imports, and step jaxprs are untouched (journals are host-side only;
pinned by ``tests/test_protocolcheck.py``).

What the witness records per observed append, keyed by
``(stream, ticket)`` — the stream resolved from the journal file's
basename (``lifecycle.machine_for_journal``):

- **undeclared-kind** — a record kind the stream's machine has no
  transition for (a writer drifted past the declaration);
- **missing-ticket** — a per-ticket kind appended without a ticket id
  (the fold and the timeline would both lose the record);
- **duplicate-terminal** — a terminal for a ticket already resolved:
  the exactly-once invariant broken at write time, caught before any
  replay audit runs;
- **wake-without-commit** — a tiering ``wake`` for a ticket whose
  ``hibernate`` intent was witnessed but whose ``hibernated`` commit
  never was (legal only through crash recovery's wake ladder, never on
  a live stream);
- **illegal-transition** — any other declared kind arriving from a
  state its transition does not list as a source.

A ticket FIRST seen mid-lifecycle (the witness armed around a
recovery, a journal reopened mid-test) is ADOPTED at the record's
target state instead of flagged: the witness asserts the legality of
what it saw, never guesses about history it did not.

Violations are recorded, not raised mid-serve — a witnessed fleet must
keep serving; chaos rows call ``assert_clean()`` afterwards, exactly
like the lockdep rows.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

__all__ = [
    "ProtocolViolation",
    "ProtocolWitness",
    "active",
    "armed",
    "journal_append",
]


class ProtocolViolation(AssertionError):
    """Raised by ``ProtocolWitness.assert_clean`` — carries the
    recorded violations so a failing chaos row prints the actual
    stream."""

    def __init__(self, violations: list):
        super().__init__(
            "protocolcheck witnessed %d violation(s):\n%s" % (
                len(violations),
                "\n".join(
                    f"  [{v['kind']}] {v['stream']} ticket="
                    f"{v['ticket']} record={v['record']!r} "
                    f"state={v['state']!r}" for v in violations)))
        self.violations = violations


def _default_machines() -> dict:
    # lazy: the declared machines load only when a witness arms (the
    # disarmed hot path must not import anything)
    from ..ensemble.lifecycle import MACHINES

    return dict(MACHINES)


class ProtocolWitness:
    """Runtime state of one armed witness: per-(stream, ticket) state,
    the observed-record count, and the violation log."""

    def __init__(self, machines: Optional[dict] = None):
        #: stream name → LifecycleMachine (default: the declared pair)
        self.machines = (_default_machines() if machines is None
                         else dict(machines))
        self._by_name = {m.journal_name: m
                         for m in self.machines.values()}
        self._mu = threading.Lock()  # leaf lock guarding the records
        self._state: dict = {}
        #: observed (classified) appends — rows assert this is nonzero
        #: so "zero violations" can never mean "witnessed nothing"
        self.records = 0
        #: [{"kind", "stream", "ticket", "record", "state"}]
        self.violations: list = []
        self._flagged: set = set()

    def observe(self, path: str, kind: str, meta: dict) -> None:
        """Classify one live append against its stream's machine."""
        import os

        machine = self._by_name.get(os.path.basename(path))
        if machine is None:
            return  # not a declared stream: never the witness's business
        with self._mu:
            self.records += 1
            t = machine.transition(kind)
            ticket = (meta or {}).get("ticket")
            if t is None:
                self._violation("undeclared-kind", machine.stream,
                                ticket, kind, None)
                return
            if t.ticketless:
                return
            if ticket is None:
                self._violation("missing-ticket", machine.stream,
                                None, kind, None)
                return
            key = (machine.stream, ticket)
            cur = self._state.get(key)
            if cur is None and "new" not in t.sources:
                # first sighting mid-lifecycle: adopt, never guess
                self._state[key] = t.target
                return
            state = cur if cur is not None else "new"
            if not machine.legal(kind, state):
                if t.terminal and state == t.target:
                    label = "duplicate-terminal"
                elif (machine.stream == "tiering" and kind == "wake"
                        and state == "hibernating"):
                    label = "wake-without-commit"
                else:
                    label = "illegal-transition"
                self._violation(label, machine.stream, ticket, kind,
                                state)
            # track the target either way: one bad record must not
            # cascade into a violation per subsequent record
            self._state[key] = t.target

    def _violation(self, label: str, stream: str, ticket, record,
                   state) -> None:
        sig = (label, stream, ticket, record, state)
        if sig in self._flagged:
            return
        self._flagged.add(sig)
        self.violations.append({
            "kind": label, "stream": stream, "ticket": ticket,
            "record": record, "state": state})

    # -- assertions ----------------------------------------------------------

    def assert_clean(self) -> None:
        if self.violations:
            raise ProtocolViolation(list(self.violations))


_ACTIVE: Optional[ProtocolWitness] = None


def active() -> Optional[ProtocolWitness]:
    """The armed witness, or None — THE fast path the journal seam
    checks (one global read when protocolcheck is off)."""
    return _ACTIVE


@contextlib.contextmanager
def armed(machines: Optional[dict] = None):
    """Arm a witness for the duration of the block (one at a time —
    overlapping witnesses would split the per-ticket state). Composes
    with ``lockdep.armed`` and ``inject.armed`` — each has its own
    global, so the chaos rows nest all three."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a protocol witness is already armed")
    w = ProtocolWitness(machines)
    _ACTIVE = w
    try:
        yield w
    finally:
        _ACTIVE = None


def journal_append(path: str, kind: str, meta: dict) -> None:
    """The seam ``TicketJournal.append`` fires after every durable
    write. One global read when disarmed."""
    st = _ACTIVE
    if st is None:
        return
    st.observe(path, kind, meta)
