"""Resilience package: failure detection + recovery (``supervisor``)
and deterministic fault injection (``inject``) — ISSUE 5 promoted the
former ``resilience.py`` module to this package so the chaos harness
and the self-healing policies it validates live side by side.

Supervisor symbols are re-exported lazily (PEP 562): ``supervisor``
imports ``models.model``, while ``models.model`` imports the
dependency-free ``inject`` seams from THIS package — an eager
``from .supervisor import *`` here would make that a cycle during
package init. The public surface is unchanged:
``from mpi_model_tpu.resilience import supervised_run`` etc. keep
working exactly as before the promotion.
"""

from __future__ import annotations

import importlib

_SUPERVISOR_SYMBOLS = (
    "HealthError",
    "SimulationFailure",
    "FailureEvent",
    "SupervisedResult",
    "check_health",
    "supervised_run",
)

__all__ = list(_SUPERVISOR_SYMBOLS) + [
    "inject", "lockdep", "protocolcheck", "supervisor"]


def __getattr__(name: str):
    if name in _SUPERVISOR_SYMBOLS:
        return getattr(importlib.import_module(".supervisor", __name__),
                       name)
    if name in ("inject", "lockdep", "protocolcheck", "supervisor"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(__all__)
