"""Runtime lockdep witness (ISSUE 12): armable lock wrappers that record
actual acquisition orders and assert them against the static
acquisition graph (``analysis.concurrency.static_lock_graph``).

The static layer proves the lock-order invariants over every path it can
resolve; this module witnesses the orders that actually happen — under
the chaos matrix and the soak driver — and catches what static analysis
structurally cannot: same-key nesting across two INSTANCES of one class
(statically indistinguishable from a legal RLock re-entry) and any
acquisition through a call path the resolver could not follow.

Same one-global-read-when-disarmed discipline as ``inject``: the
threaded modules create their locks through the factories below
(``lock``/``rlock``/``condition``); while no witness is armed each
factory returns the PLAIN ``threading`` primitive — zero wrapper, zero
overhead, byte-identical behavior. Objects constructed inside an
``armed()`` block get witnessed locks that report only WHILE that same
witness stays armed: once the block exits, their acquisitions go
unrecorded (the wrappers keep working, they just stop reporting) — so
a test must keep the work it wants witnessed, including ``stop()``,
inside the armed block.

What the witness records per acquisition, keyed by the lock's stable
string key (``"EnsembleScheduler._lock"`` — the same key the static
graph uses):

- **edges** — ``(held_key, acquired_key)`` for every distinct lock held
  at acquisition time (re-entry on the same instance is not an edge);
- **inversions** — an edge whose reverse was already observed, from any
  thread: the two orders together are a deadlock waiting for the right
  interleaving;
- **same-key nesting** — the same key on two different instances, the
  case the static layer must wave through for re-entrant locks;
- **unknown edges** — when armed with ``allowed=static_lock_graph()``,
  any observed order the static graph does not contain (either the
  graph regressed or a resolver gap just got witnessed — both are
  findings).

``Condition.wait`` releases the lock for the duration of the wait; the
wrapper suspends the key from the thread's held set around it so a
parked waiter can never fabricate an ordering edge.

Locks are host-side only — arming the witness cannot touch a step jaxpr
(pinned by ``tests/test_lockdep.py``).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

__all__ = [
    "LockOrderViolation",
    "LockWitness",
    "active",
    "armed",
    "condition",
    "lock",
    "rlock",
]


class LockOrderViolation(AssertionError):
    """Raised by ``LockWitness.assert_clean`` — carries the recorded
    violations so a failing chaos row prints the actual orders."""

    def __init__(self, violations: list):
        super().__init__(
            "lockdep witnessed %d ordering violation(s):\n%s" % (
                len(violations),
                "\n".join(f"  [{v['kind']}] {v['a']} vs {v['b']} "
                          f"(thread {v['thread']})" for v in violations)))
        self.violations = violations


class LockWitness:
    """Runtime state of one armed witness: per-thread held stacks, the
    observed edge set, and the violation log (never raises mid-serve —
    a witnessed fleet must keep serving; tests assert afterwards)."""

    def __init__(self, allowed: Optional[set] = None):
        #: the static graph to assert against (None = learn-only)
        self.allowed = None if allowed is None else set(allowed)
        self._mu = threading.Lock()  # leaf lock guarding the records
        self._tls = threading.local()
        #: (held_key, acquired_key) → name of the first witnessing thread
        self.edges: dict = {}
        #: [{"kind", "a", "b", "thread"}] in observation order
        self.violations: list = []
        self._flagged: set = set()

    # -- bookkeeping (called by the wrappers) --------------------------------

    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = []
            self._tls.stack = s
        return s

    def note_acquiring(self, lk: "_WitnessLock") -> None:
        stack = self._stack()
        if any(h is lk for h in stack):
            stack.append(lk)  # same-instance re-entry: never an edge
            return
        held: list = []
        seen: set = set()
        for h in stack:
            if id(h) not in seen:
                seen.add(id(h))
                held.append(h)
        if held:
            with self._mu:
                for h in held:
                    self._edge(h.key, lk.key)
        stack.append(lk)

    def note_release(self, lk: "_WitnessLock") -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lk:
                del stack[i]
                return

    def suspend(self, lk: "_WitnessLock") -> int:
        """Remove every held entry of ``lk`` (Condition.wait releases
        the lock fully, saved re-entries included); returns the count
        for ``resume``."""
        stack = self._stack()
        n = sum(1 for h in stack if h is lk)
        if n:
            self._tls.stack = [h for h in stack if h is not lk]
        return n

    def resume(self, lk: "_WitnessLock", n: int) -> None:
        """Re-hold after a wait — no new edges: the thread was parked,
        every ordering fact was recorded at the original acquire."""
        if n:
            self._stack().extend([lk] * n)

    def _violation(self, kind: str, a: str, b: str) -> None:
        sig = (kind, a, b) if kind != "inversion" else (
            kind, *sorted((a, b)))
        if sig in self._flagged:
            return
        self._flagged.add(sig)
        self.violations.append({
            "kind": kind, "a": a, "b": b,
            "thread": threading.current_thread().name})

    def _edge(self, held_key: str, new_key: str) -> None:
        if held_key == new_key:
            # same key, DIFFERENT instance (same-instance re-entry was
            # filtered upstream): the nesting the static layer cannot
            # distinguish from a legal RLock re-entry — here it is real
            self._violation("same-key-nesting", held_key, new_key)
            return
        e = (held_key, new_key)
        if e not in self.edges:
            self.edges[e] = threading.current_thread().name
        if (new_key, held_key) in self.edges:
            self._violation("inversion", held_key, new_key)
        if self.allowed is not None and e not in self.allowed:
            self._violation("unknown-edge", held_key, new_key)

    # -- assertions ----------------------------------------------------------

    def assert_clean(self) -> None:
        if self.violations:
            raise LockOrderViolation(list(self.violations))


class _WitnessLock:
    """Wraps one threading primitive; quacks like Lock/RLock/Condition
    (the surface the serving stack uses: with, acquire/release, wait,
    wait_for, notify, notify_all)."""

    __slots__ = ("key", "_inner", "_witness")

    def __init__(self, key: str, inner, witness: LockWitness):
        self.key = key
        self._inner = inner
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        st = _ACTIVE
        if st is self._witness and st is not None:
            # record BEFORE blocking — the lockdep way: an inversion is
            # witnessed even if this acquire is the one that deadlocks
            st.note_acquiring(self)
        ok = self._inner.acquire(blocking, timeout)
        if not ok and _ACTIVE is self._witness and _ACTIVE is not None:
            self._witness.note_release(self)
        return ok

    def release(self):
        if _ACTIVE is self._witness and _ACTIVE is not None:
            self._witness.note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition surface (present only when the inner object has it;
    # AttributeError on a plain Lock is the same error threading gives)

    def wait(self, timeout: Optional[float] = None):
        st = _ACTIVE if _ACTIVE is self._witness else None
        n = st.suspend(self) if st is not None else 0
        try:
            return self._inner.wait(timeout)
        finally:
            if st is not None:
                st.resume(self, n)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        st = _ACTIVE if _ACTIVE is self._witness else None
        n = st.suspend(self) if st is not None else 0
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            if st is not None:
                st.resume(self, n)

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()


_ACTIVE: Optional[LockWitness] = None


def active() -> Optional[LockWitness]:
    """The armed witness, or None — THE fast path the factories check
    (one global read when lockdep is off)."""
    return _ACTIVE


@contextlib.contextmanager
def armed(allowed: Optional[set] = None):
    """Arm a witness for the duration of the block (one at a time —
    overlapping witnesses would split the edge history). Locks created
    inside the block are instrumented; pass
    ``allowed=analysis.concurrency.static_lock_graph()`` to also flag
    any observed order the static graph does not contain."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a lockdep witness is already armed")
    w = LockWitness(allowed)
    _ACTIVE = w
    try:
        yield w
    finally:
        _ACTIVE = None


def lock(key: str):
    """A (non-reentrant) mutex — plain ``threading.Lock()`` when no
    witness is armed, a witnessed wrapper otherwise. ``key`` is the
    stable order-class name shared with the static graph."""
    st = _ACTIVE
    inner = threading.Lock()
    return inner if st is None else _WitnessLock(key, inner, st)


def rlock(key: str):
    """A re-entrant mutex (``threading.RLock``), witnessed when armed."""
    st = _ACTIVE
    inner = threading.RLock()
    return inner if st is None else _WitnessLock(key, inner, st)


def condition(key: str):
    """A ``threading.Condition`` (re-entrant underneath), witnessed when
    armed — ``wait`` suspends the key from the held set, so a parked
    waiter never fabricates an ordering edge."""
    st = _ACTIVE
    inner = threading.Condition()
    return inner if st is None else _WitnessLock(key, inner, st)
