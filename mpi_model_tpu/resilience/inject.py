"""Deterministic fault injection — the chaos half of the resilience
package (ISSUE 5 tentpole).

The recovery machinery (``supervisor``, checkpoint voting, ensemble
isolation) used to be exercised only by the faults nature happened to
send; this module makes every hard path drivable on demand. A
:class:`FaultPlan` is PURE DATA — a seed plus a tuple of :class:`Fault`
records naming the seam, the firing index and the corruption parameters
— so a chaos scenario is reproducible bit-for-bit: the same plan against
the same run injects the same fault at the same place every time.

Seams (each a module-level query the instrumented code calls):

=============  ==============================================================
site           where the seam lives / what the fault does
=============  ==============================================================
``executor``   ``SerialExecutor.run_model`` / ``ShardMapExecutor.run_model``
               chunk boundaries — ``kind="exc"`` raises
               :class:`InjectedFault`; ``kind="nan"`` writes NaN/Inf into a
               channel cell of the chunk's OUTPUT; ``kind="halo"``
               (sharded only) perturbs the ghost ring for that one chunk
``checkpoint``  the ``io`` writers — ``kind="torn"`` truncates or corrupts
               the just-written file at a byte offset (dense ``.npz``,
               sharded shard file, or the sharded manifest)
``ensemble``   ``run_ensemble`` — ``kind="lane_nan"`` poisons one scenario
               lane's output (by lane index, or by ticket through the
               scheduler's mapping; ``once=False`` makes it a sticky
               SCENARIO fault that re-fires on the solo retry)
``dispatch``   the ensemble scheduler — ``kind="batch_exc"`` fails one
               whole dispatch; ``kind="hang"`` adds seconds to the
               dispatch's injectable-clock duration so the deadline
               policy sees a hang
``pump``       the async serving loop (ISSUE 9) — ``kind="thread_exc"``
               raises :class:`InjectedFault` at the top of one pump
               iteration: the loop's supervisor must count it and keep
               serving (a dead dispatch thread is a dead service)
``assemble``   batch assembly/compile on the dispatch thread —
               ``kind="slow_compile"`` adds ``seconds`` to that
               dispatch's injectable-clock duration (a hung compile),
               driving the dispatch-deadline and health-gate paths
``fetch``      the non-blocking result fetch — ``kind="fetch_nan"``
               poisons scenario lane ``lane`` (default 0) of the fetched
               output, downstream of the device program: the per-lane
               conservation machinery must catch it like any diverged
               lane
``admission``  the bounded admission queue — ``kind="queue_full"`` makes
               one submission behave as if the queue were full
               (``ServiceOverloaded`` shed), exercising the overflow
               path without needing real backlog
``pump``       fleet member faults (ISSUE 10) — ``kind="member_kill"``
               raises :class:`MemberKilled` (a BaseException: it must
               escape the pump loop's supervisor — a killed member is
               DEAD, not a survivable loop fault) so the member's
               dispatch thread dies; ``kind="member_wedge"``
               (``once=False``) makes every pump iteration a no-op — a
               live thread making zero progress. Both target ONE member
               by ``channel`` = its ``service_id`` (None matches any
               member), so a restarted member (new generation, new id)
               is born un-faulted.
``journal``    the fleet ticket journal — ``kind="journal_torn"``
               tears/corrupts the journal file right after record ``at``
               is appended (``offset`` is relative to that record's
               start), driving the recover-up-to-last-verified-entry
               path
``wire``       the multi-process fleet's wire seams (ISSUE 13), all
               targetable by ``channel`` = the member's ``service_id``
               and thresholded by ``at`` on the fleet-wide wire-RPC
               count: ``kind="proc_kill"`` delivers a REAL ``SIGKILL``
               to the member process (the loopback fake hard-stops its
               serve thread) — the supervisor must notice via missed
               heartbeats, fence, respawn gen+1 and recover tickets;
               ``kind="heartbeat_loss"`` makes the member's heartbeat
               RPC behave as timed out (the member itself is healthy —
               the failure detector path alone is exercised);
               ``kind="wire_torn"`` tears one outgoing frame at the
               ``ensemble.wire`` send seam (``tear="corrupt"`` flips
               bytes so the peer's CRC fires; ``tear="truncate"`` sends
               a prefix and closes — the crash-mid-write shape), and
               the codec must raise its typed error, never hang
``tiering``    the scenario hibernate/wake paging layer (ISSUE 14) —
               ``kind="hibernate_torn"`` tears/corrupts the chain
               record a hibernation just wrote (``at`` pins the
               chain seq; the tear is SILENT, like a real torn write —
               the wake path's verified-prefix fallback is what the
               matrix asserts); ``kind="wake_corrupt"`` damages the
               newest chain record right before a wake's restore
               (``ticket`` pins the target), driving the
               prefix-fallback → journal-re-admit → loud
               ``HibernationError`` ladder (never a silent fresh
               start); ``kind="residency_pressure"`` makes one
               admission behave as if the residency budget were
               exhausted — the paging path (hibernate instead of
               shed) without needing real memory pressure
``handshake``  the TCP accept-time HMAC challenge–response (ISSUE 20) —
               ``kind="handshake_fail"`` makes one handshake leg send a
               garbage digest, so the peer must close the connection
               with a typed auth error BEFORE any frame is parsed
               (``channel`` pins the member's ``service_id``)
``wire``       ``kind="tcp_partition"`` (ISSUE 20) — one send/recv on
               the targeted member's conn behaves as a network
               partition: the conn closes and raises ``WireTimeout``,
               exercising the jitter-tolerant deadline + fence path
               without real packet loss
``lease``      the supervisor lease (ISSUE 20) — ``kind=
               "supervisor_kill"`` delivers the simulated ``kill -9``
               to the ACTIVE supervisor at tick ``at``: it stops
               renewing its lease and abandons serving (the loopback
               hard-stop discipline), so the standby must take over
               within the lease deadline and bump the journal epoch
``journal``    ``kind="stale_epoch_append"`` (ISSUE 20) — one journal
               append behaves as if issued by a ZOMBIE supervisor (its
               handle epoch decremented below the fence), so the
               epoch fence must reject it with ``StaleEpochError``
=============  ==============================================================

Zero overhead when disarmed: every seam starts with one module-global
read (``active() is None``) on the EAGER side of the jit boundary, and
the only trace-time seam (the halo ring) returns its input untouched —
the built jaxpr is identical to an uninstrumented build (asserted in
``tests/test_chaos.py`` and by the ``analysis.jaxpr_audit`` goldens).

This module imports nothing from the rest of the package (the seams
live in modules the supervisor itself imports), so any layer can import
it without cycles.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from typing import Optional

__all__ = [
    "Fault",
    "FaultPlan",
    "ArmedPlan",
    "InjectedFault",
    "MemberKilled",
    "armed",
    "active",
    "halo_perturbation",
    "build_token",
    "poison_values",
    "checkpoint_torn",
    "journal_torn",
    "hibernate_torn",
    "wake_corrupt",
    "stale_epoch_append",
    "tear_file",
]


class InjectedFault(RuntimeError):
    """The exception an armed ``exc``/``batch_exc`` fault raises — a
    distinct type so tests and supervisors can tell injected chaos from
    a genuine failure leaking through the same path."""


class MemberKilled(BaseException):
    """The ``member_kill`` fault (ISSUE 10): deliberately a
    ``BaseException`` so the async pump loop's ``except Exception``
    supervisor does NOT survive it — the member's dispatch thread dies,
    which is exactly the failure domain the fleet supervisor must
    detect, fence and restart. Only the fleet's own pump wrapper (manual
    mode) catches it, to mark the member dead."""


#: fault kind → seam site (one table, so a typo'd kind fails at plan
#: construction instead of silently never firing)
SITE_OF = {
    "exc": "executor",
    "nan": "executor",
    "halo": "executor",
    "torn": "checkpoint",
    "lane_nan": "ensemble",
    "batch_exc": "dispatch",
    "hang": "dispatch",
    # ISSUE 9: the always-on async serving seams
    "thread_exc": "pump",
    "slow_compile": "assemble",
    "fetch_nan": "fetch",
    "queue_full": "admission",
    # ISSUE 10: the fleet-supervision seams
    "member_kill": "pump",
    "member_wedge": "pump",
    "journal_torn": "journal",
    # ISSUE 13: the multi-process fleet's wire seams
    "proc_kill": "wire",
    "heartbeat_loss": "wire",
    "wire_torn": "wire",
    # ISSUE 14: the scenario-tiering (hibernate/wake paging) seams
    "hibernate_torn": "tiering",
    "wake_corrupt": "tiering",
    "residency_pressure": "tiering",
    # ISSUE 20: multi-host fleet + supervisor failover seams
    "handshake_fail": "handshake",
    "tcp_partition": "wire",
    "supervisor_kill": "lease",
    "stale_epoch_append": "journal",
}


@dataclasses.dataclass(frozen=True)
class Fault:
    """One armed fault: WHERE (kind → seam site), WHEN (``at`` = the
    seam's 0-based firing index: executor chunk, dispatch count, or —
    for ``torn`` — the checkpoint STEP), and the corruption parameters.
    ``once=True`` (default) consumes the fault after its first firing —
    a TRANSIENT fault the recovery layer must heal; ``once=False`` keeps
    it armed — a DETERMINISTIC fault (e.g. a poisoned scenario) the
    layer must fail fast on / quarantine."""

    kind: str
    #: seam firing index (None = first opportunity); for "torn" this is
    #: the checkpoint step being written, for "hibernate_torn" the
    #: chain seq being written; for the member faults
    #: ("member_kill"/"member_wedge") it is a THRESHOLD, not an index:
    #: the fault is eligible only once the pump site has been visited
    #: at least ``at`` times fleet-wide — how a chaos plan lands a kill
    #: MID-soak instead of at the first pump after arming
    at: Optional[int] = None
    #: channel to poison ("nan"/"lane_nan"; None → first channel). The
    #: member faults ("member_kill"/"member_wedge") reuse this as the
    #: TARGET ``service_id`` (None = any member), and "journal_torn"/
    #: "torn" as the part name being written
    channel: Optional[str] = None
    #: cell to poison (None → (0, 0))
    cell: Optional[tuple] = None
    #: scenario lane to poison (direct run_ensemble use; also the
    #: "fetch_nan" target lane, default 0)
    lane: Optional[int] = None
    #: scheduler ticket whose lane to poison (the scheduler maps it);
    #: "wake_corrupt" reuses this as the hibernated ticket to target
    #: (None = any wake)
    ticket: Optional[int] = None
    #: byte offset for "torn"
    offset: int = 0
    #: bytes corrupted at the offset ("torn", tear="corrupt")
    nbytes: int = 64
    #: "truncate" (tear the file AT offset) or "corrupt" (flip bytes)
    tear: str = "corrupt"
    #: injected hang duration ("hang"/"slow_compile"), in
    #: injectable-clock seconds
    seconds: float = 0.0
    #: poison / perturbation value (None → NaN for poisons, 1.0 for halo)
    value: Optional[float] = None
    once: bool = True

    def __post_init__(self):
        if self.kind not in SITE_OF:
            raise ValueError(
                f"unknown fault kind {self.kind!r} "
                f"(expected one of {sorted(SITE_OF)})")
        if self.tear not in ("truncate", "corrupt"):
            raise ValueError(f"unknown tear mode {self.tear!r}")
        if (self.kind in ("member_wedge", "heartbeat_loss", "proc_kill",
                          "wire_torn", "tcp_partition")
                and not self.once and self.channel is None):
            # an unpinned sticky member/wire fault would re-fault every
            # replacement generation: fence → restart → fault, forever
            # — pin the member it targets
            raise ValueError(
                f"a sticky {self.kind} (once=False) must pin its "
                "member via channel=service_id — unpinned it would "
                "hit every replacement generation too, an unbounded "
                "fence/restart loop")

    @property
    def site(self) -> str:
        return SITE_OF[self.kind]


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A reproducible chaos scenario: pure data, safe to log/serialize.
    ``seed`` feeds the derived perturbation values (``value_for``) so an
    unpinned fault still corrupts deterministically."""

    faults: tuple
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "faults", tuple(self.faults))

    def value_for(self, index: int) -> float:
        """Deterministic perturbation magnitude for fault ``index`` when
        its ``value`` is unpinned: drawn from a generator seeded by
        ``(seed, index)`` — stable across runs and platforms."""
        import numpy as np

        return float(np.random.default_rng((self.seed, index))
                     .uniform(1.0, 2.0))


class ArmedPlan:
    """Runtime state of one armed plan: per-site firing counters, the
    consumed-fault set, and the observable ``fired`` log (what actually
    went off, in order — chaos tests assert completeness against it).

    Internally locked since ISSUE 13: the wire seams consult
    ``member_fault``/``bump`` from every client thread plus the
    supervision tick concurrently (the pre-wire seams all ran on one
    pump/tick thread), and a racing read-modify-write on the counters
    or the consumed set would shift ``at`` thresholds or double-fire a
    ``once`` fault — nondeterministic chaos under exactly the
    multi-threaded load the seams exist to test. The mutex is a plain
    leaf lock (nothing is ever acquired under it; lockdep factories
    would invert the inject-imports-nothing layering)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._mutex = threading.Lock()
        self._counters: dict = {}
        self._consumed: set = set()
        #: [{"index", "site", "kind", "at"}] — every firing, in order
        self.fired: list = []
        #: trace-time halo perturbation (set only inside halo_window)
        self.halo_eps: Optional[float] = None
        #: (lane, Fault) poisons the scheduler pushed for the CURRENT
        #: physical dispatch (ticket → lane mapping is the scheduler's)
        self._lane_poisons: list = []

    def bump(self, site: str) -> int:
        """Advance and return ``site``'s firing index (counts every
        seam visit — retries included — so ``at`` is deterministic)."""
        with self._mutex:
            idx = self._counters.get(site, 0)
            self._counters[site] = idx + 1
            return idx

    def take(self, site: str, index: Optional[int] = None,
             kinds: Optional[tuple] = None) -> Optional[Fault]:
        """First live fault matching (site, index, kinds); consumes it
        when ``once``. ``index=None`` matches only index-unpinned
        faults."""
        with self._mutex:
            for i, f in enumerate(self.plan.faults):
                if f.site != site or (kinds is not None
                                      and f.kind not in kinds):
                    continue
                if i in self._consumed:
                    continue
                if f.at is not None and f.at != index:
                    continue
                if f.ticket is not None:
                    continue  # ticket faults fire via ticket_fault only
                self._fire_locked(i, f)
                return f
            return None

    def member_fault(self, service_id, kinds: tuple, site: str = "pump",
                     count: bool = False) -> Optional[Fault]:
        """Live member-targeted fault (``member_kill``/``member_wedge``
        on the pump site; ``proc_kill``/``heartbeat_loss``/
        ``wire_torn`` on the wire site) aimed at ``service_id``: a
        fault whose ``channel`` is None (any member) or equals the id,
        and whose ``at`` threshold — a minimum fleet-wide ``site``
        visit count, for mid-soak timing — has been reached.
        ``count=True`` advances the site counter first (the wire seams
        count per RPC through this call; the pump seam keeps its own
        explicit ``bump``). Consumed per ``once`` — a sticky fault
        (``once=False``, channel-pinned by construction) re-fires until
        its member is restarted under a new id."""
        with self._mutex:
            if count:
                idx = self._counters.get(site, 0)
                self._counters[site] = idx + 1
            pumps = self._counters.get(site, 0)
            for i, f in enumerate(self.plan.faults):
                if f.kind not in kinds or i in self._consumed:
                    continue
                if f.channel is not None and f.channel != service_id:
                    continue
                if f.at is not None and pumps < f.at:
                    continue
                self._fire_locked(i, f)
                return f
            return None

    def ticket_fault(self, ticket) -> Optional[Fault]:
        """Live ``lane_nan`` fault bound to ``ticket`` (the scheduler's
        per-dispatch lane mapping); consumed per its ``once``."""
        with self._mutex:
            for i, f in enumerate(self.plan.faults):
                if (f.kind == "lane_nan" and f.ticket == ticket
                        and i not in self._consumed):
                    self._fire_locked(i, f)
                    return f
            return None

    def _fire_locked(self, i: int, f: Fault) -> None:
        if f.once:
            self._consumed.add(i)
        self.fired.append({"index": i, "site": f.site, "kind": f.kind,
                           "at": f.at})

    def _fire(self, i: int, f: Fault) -> None:
        """Mark fault ``i`` fired (the single-threaded seam helpers —
        checkpoint/journal tears, lane poisons — call this)."""
        with self._mutex:
            self._fire_locked(i, f)

    # -- halo window (trace-time seam, chunk-scoped) -----------------------

    @contextlib.contextmanager
    def halo_window(self, fault: Fault):
        """Arm the trace-time halo perturbation for the duration of ONE
        executor chunk; pad_with_halo_* read it while tracing."""
        idx = self.plan.faults.index(fault)
        eps = (fault.value if fault.value is not None
               else self.plan.value_for(idx))
        with self._mutex:
            self.halo_eps = eps
        try:
            yield
        finally:
            with self._mutex:
                self.halo_eps = None

    # -- ensemble lane poisons (scheduler ticket → lane mapping) -----------

    def push_lane_poisons(self, poisons: list) -> None:
        with self._mutex:
            self._lane_poisons = list(poisons)

    def clear_lane_poisons(self) -> None:
        with self._mutex:
            self._lane_poisons = []

    def ensemble_poisons(self, index: int) -> list:
        """(lane, Fault) pairs to poison in this ``run_ensemble`` call:
        scheduler-pushed ticket poisons plus any direct lane faults
        matching the ensemble-site firing index."""
        out = list(self._lane_poisons)
        for i, f in enumerate(self.plan.faults):
            if (f.kind == "lane_nan" and f.ticket is None
                    and f.lane is not None and i not in self._consumed
                    and (f.at is None or f.at == index)):
                self._fire(i, f)
                out.append((f.lane, f))
        return out


_ACTIVE: Optional[ArmedPlan] = None


def active() -> Optional[ArmedPlan]:
    """The armed plan's runtime state, or None — THE fast path every
    seam checks first (one global read when chaos is off)."""
    return _ACTIVE


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """Arm ``plan`` for the duration of the block (one plan at a time —
    overlapping chaos scenarios would not be reproducible)."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a FaultPlan is already armed")
    st = ArmedPlan(plan)
    _ACTIVE = st
    try:
        yield st
    finally:
        _ACTIVE = None


# -- seam helpers (called by the instrumented modules) ------------------------

def halo_perturbation() -> Optional[float]:
    """Trace-time ghost-ring perturbation, or None (the unarmed value —
    the pad functions return their input untouched, identical jaxpr)."""
    st = _ACTIVE
    return None if st is None else st.halo_eps


def build_token():
    """Runner-cache key component: non-None only while a halo fault is
    armed, so a perturbed build never poisons the clean runner cache
    (and the clean cache key is byte-identical to the uninstrumented
    one's shape)."""
    st = _ACTIVE
    if st is None or st.halo_eps is None:
        return None
    return ("chaos-halo", st.halo_eps)


def poison_values(values: dict, fault: Fault, plan: FaultPlan) -> dict:
    """Host-side state poison: NaN (or ``fault.value``) written into one
    cell of one channel of an executor chunk's OUTPUT values."""
    import jax.numpy as jnp

    ch = fault.channel if fault.channel is not None else next(iter(values))
    x, y = fault.cell if fault.cell is not None else (0, 0)
    v = values[ch]
    bad = jnp.asarray(float("nan") if fault.value is None else fault.value,
                      v.dtype)
    return {**values, ch: v.at[x, y].set(bad)}


def poison_lane_values(values_b: dict, lane: int, fault: Fault) -> dict:
    """Lane poison for the ensemble engine: NaN into one cell of one
    channel of scenario ``lane``'s output."""
    import jax.numpy as jnp

    ch = fault.channel if fault.channel is not None else next(iter(values_b))
    x, y = fault.cell if fault.cell is not None else (0, 0)
    v = values_b[ch]
    bad = jnp.asarray(float("nan") if fault.value is None else fault.value,
                      v.dtype)
    return {**values_b, ch: v.at[lane, x, y].set(bad)}


def checkpoint_torn(path: str, step: int, part: str = "data") -> None:
    """Checkpoint-writer seam: tear/corrupt the just-written file when a
    ``torn`` fault is armed for this step. ``part`` names what was
    written — "data" (a dense ``.npz`` / sharded shard file),
    "manifest" (the sharded commit record), or the delta layout's
    "keyframe" / "delta" records and "chain" manifest — and a fault
    pins its target via the ``channel`` field. An unpinned fault
    (``channel=None``, the "data" default) matches any DATA part
    (dense, shard, keyframe, delta), so one plan drives every layout;
    the commit records ("manifest", "chain") must be named
    explicitly."""
    st = _ACTIVE
    if st is None:
        return
    for i, f in enumerate(st.plan.faults):
        if f.kind != "torn" or i in st._consumed:
            continue
        if f.at is not None and f.at != step:
            continue
        want_part = f.channel or "data"
        if want_part != part and not (
                want_part == "data" and part in ("keyframe", "delta")):
            continue
        st._fire(i, f)
        tear_file(path, f.offset, f.nbytes, f.tear)
        return


def journal_torn(path: str, index: int, record_start: int) -> None:
    """Ticket-journal seam (ISSUE 10): tear/corrupt the fleet journal
    right after record ``index`` was appended. The fault's ``offset`` is
    RELATIVE to the just-written record's byte start, so ``tear=
    "truncate", offset=3`` models a write torn mid-record (the classic
    crash shape) and the reader's recover-up-to-last-CRC-verified-entry
    contract is what the matrix asserts."""
    st = _ACTIVE
    if st is None:
        return
    for i, f in enumerate(st.plan.faults):
        if f.kind != "journal_torn" or i in st._consumed:
            continue
        if f.at is not None and f.at != index:
            continue
        st._fire(i, f)
        tear_file(path, record_start + f.offset, f.nbytes, f.tear)
        return


def hibernate_torn(path: str, seq: int) -> None:
    """Scenario-tiering seam (ISSUE 14): tear/corrupt the chain record
    a hibernation just wrote. ``at`` pins the chain seq being written
    (None = first opportunity). The tear is SILENT — hibernate goes on
    to commit its journal record, exactly like a write torn by a real
    crash or bit rot after the fact — so the wake path's
    verified-prefix fallback (an earlier chain record, bitwise-equal
    for a queued scenario) is what recovers it."""
    st = _ACTIVE
    if st is None:
        return
    for i, f in enumerate(st.plan.faults):
        if f.kind != "hibernate_torn" or i in st._consumed:
            continue
        if f.at is not None and f.at != seq:
            continue
        st._fire(i, f)
        tear_file(path, f.offset, f.nbytes, f.tear)
        return


def wake_corrupt(ticket) -> Optional["Fault"]:
    """Scenario-tiering seam (ISSUE 14): a live ``wake_corrupt`` fault
    aimed at ``ticket`` (``ticket=None`` matches any wake), consumed
    per ``once``. The tiering layer applies the fault's tear to the
    ticket's NEWEST chain record before restoring, so the wake must
    walk back to the verified prefix, re-admit from the journal, or
    fail loudly — never resume wrong or fresh state."""
    st = _ACTIVE
    if st is None:
        return None
    with st._mutex:
        for i, f in enumerate(st.plan.faults):
            if f.kind != "wake_corrupt" or i in st._consumed:
                continue
            if f.ticket is not None and f.ticket != ticket:
                continue
            st._fire_locked(i, f)
            return f
        return None


def stale_epoch_append(path: str) -> bool:
    """Journal epoch-fence seam (ISSUE 20): True when a live
    ``stale_epoch_append`` fault says THIS append should behave as a
    zombie supervisor's — the epoch-fenced ``TicketJournal.append``
    then checks the fence with its handle epoch decremented, so the
    fence must reject the record with ``StaleEpochError`` (the
    defense-in-depth the failover matrix asserts without needing a
    real resurrected process)."""
    st = _ACTIVE
    if st is None:
        return False
    with st._mutex:
        for i, f in enumerate(st.plan.faults):
            if f.kind != "stale_epoch_append" or i in st._consumed:
                continue
            if f.channel is not None and f.channel != path:
                continue
            st._fire_locked(i, f)
            return True
        return False


def tear_file(path: str, offset: int = 0, nbytes: int = 64,
              tear: str = "corrupt") -> None:
    """Deterministically damage ``path``: ``truncate`` cuts the file at
    ``offset`` (a write torn mid-flight); ``corrupt`` flips ``nbytes``
    bytes starting there (bit rot the checksums must catch)."""
    size = os.path.getsize(path)
    if tear == "truncate":
        with open(path, "r+b") as fh:
            fh.truncate(min(offset, size))
        return
    off = min(offset, max(size - 1, 0))
    with open(path, "r+b") as fh:
        fh.seek(off)
        data = fh.read(nbytes)
        fh.seek(off)
        fh.write(bytes(b ^ 0xFF for b in data))
