"""Failure detection and recovery — the subsystem the reference lacks.

SURVEY §5: the reference has **no failure handling** — live code ignores
MPI return codes entirely (``/root/reference/src/Model.hpp:73,85,90``),
exceptions exist only in the dead generic layer (``MPIImpl.cpp:7,13``),
and a failed rank means a hung job. Here failure handling is
first-class and built from the pieces the framework already has:

- ``check_health`` — **in-band failure detection**: non-finite values
  (NaN/Inf divergence, the signature of a dead shard or a numerically
  exploded kernel) and conservation drift beyond the model's contract
  (the reference's own invariant, ``Model.hpp:95``, used as a *detector*
  instead of a crash). One device-side reduction per channel.
- ``supervised_run`` — **checkpoint-based recovery**: chunked execution
  under a supervisor; every chunk is health-checked and checkpointed,
  and a failure (executor exception OR detected bad state) rolls back to
  the last good state and retries, up to ``max_failures`` consecutive
  failures, then raises ``SimulationFailure`` carrying the full event
  log. A transient device fault costs one chunk of recompute; state
  after recovery is bit-identical to an uninterrupted run (proven in
  ``tests/test_resilience.py``).
- ``FailureEvent`` — the observable record of every detection/recovery,
  for the tracing/metrics layer and post-mortems.

Recovery is *rollback* recovery (the right design for a jit-compiled
SPMD step: re-running a pure function on restored state is exact),
not rank-level elasticity — on a TPU slice a lost chip is a lost slice,
and the unit of restart is the program. ``CheckpointManager`` makes the
rollback durable across process restarts; with ``manager=None`` the
supervisor keeps the last good state in memory (cheap, non-durable).
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, Optional

from ..core.cellular_space import CellularSpace
from ..io.checkpoint import CheckpointManager
from ..models.model import Model, Report

__all__ = [
    "HealthError",
    "SimulationFailure",
    "FailureEvent",
    "SupervisedResult",
    "check_health",
    "supervised_run",
]


class HealthError(RuntimeError):
    """In-band state-health check failed (non-finite values or
    conservation drift); carries the list of problems found."""

    def __init__(self, problems: list[str]):
        super().__init__("; ".join(problems))
        self.problems = problems


class SimulationFailure(RuntimeError):
    """The supervisor exhausted ``max_failures`` consecutive recovery
    attempts; ``events`` holds the full failure log."""

    def __init__(self, message: str, events: list["FailureEvent"]):
        super().__init__(message)
        self.events = events


@dataclasses.dataclass
class FailureEvent:
    """One detected failure and what the recovery layer did about it —
    emitted by the supervisor (rollback/retry) and by the ensemble
    scheduler (quarantine), so one record type feeds tracing, metrics
    and post-mortems everywhere."""

    #: step the failed chunk would have reached
    step: int
    #: "exception" (executor raised) | "nonfinite" | "conservation"
    #: | "timeout" (a dispatch overran its deadline) | "expired" (a
    #: queued ticket's per-ticket deadline passed before dispatch —
    #: the ISSUE 9 serving path; never a silent drop) | "member" (a
    #: fleet member was fenced — dead pump, supervision-deadline wedge
    #: or ladder bottom — and restarted fresh, ISSUE 10) |
    #: "hibernation" (a hibernated scenario could not be woken from
    #: any source — chain, journal — and resolved loudly, ISSUE 14)
    kind: str
    detail: str
    #: step rolled back to (== step of the last good checkpoint)
    rolled_back_to: int
    #: consecutive-failure count at the time (1 = first)
    attempt: int
    wall_time_s: float
    #: "transient" (retried) or "deterministic" (the SAME fault recurred
    #: identically after rollback — the supervisor fails fast instead of
    #: burning max_failures recomputing a poisoned chunk; for the
    #: scheduler, a scenario whose solo retry failed too)
    classification: str = "transient"
    #: backoff slept before the retry this event triggered (0 = none)
    backoff_s: float = 0.0
    #: the scheduler ticket this event quarantined (None for supervisor
    #: events — tickets are a serving-layer concept)
    ticket: Optional[int] = None
    #: the serving member that emitted this event (ISSUE 10: fleet-level
    #: logs must be attributable per member); None outside serving
    service_id: Optional[str] = None


@dataclasses.dataclass
class SupervisedResult:
    """Final state + provenance of a supervised run."""

    space: CellularSpace
    step: int
    #: the LAST chunk's report; None when a resumed run was already at
    #: the requested step count (use ``initial_totals`` + the space for
    #: run-global accounting)
    report: Optional[Report]
    events: list[FailureEvent]
    #: the run-global conservation baseline (from the first chunk or the
    #: resumed checkpoint's extra)
    initial_totals: dict[str, float] = dataclasses.field(
        default_factory=dict)

    @property
    def recovered_failures(self) -> int:
        return len(self.events)


def check_health(space: CellularSpace,
                 initial_totals: Optional[dict[str, float]] = None,
                 threshold: Optional[float] = None,
                 view: Optional[Callable[[dict], dict]] = None
                 ) -> list[str]:
    """Detect bad simulation state; returns a list of problems (empty =
    healthy). Checks every attribute channel for non-finite values and —
    when ``initial_totals``/``threshold`` are given — total-mass drift
    beyond the conservation contract. All checks are device-side
    reductions (one ``isfinite().all()``, one ``sum`` per channel,
    accumulated in f32-or-wider); every resulting scalar is fetched in
    ONE ``jax.device_get`` — the check costs one host sync regardless
    of channel count (a per-channel ``bool()`` loop would serialize a
    round-trip per channel), so it stays cheap even at 1e8 cells and on
    sharded arrays (the sums lower to ICI all-reduces)."""
    import jax
    import jax.numpy as jnp

    problems: list[str] = []
    names, scalars = [], []
    for name, arr in space.values.items():
        acc = jnp.promote_types(arr.dtype, jnp.float32)
        names.append(name)
        scalars.append((jnp.isfinite(arr).all(), jnp.sum(arr, dtype=acc)))
    fetched = jax.device_get(scalars)  # device work above, ONE sync here
    totals: dict[str, float] = {}
    any_nonfinite = False
    for name, (finite, total) in zip(names, fetched):
        if not bool(finite):
            any_nonfinite = True
            problems.append(
                f"channel {name!r}: non-finite cell(s) "
                "(NaN/Inf divergence)")
            continue  # totals of a non-finite channel are meaningless
        totals[name] = float(total)
    if initial_totals is None or threshold is None:
        return problems
    if view is not None:
        # IR models (ISSUE 11): drift is judged on the conservation
        # VIEW — summed mass reconciled against the integrated per-term
        # budgets, plus the per-term contract keys — not on raw channel
        # totals (a declared source's per-channel drift is physics).
        # With any non-finite channel the view sums would be NaN; the
        # nonfinite problem above already tells the truth there.
        if not any_nonfinite:
            try:
                vi = view(initial_totals)
                vt = view(totals)
            except KeyError:
                # a baseline captured before some view channel existed
                # (e.g. a resume from a pre-IR checkpoint): no drift
                # reference — same skip-don't-KeyError rule as the
                # legacy per-channel branch below
                return problems
            for key in vi:
                if key not in vt:
                    continue
                drift = abs(float(vt[key]) - float(vi[key]))
                if drift > threshold:
                    problems.append(
                        f"channel {key!r}: conservation drift "
                        f"{drift:.3e} > {threshold:.3e}")
        return problems
    for name, total in totals.items():
        baseline = initial_totals.get(name)
        if baseline is None:
            # a channel added after the baseline was captured (e.g. a
            # resumed run whose checkpoint predates it) has no drift
            # reference — skip rather than KeyError mid-health-check
            continue
        drift = abs(total - baseline)
        if drift > threshold:
            problems.append(
                f"channel {name!r}: conservation drift {drift:.3e} > "
                f"{threshold:.3e}")
    return problems


def _classify(exc: BaseException) -> str:
    if isinstance(exc, HealthError):
        return ("conservation" if any("conservation" in p
                                      for p in exc.problems)
                else "nonfinite")
    return "exception"


def supervised_run(
    model: Model,
    space: CellularSpace,
    manager: Optional[CheckpointManager] = None,
    *,
    steps: Optional[int] = None,
    every: int = 1,
    max_failures: int = 3,
    executor=None,
    health_checks: bool = True,
    tolerance: float = 1e-3,
    rtol: Optional[float] = None,
    on_event: Optional[Callable[[FailureEvent], None]] = None,
    retry_backoff_s: float = 0.0,
    backoff_jitter: float = 0.5,
    backoff_seed: int = 0,
    fail_fast_deterministic: bool = True,
) -> SupervisedResult:
    """Run ``model`` for ``steps`` under failure supervision.

    The run advances in chunks of ``every`` steps. After each chunk the
    state is health-checked (``check_health``: finiteness + conservation
    against the run's ORIGINAL initial totals — drift is bounded over the
    whole run, not per chunk) and, when a ``manager`` is given, durably
    checkpointed. On any failure — the executor raising, or the health
    check failing — the supervisor rolls back to the last good state and
    re-runs the chunk. ``max_failures`` bounds *consecutive* failures
    (a success resets the count); exhausting it raises
    ``SimulationFailure`` with the event log.

    With a ``manager``, a previously interrupted supervised run resumes
    from its latest checkpoint (the original initial totals travel inside
    the checkpoint's ``extra``, so the conservation baseline survives the
    restart). ``on_event`` observes each ``FailureEvent`` as it happens
    (wire it to logging/metrics). ``health_checks=False`` disables the
    in-band state checks (executor exceptions are still supervised) —
    ``io.run_checkpointed`` is this function with ``max_failures=0``.

    ``retry_backoff_s > 0`` sleeps before each retry — exponential in
    the consecutive-failure count with a JITTERED factor drawn from a
    generator seeded by ``backoff_seed`` (deterministic per run, but
    decorrelated across a fleet of restarting supervisors hammering a
    shared filesystem/coordinator). The slept duration is recorded on
    the event (``FailureEvent.backoff_s``).

    ``fail_fast_deterministic`` (default on) classifies each failure
    against the previous one: when the SAME fault (kind, step, detail)
    recurs immediately after a rollback, the fault is deterministic —
    recomputing the chunk can only reproduce it — so the supervisor
    raises ``SimulationFailure`` at once instead of burning
    ``max_failures`` retries on a poisoned chunk. The classification
    rides each event (``FailureEvent.classification``).
    """
    total = model.num_steps if steps is None else int(steps)
    if every <= 0:
        raise ValueError(f"every must be positive, got {every}")

    start = 0
    initial: Optional[dict[str, float]] = None
    if manager is not None:
        # an async manager may hold a STAGED save from earlier caller
        # activity: commit it first, or latest()/steps() below read a
        # stale resume point and our own first save would commit the
        # unrelated step out from under this run
        getattr(manager, "flush", lambda: None)()
        # resume onto the executor's mesh when it has one: a sharded
        # (per-process) checkpoint then restores O(shard) via
        # make_array_from_callback instead of dense-assembling the full
        # grid on every host (dense .npz checkpoints ignore the mesh)
        ck = manager.latest(mesh=getattr(executor, "mesh", None))
        if ck is not None:
            if ck.step > total:
                raise ValueError(
                    f"latest checkpoint is at step {ck.step} > requested "
                    f"total {total}")
            space, start = ck.space, ck.step
            saved = ck.extra.get("initial_totals")
            if saved is not None:
                initial = {k: float(v) for k, v in saved.items()}
    if initial is None:
        initial = {k: float(space.total(k)) for k in space.values}
    threshold = (model.conservation_threshold(
        space, tolerance, rtol, initial_totals=initial)
        if health_checks else None)

    # Last good state: durable via the manager when present, always also
    # in memory so rollback never needs disk on the hot path.
    good_space, good_step = space, start
    if manager is not None and not manager.steps():
        manager.save(good_space, good_step,
                     extra={"initial_totals": initial})

    from ..utils.tracing import get_tracer

    tracer = get_tracer()
    events: list[FailureEvent] = []
    # explicit flag, NOT sys.exc_info(): exc_info is thread-global and
    # also reports an exception a CALLER is currently handling, which
    # would make a successful run called from inside an except block
    # swallow its own flush failure
    run_raising = False
    try:
        return _supervise_loop(
            model, space, manager, total, every, max_failures, executor,
            health_checks, threshold, initial, good_space, good_step,
            tracer, events, on_event,
            _RetryPolicy(retry_backoff_s, backoff_jitter, backoff_seed,
                         fail_fast_deterministic))
    except BaseException:
        run_raising = True
        raise
    finally:
        if manager is not None:
            # async managers: the last good step's write may still be in
            # flight — commit it EVEN when the run is raising, or a
            # verified-good checkpoint dies staged (the exact scenario
            # checkpoints exist for). A flush failure must not mask the
            # run's own exception, but must PROPAGATE when the run
            # succeeded.
            try:
                getattr(manager, "flush", lambda: None)()
            # analysis: ignore[broad-except] — unwind boundary: a flush
            # failure must not mask the run's own in-flight exception
            # (it re-raises only when the run succeeded)
            except BaseException:
                if not run_raising:
                    raise
                tracer.instant("supervise.flush_failed")


@dataclasses.dataclass(frozen=True)
class _RetryPolicy:
    """The supervisor's between-retry knobs, bundled so the loop keeps
    a readable signature."""

    backoff_s: float
    jitter: float
    seed: int
    fail_fast: bool

    def delay(self, rng, attempt: int) -> float:
        """Jittered exponential backoff for consecutive failure
        ``attempt`` (1-based); 0.0 when backoff is off."""
        if self.backoff_s <= 0.0:
            return 0.0
        return (self.backoff_s * (2.0 ** (attempt - 1))
                * (1.0 + self.jitter * float(rng.random())))


def _supervise_loop(model, space, manager, total, every, max_failures,
                    executor, health_checks, threshold, initial,
                    good_space, good_step, tracer, events, on_event,
                    retry: _RetryPolicy) -> SupervisedResult:
    consecutive = 0
    report: Optional[Report] = None
    # seeded ONCE per run: backoff jitter is reproducible given the seed
    # yet still decorrelates a fleet (different seeds per process)
    backoff_rng = None
    if retry.backoff_s > 0.0:
        import numpy as np

        backoff_rng = np.random.default_rng(retry.seed)
    #: (kind, step, detail) of the previous failure — an identical
    #: consecutive signature means the fault is deterministic
    last_sig = None
    while good_step < total:
        n = min(every, total - good_step)
        t0 = _time.perf_counter()
        try:
            with tracer.span("supervise.chunk", start=good_step, steps=n):
                # conservation is checked HERE against the run-global
                # baseline; execute()'s own per-chunk check would
                # re-baseline each chunk
                out_space, report = model.execute(
                    good_space, executor, steps=n, check_conservation=False)
                if health_checks:
                    problems = check_health(
                        out_space, initial, threshold,
                        view=getattr(model, "conservation_view", None))
                    if problems:
                        raise HealthError(problems)
        # analysis: ignore[broad-except] — THE supervisor boundary: any
        # step/health failure becomes a FailureEvent + rollback; only
        # max_failures exhaustion re-raises
        except Exception as exc:  # noqa: BLE001 — supervisor boundary
            consecutive += 1
            detail = f"{type(exc).__name__}: {exc}"
            sig = (_classify(exc), good_step + n, detail)
            deterministic = retry.fail_fast and sig == last_sig
            last_sig = sig
            exhausted = consecutive > max_failures
            backoff = (0.0 if deterministic or exhausted
                       else retry.delay(backoff_rng, consecutive))
            ev = FailureEvent(
                step=good_step + n,
                kind=sig[0],
                detail=detail,
                rolled_back_to=good_step,
                attempt=consecutive,
                wall_time_s=_time.perf_counter() - t0,
                classification=("deterministic" if deterministic
                                else "transient"),
                backoff_s=backoff,
            )
            events.append(ev)
            tracer.instant("supervise.failure", kind=ev.kind,
                           step=ev.step, attempt=ev.attempt,
                           detail=ev.detail,
                           rolled_back_to=ev.rolled_back_to,
                           classification=ev.classification)
            if on_event is not None:
                on_event(ev)
            if deterministic:
                # the same fault at the same step with the same detail,
                # straight after a rollback: recomputing the chunk can
                # only reproduce it — fail fast instead of burning the
                # remaining max_failures budget on a poisoned chunk
                raise SimulationFailure(
                    f"deterministic failure at step {good_step + n}: the "
                    "same fault recurred identically after rollback "
                    f"(failing fast; max_failures={max_failures} "
                    f"unspent); last: {ev.detail}", events) from exc
            if exhausted:
                raise SimulationFailure(
                    f"{consecutive} consecutive failures at step "
                    f"{good_step + n} (max_failures={max_failures}); "
                    f"last: {ev.detail}", events) from exc
            if backoff > 0.0:
                _time.sleep(backoff)
            # roll back: re-run the chunk from the last good state (the
            # in-memory copy; the manager holds the same state durably)
            continue

        consecutive = 0
        last_sig = None
        good_space, good_step = out_space, good_step + n
        if manager is not None:
            kw = {}
            if getattr(manager, "layout", None) == "delta":
                # the active executor's dirty-tile export covers exactly
                # this chunk (= the interval since the last save), so a
                # delta snapshot skips the full-grid diff; None (dense/
                # composed impls, a poisoned chunk) falls back to the
                # writer's byte diff
                kw["dirty_tiles"] = getattr(executor, "last_dirty_tiles",
                                            None)
            manager.save(good_space, good_step,
                         extra={"initial_totals": initial}, **kw)

    return SupervisedResult(space=good_space, step=good_step,
                            report=report, events=events,
                            initial_totals=initial)
