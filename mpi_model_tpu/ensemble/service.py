"""Serving facades over the bucketed ensemble scheduler.

Two shapes:

- :class:`EnsembleService` — the synchronous submit/poll facade (PR 2):
  dispatch happens inline on the caller's thread when a bucket fills or
  the caller flushes. Simple, deterministic, still the right tool for
  scripted batch jobs and tests.
- :class:`AsyncEnsembleService` — the ALWAYS-ON loop (ISSUE 9): a
  dispatch thread pumps continuously — while batch N runs on-device,
  batch N+1 is assembled, padded and (on a runner-cache miss) compiled
  on the host thread (``EnsembleScheduler.launch_due`` /
  ``finish_flight``); results come back via non-blocking fetch, and
  consecutive windows of a dispatch carry their ``[B,H,W]`` state by
  DONATION (no inter-window copy). Robustness is the contract, not an
  afterthought:

  * bounded admission queue — ``submit`` raises
    :class:`ServiceOverloaded` (queue depth + a retry-after estimate)
    instead of accreting unbounded backlog;
  * per-ticket deadlines (``deadline_s``, injectable clock) — a ticket
    still queued past its deadline resolves as ``TicketExpired`` with a
    complete ``FailureEvent``, never a silent drop;
  * health-gated intake — while the degradation ladder is mid-fall,
    admission sheds until a dispatch completes cleanly;
  * retry budgets — solo-retry amplification under sustained faults is
    capped (``retry_budget``);
  * a supervised pump loop — an exception on the dispatch thread
    (including the injected ``thread_exc`` chaos fault) is counted
    (``loop_faults``) and the loop keeps serving.

  Every submitted ticket resolves to exactly one of: a result, a
  quarantine error, ``TicketExpired``, or (no ticket at all) an
  admission shed — the zero-silently-dropped-tickets ledger the soak
  bench audits.

``run_soak`` is the open-loop arrival driver behind
``bench.bench_service`` and the CLI's ``--serve`` mode: submissions
arrive on a fixed-rate schedule regardless of completions (the load
shape a million-user deployment actually sees), and the report carries
sustained scenarios/s, p50/p99 queue latency, device occupancy and the
shed/expired/recovered/quarantined ledger.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Callable, Optional, Sequence

from ..core.cellular_space import CellularSpace
from ..obs.flight import get_recorder
from ..resilience import inject, lockdep
from .scheduler import (DEFAULT_BUCKETS, EnsembleScheduler, TicketExpired,
                        TicketNotMigratable)
from .tiering import HibernationError, ScenarioTiering, scenario_nbytes


class ServiceOverloaded(RuntimeError):
    """Admission refused (ISSUE 9): the bounded queue is full, the
    health gate is up, or an injected ``queue_full`` fault fired.
    Carries ``queue_depth`` (pending tickets at refusal) and
    ``retry_after_s`` (a drain-time estimate from the recent per-
    scenario service time) so a client can back off instead of
    hammering a saturated service."""

    def __init__(self, message: str, *, queue_depth: int,
                 retry_after_s: float):
        super().__init__(message)
        self.queue_depth = int(queue_depth)
        self.retry_after_s = float(retry_after_s)


class EnsembleService:
    """submit/poll API over ``EnsembleScheduler``.

    ``steps`` sets the default per-submission step count (falling back
    to the template's ``time/time_step`` schedule); all other keyword
    arguments configure the scheduler (impl, substeps, buckets,
    max_wait_s, max_batch, conservation policy, clock, and the
    self-healing knobs: ``retry="solo"`` for retry-with-quarantine,
    ``dispatch_deadline_s`` for the hung-dispatch bound,
    ``ticket_deadline_s`` for per-ticket queue deadlines,
    ``retry_budget`` for the solo-retry amplification cap,
    ``degrade_after`` for the impl degradation ladder).

    ``compile_cache`` points the JAX persistent compilation cache at a
    directory before the first dispatch compiles. The DEFAULT is
    ``"auto"`` (ISSUE 9 satellite / ROADMAP direction 5): the cache is
    armed at ``utils.compile_cache.default_cache_dir()`` without being
    asked, so a restarted service re-uses every executable a previous
    process on this machine already built and reaches full throughput
    on its first batch. Pass ``None`` to disable, or a directory to
    pin one.
    """

    def __init__(self, model, *, steps: Optional[int] = None,
                 impl: str = "xla", substeps: int = 1,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_s: float = 0.0, max_batch: Optional[int] = None,
                 compute_dtype=None, check_conservation: bool = True,
                 tolerance: float = 1e-3, rtol: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 retry: str = "none",
                 dispatch_deadline_s: Optional[float] = None,
                 degrade_after: int = 2,
                 ticket_deadline_s: Optional[float] = None,
                 retry_budget: Optional[int] = None,
                 windows: int = 1, donate: bool = False,
                 compile_cache: Optional[str] = "auto",
                 service_id: Optional[str] = None,
                 mesh=None):
        self.model = model
        self.default_steps = (model.num_steps if steps is None
                              else int(steps))
        #: stable member identity (ISSUE 10 satellite) — stamped into
        #: stats/backend_reports/FailureEvents by the scheduler
        self.service_id = service_id
        self.scheduler = EnsembleScheduler(
            impl=impl, substeps=substeps, buckets=buckets,
            max_wait_s=max_wait_s, max_batch=max_batch,
            compute_dtype=compute_dtype,
            check_conservation=check_conservation, tolerance=tolerance,
            rtol=rtol, clock=clock, retry=retry,
            dispatch_deadline_s=dispatch_deadline_s,
            degrade_after=degrade_after,
            ticket_deadline_s=ticket_deadline_s,
            retry_budget=retry_budget,
            windows=windows, donate=donate,
            compile_cache=compile_cache, service_id=service_id,
            mesh=mesh)
        #: the persistent-cache dir actually armed (None = disabled or
        #: unsupported by this jax — the service still serves)
        self.compile_cache = self.scheduler.compile_cache

    def submit(self, space: CellularSpace, *, model=None,
               steps: Optional[int] = None) -> int:
        """Queue one scenario; returns its ticket. ``model`` (default:
        the template) may vary numeric flow parameters; its structure
        must match the template's."""
        m = self.model if model is None else model
        return self.scheduler.submit(
            space, m, self.default_steps if steps is None else int(steps))

    def poll(self, ticket: int):
        """(space, Report) when served, None while queued; raises the
        scenario's ``EnsembleConservationError`` on violation."""
        return self.scheduler.poll(ticket)

    def result(self, ticket: int):
        """Force THIS ticket's scenario through (flushing only its
        structure group — other clients' partial batches keep
        accumulating toward their own flush policies) and return its
        (space, Report)."""
        res = self.poll(ticket)
        if res is None:
            self.scheduler.flush_ticket(ticket)
            res = self.poll(ticket)
        if res is None:  # pragma: no cover - flush_ticket serves it
            raise RuntimeError(f"ticket {ticket} still pending after flush")
        return res

    def migrate(self, ticket: int, target: "EnsembleService") -> int:
        """Move one queued scenario to ``target`` service through the
        CRC-verified delta-stream handoff
        (``EnsembleScheduler.migrate_ticket``) and return its new
        ticket THERE — rebalancing between services (different bucket
        ladders, impls, machines-to-be) without stopping either."""
        return self.scheduler.migrate_ticket(ticket, target.scheduler)

    def flush(self) -> int:
        """Dispatch everything queued; returns the dispatch count."""
        return self.scheduler.drain()

    def stats(self) -> dict:
        """Serving counters: scenarios/s, batch occupancy, compile-cache
        hits, dispatches, queue depth (``EnsembleScheduler.stats``)."""
        return self.scheduler.stats()


class AsyncEnsembleService:
    """The always-on serving loop (module docstring): an
    ``EnsembleScheduler`` with ``inline_dispatch=False`` plus a pump
    thread driving launch/finish in a double-buffered cadence —
    iteration i LAUNCHES batch i (host assembly + compile overlap batch
    i-1's device execution) and then COMPLETES batch i-1.

    ``start=False`` skips the thread: tests drive ``pump_once()``
    deterministically on their own thread (with the injectable clock,
    so every deadline/backoff path is wall-clock-free). ``stop()``
    drains — every outstanding ticket resolves before it returns — and
    the service is a context manager (``with AsyncEnsembleService(...)
    as svc: ...`` stops on exit).

    ``donate=True`` (default; xla impl only, silently off for engines
    whose runners carry stat lanes) lets consecutive windows of each
    dispatch reuse the ``[B,H,W]`` state buffers in place."""

    def __init__(self, model, *, steps: Optional[int] = None,
                 max_queue: int = 64,
                 deadline_s: Optional[float] = None,
                 impl: str = "xla", substeps: int = 1,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_s: float = 0.0, max_batch: Optional[int] = None,
                 compute_dtype=None, check_conservation: bool = True,
                 tolerance: float = 1e-3, rtol: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 retry: str = "solo",
                 dispatch_deadline_s: Optional[float] = None,
                 degrade_after: int = 2,
                 retry_budget: Optional[int] = None,
                 windows: int = 1, donate: bool = True,
                 compile_cache: Optional[str] = "auto",
                 start: bool = True, poll_interval_s: float = 0.02,
                 service_id: Optional[str] = None,
                 residency_budget: Optional[int] = None,
                 hibernate_dir: Optional[str] = None,
                 hibernate_budget: Optional[int] = None,
                 mesh=None):
        if max_queue < 1:
            raise ValueError(f"max_queue={max_queue} must be >= 1")
        if (residency_budget is None) != (hibernate_dir is None):
            raise ValueError(
                "scenario tiering needs BOTH residency_budget and "
                "hibernate_dir (or neither)")
        self.model = model
        self.default_steps = (model.num_steps if steps is None
                              else int(steps))
        self.max_queue = int(max_queue)
        #: stable member identity (ISSUE 10 satellite): the fleet names
        #: its members ("m<slot>g<gen>"); the member chaos faults
        #: (member_kill/member_wedge) target it by this id
        self.service_id = service_id
        self.scheduler = EnsembleScheduler(
            impl=impl, substeps=substeps, buckets=buckets,
            max_wait_s=max_wait_s, max_batch=max_batch,
            compute_dtype=compute_dtype,
            check_conservation=check_conservation, tolerance=tolerance,
            rtol=rtol, clock=clock, retry=retry,
            dispatch_deadline_s=dispatch_deadline_s,
            degrade_after=degrade_after,
            ticket_deadline_s=deadline_s,
            retry_budget=retry_budget,
            windows=windows, donate=donate,
            inline_dispatch=False, compile_cache=compile_cache,
            service_id=service_id, mesh=mesh)
        self.compile_cache = self.scheduler.compile_cache
        self._clock = clock
        #: ISSUE 14 — capacity-aware paging: with a residency budget
        #: and a vault directory, admission overload HIBERNATES (the
        #: LRU queued resident, else the new arrival) instead of
        #: shedding; ServiceOverloaded fires only when the hibernation
        #: tier itself is exhausted. The pump wakes hibernated
        #: scenarios FIFO as capacity frees.
        self.tiering: Optional[ScenarioTiering] = (
            ScenarioTiering(hibernate_dir,
                            residency_budget=residency_budget,
                            hibernate_budget=hibernate_budget,
                            clock=clock, counter=self.scheduler.counter)
            if residency_budget is not None else None)
        #: hibernated-ticket bookkeeping (mutated under ``_lock_cv``):
        #: client ticket → (model, steps) while paged out; client
        #: ticket → current scheduler ticket once woken (the alias a
        #: wake creates — the client's ticket id never changes); client
        #: ticket → the terminal error resolved while hibernated
        #: (deadline expiry, an unwakeable chain)
        self._hib_meta: dict = {}
        self._woken: dict = {}
        self._hib_resolved: dict = {}
        self._poll_interval = float(poll_interval_s)
        #: condition guarding the loop state below (its lock is the
        #: "dispatch lock" of this class for the shared-mutation rule);
        #: lockdep-witnessed when the order witness is armed (ISSUE 12)
        self._lock_cv = lockdep.condition("AsyncEnsembleService._lock_cv")
        self._inflight = None
        self._stop = False
        #: abandon(): the loop must EXIT NOW, no drain — distinct from
        #: _stop, which the loop reads as "drain then exit"
        self._abandoned = False
        self._thread: Optional[threading.Thread] = None
        #: most recent supervised pump-loop failures (bounded)
        self.loop_errors: list = []
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Start the dispatch thread (idempotent)."""
        with self._lock_cv:
            if self._thread is not None:
                return
            if self._abandoned:
                raise RuntimeError(
                    "this service was abandoned (fleet fencing) — "
                    "build a fresh one instead of restarting it")
            self._stop = False
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="ensemble-dispatch")
            self._thread = t
        t.start()

    def stop(self) -> None:
        """Drain and stop: the loop keeps pumping until every pending
        ticket is resolved (served, quarantined or expired), then the
        thread exits. Without a thread (``start=False``) the drain runs
        synchronously here. Idempotent; the service may be
        ``start()``-ed again afterwards."""
        with self._lock_cv:
            t = self._thread
            self._stop = True
            self._lock_cv.notify_all()
        if t is not None:
            t.join()
            with self._lock_cv:
                self._thread = None
                self._stop = False
            return
        # manual mode: drain on the caller's thread
        while True:
            if not self.pump_once(force=True):
                with self._lock_cv:
                    idle = (self._inflight is None
                            and self.scheduler.pending_count() == 0
                            and not self._tiering_pending())
                if idle:
                    break
        with self._lock_cv:
            self._stop = False

    def abandon(self) -> None:
        """Signal the loop to EXIT NOW — no drain, no join: the fleet
        supervisor's escape hatch for a failed member (``stop`` would
        drain, and a wedged pump never drains; a drain would also keep
        dispatching work the fleet has already re-admitted elsewhere).
        The abandoned flag is checked at the top of every loop
        iteration, so the daemon thread exits at its next wakeup even
        mid-backlog; unresolved tickets are the caller's to re-admit
        (the fleet does, from its journaled/stored state). Abandonment
        is final: the service cannot be ``start()``-ed again — the
        fleet replaces the member instead."""
        with self._lock_cv:
            self._stop = True
            self._abandoned = True
            self._thread = None
            self._lock_cv.notify_all()

    def is_alive(self) -> bool:
        """True while the dispatch thread exists and is running (manual
        mode has no thread and reports False) — the fleet's dead-pump
        probe."""
        with self._lock_cv:
            t = self._thread
        return t is not None and t.is_alive()

    def has_work_due(self) -> bool:
        """True when the pump SHOULD be making progress right now: a
        launched flight is outstanding, or a queued group is due
        (full / past max-wait). The fleet's wedge detector keys on
        this — pending work that is merely waiting out the batching
        policy is not evidence of a wedge."""
        with self._lock_cv:
            if self._inflight is not None:
                return True
        return self.scheduler.due_backlog()

    def __enter__(self) -> "AsyncEnsembleService":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface ------------------------------------------------------

    def submit(self, space: CellularSpace, *, model=None,
               steps: Optional[int] = None) -> int:
        """Admit one scenario, or raise :class:`ServiceOverloaded`
        (bounded queue full / health gate up / injected ``queue_full``
        fault). Admission + enqueue are atomic under the scheduler
        lock, so the queue bound holds under concurrent submitters."""
        m = self.model if model is None else model
        n = self.default_steps if steps is None else int(steps)
        st = inject.active()
        forced = False
        if st is not None:
            f = st.take("admission", st.bump("admission"),
                        kinds=("queue_full",))
            forced = f is not None
        if self.tiering is not None:
            return self._submit_paged(space, m, n, forced)
        sched = self.scheduler
        # the scheduler's own lock makes depth-check + enqueue atomic
        # (its submit re-enters the RLock; inline_dispatch=False means
        # no device work ever runs on this caller's thread)
        with sched._lock:
            depth = sched.pending_count()
            # the gate sheds NEW load only while the degraded engine
            # still has backlog to prove itself on — an idle degraded
            # service accepts the next scenario as its health probe
            gated = sched.intake_gated and depth > 0
            if forced or gated or depth >= self.max_queue:
                sched.counter.bump("shed")
                get_recorder().record("shed", service_id=self.service_id,
                                      depth=depth)
                reason = (
                    "injected queue-full fault" if forced
                    else "intake health-gated (degradation ladder "
                         "mid-fall)" if gated
                    else f"admission queue full ({depth}/{self.max_queue})")
                raise ServiceOverloaded(
                    f"submission shed — {reason}; retry after the "
                    "estimated drain time",
                    queue_depth=depth,
                    retry_after_s=self._retry_after(depth))
            # analysis: ignore[blocking-under-lock] — this scheduler
            # runs inline_dispatch=False: submit is enqueue-only (the
            # statically-visible inline-dispatch tail is unreachable),
            # and depth-check + enqueue must be atomic under the lock
            ticket = sched.submit(space, m, n)
        with self._lock_cv:
            self._lock_cv.notify_all()
        return ticket

    def _submit_paged(self, space: CellularSpace, model, steps: int,
                      forced: bool) -> int:
        """Capacity-aware paging admission (ISSUE 14): a submission
        that fits the residency budget and the queue admits normally;
        on pressure the LRU queued resident pages out to make room, and
        when nothing is extractable the NEW arrival hibernates. The
        only refusal left is an exhausted hibernation tier."""
        sched = self.scheduler
        nbytes = scenario_nbytes(space)
        why = self.tiering.pressure(nbytes)
        # an INJECTED pressure (residency_pressure / queue_full chaos)
        # must exercise the hibernation path itself — the page-out
        # shortcut would notice the budget actually fits and admit,
        # silently skipping the seam under test
        injected = forced or why == "injected"
        ticket = None
        if not injected and why is None:
            with sched._lock:
                depth = sched.pending_count()
                gated = sched.intake_gated and depth > 0
                if not gated and depth < self.max_queue:
                    # analysis: ignore[blocking-under-lock] — same
                    # contract as the unpaged admission: this scheduler
                    # runs inline_dispatch=False (enqueue-only), and
                    # depth-check + enqueue must be atomic
                    ticket = sched.submit(space, model, steps)
        if ticket is None and not injected and self._page_out(nbytes):
            # room was made: admit (enqueue-only; a concurrent
            # submitter racing into the freed slot is a bounded
            # overshoot, not a correctness issue)
            ticket = sched.submit(space, model, steps)
        if ticket is not None:
            self.tiering.admit(ticket, nbytes)
            with self._lock_cv:
                self._lock_cv.notify_all()
            return ticket
        # no extractable victim: the new arrival hibernates — unless
        # even the hibernation tier is full, the one remaining shed
        if not self.tiering.room_for(nbytes):
            sched.counter.bump("shed")
            depth = sched.pending_count()
            raise ServiceOverloaded(
                "submission shed — hibernation tier exhausted "
                f"(hibernate_budget={self.tiering.hibernate_budget} "
                "bytes); paging absorbed the overflow until now",
                queue_depth=depth,
                retry_after_s=self._retry_after(depth))
        ticket = sched.allocate_ticket()
        with self._lock_cv:
            self._hib_meta[ticket] = (model, steps)
        try:
            self.tiering.hibernate(ticket, space, model, steps,
                                   submitted_at=self._clock())
        except (OSError, ValueError) as e:
            # the vault is unwritable: the ticket was never handed out
            # and the caller still holds its state — clean up the
            # registration and refuse the admission observably
            with self._lock_cv:
                self._hib_meta.pop(ticket, None)
            sched.counter.bump("shed")
            raise ServiceOverloaded(
                f"submission shed — hibernation write failed: {e}",
                queue_depth=sched.pending_count(),
                retry_after_s=self._retry_after(
                    sched.pending_count())) from e
        with self._lock_cv:
            self._lock_cv.notify_all()
        return ticket

    def _page_out(self, needed: int) -> bool:
        """Hibernate LRU queued residents until ``needed`` bytes fit
        the budget AND a queue slot is free; False when no victim is
        extractable (everything resident is claimed/launched — their
        dispatches are about to free the room anyway)."""
        sched = self.scheduler

        def room() -> bool:
            return (self.tiering.fits(needed)
                    and sched.pending_count() < self.max_queue)

        for t in self.tiering.lru_candidates():
            if room():
                return True
            # mark the victim hibernated-in-progress BEFORE extracting:
            # between extract (the scheduler forgets the ticket) and
            # the vault commit, a concurrent poll() of the victim must
            # see "pending" (None), never a KeyError on a live ticket
            with self._lock_cv:
                target = self._woken.pop(t, t)
                placeholder = t not in self._hib_meta
                if placeholder:
                    self._hib_meta[t] = (None, None)
            since = sched.queued_since(target)
            try:
                vspace, vmodel, vsteps = sched.extract_ticket(target)
            except (TicketNotMigratable, KeyError):
                with self._lock_cv:
                    if target != t:
                        self._woken[t] = target
                    if placeholder:
                        self._hib_meta.pop(t, None)
                continue
            with self._lock_cv:
                self._hib_meta[t] = (vmodel, vsteps)
            try:
                # the victim's deadline clock survives the page-out:
                # its ORIGINAL queued-since time is what the
                # hibernated-expiry check ages against
                self.tiering.hibernate(
                    t, vspace, vmodel, vsteps,
                    submitted_at=(self._clock() if since is None
                                  else since))
            except (OSError, ValueError) as e:
                # the vault is unwritable: the extracted state in hand
                # is the victim's ONLY copy — put it straight back in
                # the scheduler (new ticket, aliased) and stop paging;
                # losing the victim is never an acceptable outcome
                t2 = sched.submit(vspace, vmodel, vsteps)
                with self._lock_cv:
                    self._woken[t] = t2
                sched.counter.bump("loop_faults")
                warnings.warn(
                    f"page-out of ticket {t} failed ({e}); the victim "
                    "was re-queued and paging is disabled for this "
                    "admission", RuntimeWarning)
                return False
        return room()

    def _wake_due(self, draining: bool = False) -> int:
        """Wake FIFO hibernated scenarios while there is room (queue
        slot + residency budget), the service is idle (an idle service
        always wakes one — a scenario must eventually run even when the
        budget is smaller than its state), or a drain is forcing.
        Hibernated tickets past their deadline resolve as
        ``TicketExpired`` here, at the same cadence the scheduler
        expires queued ones. Returns resolutions + wakes performed."""
        from ..resilience import FailureEvent

        sched = self.scheduler
        did = 0
        while True:
            nxt = self.tiering.peek_next()
            if nxt is None:
                return did
            ticket, nbytes = nxt
            depth = sched.pending_count()
            with self._lock_cv:
                idle = self._inflight is None and depth == 0
            room = depth < self.max_queue and self.tiering.fits(nbytes)
            # the health gate applies to wakes too: while the
            # degradation ladder is mid-fall with backlog unproven,
            # paging scenarios back in would bypass exactly the gate
            # admission enforces (an idle gated service still wakes
            # one — its health probe, same as admission)
            gated = sched.intake_gated and depth > 0
            if not draining and (gated or not (room or idle)):
                return did
            entry = self.tiering.entry(ticket)
            if entry is None:  # pragma: no cover - racing drop
                continue
            ddl = sched.ticket_deadline_s
            if ddl is not None \
                    and self._clock() - entry.submitted_at > ddl:
                age = self._clock() - entry.submitted_at
                err: Exception = TicketExpired(
                    f"ticket {ticket} expired after {age:.3f}s in the "
                    f"hibernation tier (deadline {ddl}s) — never "
                    "dispatched")
                ev = FailureEvent(
                    step=entry.steps, kind="expired", detail=str(err),
                    rolled_back_to=0, attempt=1, wall_time_s=0.0,
                    classification="deterministic", ticket=ticket,
                    service_id=self.service_id)
                err.ticket = ticket
                err.failure_event = ev
                sched.expired_log.append(ev)
                sched.counter.bump("expired")
                self._resolve_hibernated(ticket, err)
                did += 1
                continue
            try:
                space, entry = self.tiering.wake(ticket)
            except HibernationError as e:
                e.ticket = ticket
                ev = FailureEvent(
                    step=entry.steps, kind="hibernation", detail=str(e),
                    rolled_back_to=0, attempt=1, wall_time_s=0.0,
                    classification="deterministic", ticket=ticket,
                    service_id=self.service_id)
                e.failure_event = ev
                sched.quarantine_log.append(ev)
                sched.counter.bump("quarantined")
                # the flight recorder dumps beside the HibernationError's
                # FailureEvent (ISSUE 15) — no lock held here
                get_recorder().dump("hibernation",
                                    service_id=self.service_id,
                                    ticket=ticket)
                self._resolve_hibernated(ticket, e)
                did += 1
                continue
            t2 = sched.submit(space, entry.model, entry.steps)
            self.tiering.admit(ticket, entry.nbytes)
            with self._lock_cv:
                self._woken[ticket] = t2
                self._lock_cv.notify_all()
            did += 1

    def _resolve_hibernated(self, ticket: int, err: Exception) -> None:
        self.tiering.drop(ticket)
        with self._lock_cv:
            self._hib_resolved[ticket] = err
            self._hib_meta.pop(ticket, None)
            self._lock_cv.notify_all()

    def _resolve_tiering(self, ticket: int) -> None:
        """A tiered ticket reached its terminal outcome through the
        scheduler: free its residency, reclaim its chain, drop the
        wake alias."""
        self.tiering.release(ticket)
        with self._lock_cv:
            self._woken.pop(ticket, None)
            self._hib_meta.pop(ticket, None)

    def _tiering_pending(self) -> bool:
        return (self.tiering is not None
                and self.tiering.hibernated_count() > 0)

    def _retry_after(self, depth: int) -> float:
        """Drain-time estimate: queue depth x the recent per-scenario
        busy time, floored at the pump interval. O(1) on purpose — this
        runs per SHED submission while the caller holds the scheduler
        lock, exactly when the pump thread is contending for it, so it
        must not pay ``snapshot()``'s latency-reservoir sort."""
        per = self.scheduler.counter.busy_per_scenario()
        if per is None:
            return max(self._poll_interval, self.scheduler.max_wait_s)
        return max(depth * per, self._poll_interval)

    def poll(self, ticket: int):
        """(space, Report) when served, None while in flight (or
        hibernated — a paged-out ticket polls None exactly like a
        queued one); raises the ticket's quarantine/expiry error.
        Never dispatches on the caller's thread — the loop owns the
        device."""
        if self.tiering is None:
            return self.scheduler.poll(ticket, pump=False)
        with self._lock_cv:
            if ticket in self._hib_resolved:
                raise self._hib_resolved.pop(ticket)
            mapped = self._woken.get(ticket, ticket)
            hibernated = (ticket in self._hib_meta
                          and ticket not in self._woken)
        if hibernated:
            return None
        try:
            res = self.scheduler.poll(mapped, pump=False)
        except Exception as e:
            if mapped != ticket:
                # the client holds ITS ticket id, not the wake alias:
                # a quarantine/expiry raised under the alias must
                # correlate with the ticket the client submitted
                e.ticket = ticket
            self._resolve_tiering(ticket)
            raise
        if res is None:
            self.tiering.touch(ticket)
            return None
        self._resolve_tiering(ticket)
        return res

    def result(self, ticket: int, timeout: Optional[float] = None):
        """Block until ``ticket`` resolves (the loop serves it);
        ``TimeoutError`` after ``timeout`` seconds. In manual mode
        (``start=False``) this pumps synchronously instead."""
        # analysis: ignore[naked-timer] — result()'s timeout= is a
        # CLIENT-facing wall bound, not a measurement (see the fleet
        # twin); nothing is recorded
        deadline = (
            # analysis: ignore[naked-timer] — client wall bound (see
            # the pragma block above), not a measurement
            None if timeout is None
            # analysis: ignore[naked-timer] — same bound
            else time.monotonic() + float(timeout))
        while True:
            res = self.poll(ticket)
            if res is not None:
                return res
            with self._lock_cv:
                threaded = self._thread is not None
            if not threaded:
                did = self.pump_once(force=True)
                if not did:
                    # a ticket resolved by expiry inside the claim does
                    # not count as pump work — re-poll (raises
                    # TicketExpired / returns) before declaring the
                    # queue inconsistent
                    res = self.poll(ticket)
                    if res is not None:  # pragma: no cover - defensive
                        return res
                    raise RuntimeError(  # pragma: no cover - defensive
                        f"ticket {ticket} pending but the pump found no "
                        "work — queue state is inconsistent")
                continue
            with self._lock_cv:
                # analysis: ignore[naked-timer] — the same client
                # wall bound's expiry check
                if (deadline is not None
                        # analysis: ignore[naked-timer] — same bound
                        and time.monotonic() >= deadline):
                    raise TimeoutError(
                        f"ticket {ticket} still pending after "
                        f"{timeout}s")
                self._lock_cv.wait(self._poll_interval)

    def stats(self) -> dict:
        out = self.scheduler.stats()
        with self._lock_cv:
            out.update({
                "max_queue": self.max_queue,
                "async": True,
                "running": self._thread is not None,
                "loop_errors": len(self.loop_errors),
            })
        if self.tiering is not None:
            out.update(self.tiering.stats())
        return out

    # -- the pump ------------------------------------------------------------

    def pump_once(self, force: bool = False) -> bool:
        """ONE double-buffered loop iteration, on the calling thread:
        LAUNCH the next due batch (expiring overdue tickets first —
        the claim path does it — then host assembly/compile, which
        overlaps the previously launched batch's device execution),
        then COMPLETE the previous batch (non-blocking fetch + result
        fan-out). Returns whether any work was done. The ``thread_exc``
        chaos seam fires at the top — before any state moves — so an
        injected dispatch-thread death never strands a launched batch;
        and a failure escaping the completion itself resolves the
        flight's tickets (``fail_flight``) before re-raising, so even
        an unwind cannot drop a ticket silently."""
        st = inject.active()
        if st is not None:
            # the member faults fire BEFORE the pump counter moves, so
            # a wedged member's thread_exc indices stay deterministic
            if st.member_fault(self.service_id,
                               ("member_wedge",)) is not None:
                return False  # a live thread making zero progress
            if st.member_fault(self.service_id,
                               ("member_kill",)) is not None:
                raise inject.MemberKilled(
                    f"injected member kill ({self.service_id})")
            f = st.take("pump", st.bump("pump"), kinds=("thread_exc",))
            if f is not None:
                raise inject.InjectedFault(
                    "injected dispatch-thread exception")
        woke = 0
        if self.tiering is not None:
            # wake hibernated scenarios into the freed capacity BEFORE
            # claiming the next batch, so a wake rides this very pump.
            # Only a STOP drain overrides the residency budget — a
            # manual-mode result() also pumps with force=True, and it
            # must page scenarios in one at a time, not flood the
            # whole tier back into memory
            with self._lock_cv:
                stopping = self._stop
            woke = self._wake_due(draining=force and stopping)
        flight = self.scheduler.launch_due(force=force)
        with self._lock_cv:
            prev, self._inflight = self._inflight, flight
        if prev is not None:
            try:
                self.scheduler.finish_flight(prev)
            except BaseException as e:
                # resolve the flight's tickets before unwinding — the
                # loop supervisor counts the fault; no ticket strands
                self.scheduler.fail_flight(prev, e)
                raise
            finally:
                with self._lock_cv:
                    self._lock_cv.notify_all()
        return flight is not None or prev is not None or woke > 0

    def _loop(self) -> None:
        while True:
            try:
                with self._lock_cv:
                    if self._abandoned:
                        return  # exit NOW: no drain (see abandon())
                    draining = self._stop
                did = self.pump_once(force=draining)
            except inject.MemberKilled:
                # the member_kill chaos fault: this thread DIES — no
                # drain, no supervision, exactly like a real thread
                # death (the fleet's health check is what must notice);
                # returning (vs propagating) only spares the noisy
                # default excepthook traceback
                return
            # analysis: ignore[broad-except] — the pump-loop supervisor:
            # a dispatch-thread exception (chaos thread_exc included)
            # must be counted and survived — a dead loop is a dead
            # service; per-dispatch failures already fan out upstream
            except Exception as e:
                self.scheduler.counter.bump("loop_faults")
                with self._lock_cv:
                    self.loop_errors.append(
                        f"{type(e).__name__}: {e}")
                    del self.loop_errors[:-32]
                did = True
            with self._lock_cv:
                if (self._stop and self._inflight is None
                        and self.scheduler.pending_count() == 0
                        and not self._tiering_pending()):
                    return
                if not did and not self._stop:
                    self._lock_cv.wait(self._poll_interval)


def run_soak(service, scenarios, *, arrival_rate_hz: float,
             clock: Callable[[], float] = time.monotonic,
             sleep: Callable[[float], None] = time.sleep,
             snapshot_path: Optional[str] = None,
             snapshot_interval_s: float = 5.0,
             status_port: Optional[int] = None) -> dict:
    """Open-loop soak: submit ``scenarios`` (``(space, model, steps)``
    triples; model/steps may be None for the service defaults) at a
    fixed arrival rate — arrivals do NOT wait for completions, so a
    service slower than the offered load builds real backlog and must
    shed — then collect every issued ticket and account for all of
    them. Returns the serving report: sustained scenarios/s (served /
    soak wall), p50/p99 queue latency, device occupancy (dispatch busy
    seconds / soak wall) and the complete ledger (served + failed +
    expired + shed == offered — the zero-silently-dropped-tickets
    audit; ``ledger_complete`` says so).

    ``clock``/``sleep`` are injectable so tests drive the arrival
    process without wall-clock sleeps; the bench uses real time.

    ``snapshot_path`` (ISSUE 15): dump the unified telemetry-plane
    snapshot (``obs.write_snapshot`` — atomic tmp+rename) there every
    ``snapshot_interval_s`` of injectable-clock time during the soak,
    and once at the end — bench rows, chaos tests and a human watching
    the file all consume the SAME plane.

    ``status_port`` (ISSUE 20): also stand up the LIVE scrape endpoint
    (``obs.serve_status`` — ``GET /metrics`` Prometheus, ``GET /`` the
    snapshot JSON, computed fresh per request) for the soak's
    duration; torn down before the report returns. Port 0 binds an
    ephemeral port. Independent of ``snapshot_path`` — the endpoint
    scrapes the live service, not the dumped file."""
    if arrival_rate_hz <= 0:
        raise ValueError(
            f"arrival_rate_hz={arrival_rate_hz} must be positive")
    if status_port is not None:
        from .. import obs

        server = obs.serve_status(
            status_port, lambda: obs.fleet_snapshot(service))
        try:
            return run_soak(service, scenarios,
                            arrival_rate_hz=arrival_rate_hz,
                            clock=clock, sleep=sleep,
                            snapshot_path=snapshot_path,
                            snapshot_interval_s=snapshot_interval_s)
        finally:
            server.shutdown()
            server.server_close()

    def dump_snapshot() -> None:
        if snapshot_path is None:
            return
        from .. import obs

        try:
            obs.write_snapshot(snapshot_path, service)
        except OSError as e:  # observability must not fail the soak
            warnings.warn(f"telemetry snapshot write failed: {e}",
                          RuntimeWarning)

    scenarios = list(scenarios)
    t0 = clock()
    next_snap = t0 + float(snapshot_interval_s)

    def maybe_dump(now: Optional[float] = None) -> None:
        """The ONE interval-cadence owner: due-check + dump +
        next_snap reset (four call sites — rate wait, post-wait,
        drain, result slice — must never drift apart)."""
        nonlocal next_snap
        if snapshot_path is None:
            return
        if (clock() if now is None else now) < next_snap:
            return
        dump_snapshot()
        next_snap = clock() + float(snapshot_interval_s)

    tickets: list = []
    shed = 0
    for i, (space, model, steps) in enumerate(scenarios):
        due = t0 + i / arrival_rate_hz
        while True:
            now = clock()
            if now >= due:
                break
            # the rate-wait is where a SLOW arrival process parks
            # (20 s between tickets at 0.05 Hz): the interval dump
            # must keep firing inside it or the --status file goes
            # stale for the whole inter-arrival gap
            maybe_dump(now)
            sleep(min(due - now, 0.01))
        maybe_dump()
        try:
            tickets.append(service.submit(space, model=model, steps=steps))
        except ServiceOverloaded:
            shed += 1
            tickets.append(None)
    served = failed = expired = 0
    for t in tickets:
        # the drain phase is where a long soak spends its wall time
        # (the default CLI invocation arrives at open throttle, so the
        # arrival loop is over in microseconds): the interval dump must
        # keep firing HERE or an operator watching the file sees
        # nothing until the soak ends
        maybe_dump()
        if t is None:
            continue
        try:
            if snapshot_path is None:
                service.result(t)
            else:
                # one long-blocking result() must not freeze the
                # --status file: wait in interval-sized slices and
                # keep the cadence between them (the async service
                # and the fleet both take result(timeout=))
                while True:
                    try:
                        service.result(
                            t, timeout=float(snapshot_interval_s))
                        break
                    except TimeoutError:
                        maybe_dump()
            served += 1
        except TicketExpired:
            expired += 1
        # analysis: ignore[broad-except] — the soak LEDGER: every
        # non-served ticket must be counted (quarantine, conservation,
        # dispatch error), not crash the audit — per-ticket honesty
        except Exception:
            failed += 1
    wall = clock() - t0
    dump_snapshot()  # the final cut: the plane at soak end
    st = service.stats()
    offered = len(scenarios)
    fleet_fields = (
        # fleet mode (ISSUE 10): per-member attribution + the
        # supervision ledger ride along so the soak report reconciles
        # ACROSS members, not just in aggregate
        {k: st[k] for k in ("services", "member_faults", "readmitted",
                            "scale_ups", "scale_downs")}
        if "services" in st else {})
    return {
        **fleet_fields,
        "telemetry_snapshot": snapshot_path,
        "offered": offered,
        "arrival_rate_hz": arrival_rate_hz,
        "served": served,
        "failed": failed,
        "expired": expired,
        "shed": shed,
        "ledger_complete": served + failed + expired + shed == offered,
        "wall_s": wall,
        "sustained_scenarios_per_s": served / wall if wall > 0 else None,
        # in-flight fraction: how much of the soak wall a dispatch was
        # OUTSTANDING (inflight_s spans launch→fetched, including the
        # async overlap gap; synchronously it equals busy_s)
        "occupancy": st["inflight_s"] / wall if wall > 0 else None,
        "latency_p50_s": st["latency_p50_s"],
        "latency_p99_s": st["latency_p99_s"],
        "batch_occupancy": st["batch_occupancy"],
        "compile_cache_hit_rate": st["compile_cache_hit_rate"],
        "dispatches": st["dispatches"],
        "solo_retries": st["solo_retries"],
        "recovered_failures": st["recovered_failures"],
        "quarantined": st["quarantined"],
        "expired_total": st["expired"],
        "shed_total": st["shed"],
        "loop_faults": st["loop_faults"],
        "degraded_from": st["degraded_from"],
    }
