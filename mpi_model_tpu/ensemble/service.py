"""Submit/poll serving facade over the bucketed ensemble scheduler.

The shape a traffic-serving deployment programs against: a service is
constructed around a TEMPLATE model (the structure every submission must
share — see ``batch.structure_key``); clients ``submit`` scenarios (a
space, optionally a parameter-varied model and step count) and
``poll``/``result`` their per-scenario ``Report``s back. Throughput
accounting (scenarios/s, batch occupancy, compile-cache hits) runs
through ``utils.metrics.ThroughputCounter`` and is surfaced by
``stats()`` — the fields the CLI's ``--ensemble`` run and
``bench.bench_ensemble`` publish.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from ..core.cellular_space import CellularSpace
from .scheduler import DEFAULT_BUCKETS, EnsembleScheduler


class EnsembleService:
    """submit/poll API over ``EnsembleScheduler``.

    ``steps`` sets the default per-submission step count (falling back
    to the template's ``time/time_step`` schedule); all other keyword
    arguments configure the scheduler (impl, substeps, buckets,
    max_wait_s, max_batch, conservation policy, clock, and the
    self-healing knobs: ``retry="solo"`` for retry-with-quarantine,
    ``dispatch_deadline_s`` for the hung-dispatch bound,
    ``degrade_after`` for the impl degradation ladder).

    ``compile_cache`` (a directory path) points the JAX persistent
    compilation cache there before the first dispatch compiles
    (``utils.configure_compile_cache``): a restarted service re-uses
    every executable a previous process on this machine already built —
    the per-machine cold-start eliminator of ROADMAP direction 5,
    surfaced as the CLI's ``--compile-cache`` flag.
    """

    def __init__(self, model, *, steps: Optional[int] = None,
                 impl: str = "xla", substeps: int = 1,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_s: float = 0.0, max_batch: Optional[int] = None,
                 compute_dtype=None, check_conservation: bool = True,
                 tolerance: float = 1e-3, rtol: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 retry: str = "none",
                 dispatch_deadline_s: Optional[float] = None,
                 degrade_after: int = 2,
                 compile_cache: Optional[str] = None):
        from ..utils.compile_cache import configure_compile_cache

        #: the persistent-cache dir actually armed (None = disabled or
        #: unsupported by this jax — the service still serves)
        self.compile_cache = configure_compile_cache(compile_cache)
        self.model = model
        self.default_steps = (model.num_steps if steps is None
                              else int(steps))
        self.scheduler = EnsembleScheduler(
            impl=impl, substeps=substeps, buckets=buckets,
            max_wait_s=max_wait_s, max_batch=max_batch,
            compute_dtype=compute_dtype,
            check_conservation=check_conservation, tolerance=tolerance,
            rtol=rtol, clock=clock, retry=retry,
            dispatch_deadline_s=dispatch_deadline_s,
            degrade_after=degrade_after)

    def submit(self, space: CellularSpace, *, model=None,
               steps: Optional[int] = None) -> int:
        """Queue one scenario; returns its ticket. ``model`` (default:
        the template) may vary numeric flow parameters; its structure
        must match the template's."""
        m = self.model if model is None else model
        return self.scheduler.submit(
            space, m, self.default_steps if steps is None else int(steps))

    def poll(self, ticket: int):
        """(space, Report) when served, None while queued; raises the
        scenario's ``EnsembleConservationError`` on violation."""
        return self.scheduler.poll(ticket)

    def result(self, ticket: int):
        """Force THIS ticket's scenario through (flushing only its
        structure group — other clients' partial batches keep
        accumulating toward their own flush policies) and return its
        (space, Report)."""
        res = self.poll(ticket)
        if res is None:
            self.scheduler.flush_ticket(ticket)
            res = self.poll(ticket)
        if res is None:  # pragma: no cover - flush_ticket serves it
            raise RuntimeError(f"ticket {ticket} still pending after flush")
        return res

    def migrate(self, ticket: int, target: "EnsembleService") -> int:
        """Move one queued scenario to ``target`` service through the
        CRC-verified delta-stream handoff
        (``EnsembleScheduler.migrate_ticket``) and return its new
        ticket THERE — rebalancing between services (different bucket
        ladders, impls, machines-to-be) without stopping either."""
        return self.scheduler.migrate_ticket(ticket, target.scheduler)

    def flush(self) -> int:
        """Dispatch everything queued; returns the dispatch count."""
        return self.scheduler.drain()

    def stats(self) -> dict:
        """Serving counters: scenarios/s, batch occupancy, compile-cache
        hits, dispatches, queue depth (``EnsembleScheduler.stats``)."""
        return self.scheduler.stats()
