"""Fleet supervisor: one arrival stream sharded across N always-on
services (ISSUE 10 tentpole; ROADMAP direction 1's last gap).

PR 9 removed the single synchronous dispatcher but kept a one-process
version of the paper's master-rank weakness: `AsyncEnsembleService` is a
single pump thread whose death (or wedge) takes the whole arrival
stream with it. The :class:`FleetSupervisor` closes that gap with three
robustness layers over N member services:

**Routing** — structure-affine with a least-queue-depth tiebreak: a
scenario's ``structure_key`` (+ step count) hashes to a preferred
member, so scenarios that batch together keep landing on the same
member and its bucketed runner caches stay hot; when the preferred
member sheds (full queue, health gate, injected ``queue_full``), the
remaining members are tried in ascending queue depth. Only when EVERY
member refuses does the fleet shed — a single member's overload or
chaos fault reroutes instead of failing the client.

**Autoscaling** — a policy over the signals PR 9 already exports (shed
rate, p99 queue latency, queue-depth occupancy, ``intake_gated``),
evaluated once per supervision tick on the injectable clock, scaling
the member count within ``[min_services, max_services]``. Hysteresis
both ways (``scale_up_after``/``scale_down_after`` consecutive votes,
plus a post-action cooldown) keeps a noisy signal from flapping the
fleet. Scale-down is DRAIN-BEFORE-RETIRE: the retiring member stops
taking intake, its queued tickets move to healthy members through
``migrate_ticket`` (the CRC-verified delta-stream handoff), and the
member is only removed once every ticket it held is migrated or
resolved — zero ticket loss, asserted.

**Failure-domain isolation** — each supervision tick health-checks
every member: a pump thread that died (``member_kill`` chaos, or a real
thread death), a member making zero progress past
``supervision_deadline_s`` while holding work (``member_wedge``), or a
member that fell to the bottom of the degradation ladder is FENCED (no
new intake), its queued tickets are migrated to healthy members, its
claimed/launched tickets are re-admitted from the fleet's own copy of
their state (the one case ``migrate_ticket`` must refuse — see
``TicketNotMigratable``), and a fresh member is started in the same
slot under a new generation id. Every fencing lands a
``FailureEvent(kind="member")`` in ``member_log`` — the same event
stream quarantines and expiries use, attributable by ``service_id``.

**Crash-restart ticket recovery** — with ``journal_dir`` set, every
ticket's lifecycle is journaled at the scheduler seams (see
``ensemble.journal``): admission (with full scenario state), harvest
(served state), quarantine/expiry, migration. After a hard process
kill, ``FleetSupervisor.recover(journal_dir, model)`` replays the
CRC-verified journal prefix: terminal tickets resolve from the journal
(a served-but-unacknowledged ticket is NOT re-run), unresolved tickets
are re-admitted with their original ids, and the soak ledger still
audits complete — PR 9's "zero silent drops" contract extended across
process death.

The fleet duck-types the service surface (``submit``/``poll``/
``result``/``stats``/``stop``/context manager), so ``run_soak`` and the
bench drive it unchanged. ``start=False`` builds members in manual mode
and lets tests drive ``pump_once()`` deterministically on the
injectable clock — zero wall sleeps.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import json
import os
import threading
import time
import warnings
from typing import Callable, Optional

from ..core.cellular_space import CellularSpace
from ..obs.flight import get_recorder
from ..resilience import inject, lockdep
from ..utils.metrics import ThroughputCounter
from ..utils.tracing import TraceContext, get_tracer
from .batch import structure_key
from .journal import (StaleEpochError, TicketJournal, declare_epoch,
                      journal_path, model_from_meta, model_meta, replay,
                      space_from_record, space_payload)
from .lifecycle import (EXPIRED, MIGRATE, QUARANTINED, READMIT, SERVED,
                        SHED, SUBMIT, WAKE)
from .member_proc import resolve_deadlines, spawn_process_member
from .scheduler import TicketExpired, TicketNotMigratable
from .service import AsyncEnsembleService, ServiceOverloaded
from .tiering import HibernationError, ScenarioTiering, scenario_nbytes
from .wire import WireError

__all__ = ["AutoscalePolicy", "FleetSupervisor", "MemberFailure",
           "StandbySupervisor", "lease_path", "read_lease"]

#: the supervisor lease file inside a journal directory (ISSUE 20):
#: JSON ``{"owner", "epoch", "t", "lease_s"}`` rewritten atomically on
#: every supervision tick by the ACTIVE supervisor. A standby that
#: observes the stamp going stale past ``lease_s`` (on the SHARED
#: injectable clock — ``time.monotonic`` is host-wide on Linux, so
#: same-host processes compare directly) takes the fleet over.
LEASE_NAME = "supervisor.lease"


def lease_path(journal_dir: str) -> str:
    return os.path.join(journal_dir, LEASE_NAME)


def read_lease(path: str) -> Optional[dict]:
    """The lease record, or None when the file is missing or garbled
    (a torn lease write is a missed renewal, never a crash)."""
    try:
        with open(path) as fh:
            rec = json.load(fh)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """When to grow/shrink the member count (evaluated per supervision
    tick). A tick votes UP when any pressure signal fires — a shed
    since the last tick, aggregate queue depth above ``depth_high`` of
    fleet capacity, p99 queue latency above ``latency_p99_target_s``,
    or a health-gated member holding backlog — and DOWN when depth sits
    below ``depth_low`` with no pressure at all. Votes must persist for
    ``scale_up_after``/``scale_down_after`` CONSECUTIVE ticks before an
    action, and ``cooldown_ticks`` must pass after one — the hysteresis
    that keeps a noisy signal from flapping the fleet."""

    min_services: int = 1
    max_services: int = 4
    depth_high: float = 0.75
    depth_low: float = 0.10
    latency_p99_target_s: Optional[float] = None
    scale_up_after: int = 2
    scale_down_after: int = 4
    cooldown_ticks: int = 2

    def __post_init__(self):
        if not 1 <= self.min_services <= self.max_services:
            raise ValueError(
                f"need 1 <= min_services ({self.min_services}) <= "
                f"max_services ({self.max_services})")


@dataclasses.dataclass
class _Member:
    """One fleet slot's current occupant."""

    service: AsyncEnsembleService
    slot: int
    gen: int
    fenced: bool = False
    retiring: bool = False
    #: why this member is draining out: "scale" (autoscale retirement,
    #: counted as a scale_down on removal) or "fence" (a LIVE fencing —
    #: ladder bottom: the pump still works, so in-flight batches finish
    #: here instead of being re-admitted and double-dispatched)
    retire_kind: str = "scale"
    #: manual-mode pump raised MemberKilled (threaded death is probed
    #: via the thread itself)
    dead: bool = False
    #: wedge detection: last observed progress signature + when it
    #: last changed (fleet clock)
    progress_sig: tuple = ()
    progress_t: float = 0.0

    @property
    def service_id(self) -> str:
        return self.service.service_id


@dataclasses.dataclass
class _Route:
    """One outstanding fleet ticket: where it lives now, plus the
    fleet's own copy of the scenario — the re-admission source when a
    member dies with the ticket claimed/launched (the state
    ``migrate_ticket`` can no longer reach)."""

    member: Optional[_Member]
    member_ticket: int
    space: CellularSpace
    model: object
    steps: int
    submitted_at: float
    #: the fleet submit span's TraceContext (ISSUE 15) — re-admissions
    #: and wakes re-attach it, so a ticket's whole flight (including
    #: across a fence) stays one trace; also journaled on the submit
    #: record so obs.timeline can join spans offline
    trace: Optional[object] = None


class MemberFailure(RuntimeError):
    """A fleet member was fenced (dead pump / wedge / ladder bottom);
    carries the member's ``service_id`` for attribution."""

    def __init__(self, message: str, service_id: str):
        super().__init__(message)
        self.service_id = service_id


class FleetSupervisor:
    """N ``AsyncEnsembleService`` members behind one service surface
    (module docstring). Keyword arguments not listed here are forwarded
    to every member (``steps``, ``impl``, ``max_queue``, ``deadline_s``,
    ``retry``, ``windows`` …); ``clock`` is shared by the fleet's
    supervision timers and every member, so fake-clock tests drive the
    whole stack. ``start=True`` starts member pump threads plus one
    fleet supervision thread; ``start=False`` is manual mode
    (``pump_once()`` pumps every member once, then runs a supervision
    ``tick``)."""

    def __init__(self, model, *, services: int = 2,
                 policy: Optional[AutoscalePolicy] = None,
                 journal_dir: Optional[str] = None,
                 journal_results: bool = True,
                 supervision_deadline_s: float = 5.0,
                 tick_interval_s: float = 0.05,
                 fence_on_ladder_bottom: bool = True,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True,
                 poll_interval_s: float = 0.02,
                 member_transport: str = "inproc",
                 member_spawner: Optional[Callable] = None,
                 member_host: str = "127.0.0.1",
                 heartbeat_deadline_s: Optional[float] = None,
                 rpc_deadline_s: Optional[float] = None,
                 supervisor_id: Optional[str] = None,
                 lease_s: float = 2.0,
                 takeover_from: Optional[str] = None,
                 member_env: Optional[dict] = None,
                 residency_budget: Optional[int] = None,
                 hibernate_dir: Optional[str] = None,
                 hibernate_budget: Optional[int] = None,
                 **member_kwargs):
        if services < 1:
            raise ValueError(f"services={services} must be >= 1")
        if policy is not None and services > policy.max_services:
            raise ValueError(
                f"services={services} exceeds the policy's max_services="
                f"{policy.max_services}")
        if member_transport not in ("inproc", "process", "tcp"):
            raise ValueError(
                f"unknown member_transport {member_transport!r} "
                "(expected 'inproc', 'process' or 'tcp')")
        #: ISSUE 13: "inproc" (the default — in-process
        #: AsyncEnsembleService members, behaviorally identical to
        #: PR 10) or "process" — members behind the ensemble.wire
        #: protocol, spawned by ``member_spawner`` (default: real OS
        #: processes via member_proc.spawn_process_member; tests pass
        #: spawn_loopback_member for the zero-subprocess fake). Health
        #: rides heartbeats (missed past ``heartbeat_deadline_s`` on
        #: the injectable clock → fence + respawn gen+1); every RPC is
        #: bounded by ``rpc_deadline_s`` and a wire failure is a
        #: MEMBER fault, never a ticket outcome. ``member_env`` is the
        #: device-pinning env contract laid over each spawned child: a
        #: dict pins every member identically; a SEQUENCE of dicts pins
        #: per slot (``member_env[slot % len]`` — how N members split
        #: one host's chips, e.g. disjoint ``CUDA_VISIBLE_DEVICES``,
        #: the ISSUE 16 N-single-chip-members layout); a callable gets
        #: the slot and returns the env.
        self._transport = member_transport
        #: ISSUE 20 — deadlines default per transport: TCP members ride
        #: real network jitter (handshake RTT, kernel backlog), so their
        #: heartbeats/RPCs get the retuned wire.TCP_* bounds; unix/local
        #: keep the tight PR-13 values. An explicit float always wins.
        self._heartbeat_deadline, self._rpc_deadline = resolve_deadlines(
            "tcp" if member_transport == "tcp" else "unix",
            heartbeat_deadline_s, rpc_deadline_s)
        if (member_env is not None and not isinstance(member_env, dict)
                and not callable(member_env)):
            member_env = [dict(e) if e else {} for e in member_env]
            if not member_env:
                raise ValueError(
                    "member_env sequence must not be empty (pass None "
                    "for no pinning)")
        self._member_env = member_env
        self._spawner = member_spawner
        if member_transport in ("process", "tcp"):
            if self._spawner is None:
                #: ISSUE 20 — "tcp" is "process" over an authenticated
                #: TCP socket: spawn_process_member mints a per-member
                #: shared secret (child env only), listens on an
                #: ephemeral ``member_host`` port, and both sides run
                #: the wire.py HMAC challenge–response before the first
                #: frame. Cross-HOST members are spawned by an external
                #: launcher and handed in via ``member_spawner``.
                self._spawner = (
                    functools.partial(spawn_process_member,
                                      transport="tcp", host=member_host)
                    if member_transport == "tcp"
                    else spawn_process_member)
            if model_meta(model) is None:
                raise ValueError(
                    f"member_transport={member_transport!r} needs a "
                    "template model model_meta() can serialize "
                    "(scalar-field flows); this model has no wire "
                    "recipe")
        self.model = model
        self.default_steps = (int(member_kwargs["steps"])
                              if member_kwargs.get("steps") is not None
                              else model.num_steps)
        self._policy = policy
        self._member_kwargs = dict(member_kwargs)
        self._member_kwargs["clock"] = clock
        self._member_kwargs.setdefault("max_queue", 64)
        self._member_kwargs.setdefault("poll_interval_s", poll_interval_s)
        self._max_queue = int(self._member_kwargs["max_queue"])
        self._supervision_deadline = float(supervision_deadline_s)
        self._tick_interval = float(tick_interval_s)
        self._fence_on_ladder_bottom = bool(fence_on_ladder_bottom)
        self._clock = clock
        self._threaded = bool(start)
        self._poll_interval = float(poll_interval_s)
        #: THE fleet lock (a Condition: result() waiters park on it) —
        #: every supervisor-state mutation below holds it; member device
        #: work never runs under it (members pump themselves);
        #: lockdep-witnessed when the order witness is armed (ISSUE 12)
        self._cv = lockdep.condition("FleetSupervisor._cv")
        self._members: dict[int, _Member] = {}
        self._route: dict[int, _Route] = {}
        self._resolved: dict[int, object] = {}
        self._ids = itertools.count()
        self._slot_ids = itertools.count()
        #: FailureEvent(kind="member") per fencing, in order — the
        #: member-level arm of the fleet's failure-event stream
        self.member_log: list = []
        #: fleet-level counters: shed (fleet-wide refusals only —
        #: member-level sheds that rerouted are not client outcomes),
        #: fleet-observed queue latency, member_faults/readmitted/
        #: scale_ups/scale_downs
        self.counter = ThroughputCounter()
        self.journal: Optional[TicketJournal] = None
        self._journal_results = bool(journal_results)
        #: ISSUE 20 — supervisor identity + failover state. A NAMED
        #: supervisor (``supervisor_id``) is one competing for the
        #: fleet: it declares a fresh journal epoch at startup (fencing
        #: every older handle) and renews ``supervisor.lease`` each
        #: tick so a StandbySupervisor can detect its death. Anonymous
        #: supervisors (the default) keep the PR-10 single-owner
        #: journal semantics: no epoch stamps, no lease.
        self._supervisor_id = supervisor_id
        self._lease_s = float(lease_s)
        self._lease_path: Optional[str] = None
        if supervisor_id is not None and journal_dir is None:
            raise ValueError(
                "supervisor_id needs journal_dir: failover is fenced "
                "through the journal's epoch file and lease")
        if journal_dir is not None:
            if supervisor_id is not None:
                self.journal = TicketJournal(journal_path(journal_dir),
                                             epoch=0)
                declare_epoch(self.journal, supervisor=supervisor_id,
                              takeover_from=takeover_from,
                              lease_s=self._lease_s)
                self._lease_path = lease_path(journal_dir)
                self._renew_lease()
            else:
                self.journal = TicketJournal(journal_path(journal_dir))
        #: ISSUE 14 — fleet-level scenario tiering: when every member
        #: refuses (or the fleet residency budget is exhausted) a
        #: submission HIBERNATES to the vault instead of shedding;
        #: tick() wakes hibernated scenarios FIFO onto the
        #: structure-affine member as capacity frees. ServiceOverloaded
        #: fires only when the hibernation tier itself is exhausted.
        if (residency_budget is None) != (hibernate_dir is None):
            raise ValueError(
                "scenario tiering needs BOTH residency_budget and "
                "hibernate_dir (or neither)")
        self.tiering: Optional[ScenarioTiering] = (
            ScenarioTiering(hibernate_dir,
                            residency_budget=residency_budget,
                            hibernate_budget=hibernate_budget,
                            clock=clock, counter=self.counter)
            if residency_budget is not None else None)
        #: hibernated fleet tickets (under ``_cv``): ticket →
        #: (model, steps, skey, submitted_at) — the state itself lives
        #: ONLY in the vault chain (+ the fleet journal's submit
        #: record): paging a scenario out genuinely frees its memory
        self._hib_meta: dict = {}
        #: wake placements per member id — the per-member attribution
        #: of the paging tier (m<slot>g<gen> keys)
        self._wakes_by_member: dict = {}
        #: (stat signature, JournalState) — the journal-fallback wake
        #: path's replay cache (see _journal_state_fallback)
        self._journal_fallback_cache: Optional[tuple] = None
        #: counters of members that were fenced or retired — folded
        #: into stats() so fleet-level metrics never undercount the
        #: work a dead member did before dying
        self._absorbed: dict = {}
        # autoscale hysteresis state
        self._up_ticks = 0
        self._down_ticks = 0
        self._cooldown = 0
        self._last_shed = 0
        self._stop_flag = False
        self._stopped = False
        #: spawn requests a previous tick failed to fulfill (a raising
        #: spawner) — retried at the next tick, so a transient spawn
        #: failure can never permanently shrink the fleet below its
        #: configured capacity
        self._pending_spawns: list = []
        #: fenced members whose DRAIN was deferred because their
        #: replacement spawn failed and no live member remained — the
        #: drain completes once the retried spawn installs, so the
        #: fenced member's tickets re-admit instead of resolving as
        #: MemberFailure for want of a one-tick-late candidate
        self._pending_fences: list = []
        #: a simulated process kill: tick() becomes a no-op, so nothing
        #: is harvested (or journaled) after the "crash"
        self._abandoned = False
        self._thread: Optional[threading.Thread] = None
        with self._cv:
            for _ in range(services):
                self._spawn_locked(next(self._slot_ids), 0)
        if start:
            t = threading.Thread(target=self._supervise_loop, daemon=True,
                                 name="fleet-supervisor")
            with self._cv:
                self._thread = t
            t.start()

    # -- lifecycle -----------------------------------------------------------

    def _member_env_for(self, slot: int) -> Optional[dict]:
        """Resolve the device-pinning env for one member slot: uniform
        dict, per-slot sequence (``slot % len`` — a respawned gen+1
        inherits its slot's pin, so fencing never migrates a member
        onto another member's chips), or slot → env callable."""
        me = self._member_env
        if me is None or isinstance(me, dict):
            return me
        if callable(me):
            return me(slot)
        return me[slot % len(me)]

    def _make_member(self, slot: int, gen: int) -> _Member:
        """Build one member WITHOUT touching fleet state — safe to run
        outside the fleet lock (ISSUE 14 satellite: a process member's
        spawn+connect takes seconds, and under the lock it stalled
        every submit/poll for the duration)."""
        sid = f"m{slot}g{gen}"
        if self._transport == "inproc":
            svc = AsyncEnsembleService(self.model, service_id=sid,
                                       start=self._threaded,
                                       **self._member_kwargs)
        else:
            # a wire-backed member: the spawner owns the transport
            # (real child process, or the loopback serve thread); the
            # member pumps itself when the fleet is threaded and is
            # pumped over the wire in manual mode
            svc = self._spawner(
                self.model, service_id=sid,
                member_kwargs=dict(self._member_kwargs),
                clock=self._clock,
                heartbeat_deadline_s=self._heartbeat_deadline,
                rpc_deadline_s=self._rpc_deadline,
                member_env=self._member_env_for(slot),
                pump_mode="thread" if self._threaded else "rpc")
        if (self.journal is not None and self.journal.epoch is not None
                and hasattr(svc, "epoch")):
            # ISSUE 20 — arm the member-side fence: every RPC this
            # client sends is stamped with the supervisor's epoch, so a
            # member inherited by a newer supervisor refuses the
            # zombie's frames (the server ratchets to the highest epoch
            # it has seen and errs anything lower)
            svc.epoch = self.journal.epoch
        if gen > 0:
            # observability: how many times this fleet replaced a
            # member in place (fence → gen+1)
            self.counter.bump("respawns")
            get_recorder().record("respawn", service_id=sid)
        return _Member(service=svc, slot=slot, gen=gen,
                       progress_t=self._clock())

    def _install_locked(self, m: _Member) -> _Member:
        self._members[m.slot] = m
        return m

    def _spawn_locked(self, slot: int, gen: int) -> _Member:
        """Spawn + install in one step — the constructor/recovery path
        (no traffic contends for the lock yet). The supervision tick
        spawns through ``_make_member`` OUTSIDE the lock instead."""
        # analysis: ignore[blocking-under-lock] — constructor/recovery
        # only: no client traffic exists yet, so nothing contends for
        # the fleet lock during these spawns; every LIVE spawn (fence
        # respawn, autoscale up) runs through tick()'s unlocked phase
        return self._install_locked(self._make_member(slot, gen))

    def stop(self) -> None:
        """Drain and stop: members drain their queues (every pending
        ticket resolves), the final tick harvests everything, the
        journal closes. Idempotent."""
        with self._cv:
            if self._stopped:
                return
            self._stop_flag = True
            t = self._thread
            self._cv.notify_all()
        if t is not None:
            t.join()
        # the paging drain comes FIRST: hibernated tickets wake onto
        # members that are still pumping, so the member drains below
        # resolve them like any other queued work
        self._drain_hibernated()
        with self._cv:
            members = [m for m in self._members.values()
                       if not m.dead and not m.fenced]
        for m in members:
            m.service.stop()
        self.tick()
        with self._cv:
            self._stopped = True
            if self.journal is not None:
                self.journal.close()
            if self.tiering is not None:
                self.tiering.close()
            remaining = list(self._members.values())
        if self._transport != "inproc":
            # wire teardown AFTER the final harvest: the drain RPC in
            # stop() above kept each member's connection open so the
            # last tick could still poll results across it
            for m in remaining:
                try:
                    m.service.close()
                except WireError:  # pragma: no cover - best effort
                    pass

    def abandon(self) -> None:
        """Walk away WITHOUT draining — the crash simulation used by the
        recovery tests/bench: supervision stops dead (the abandoned flag
        makes any in-flight tick a no-op, so nothing is harvested or
        journaled after the "crash"), member threads are told to stop
        but not joined, and the journal handle closes with whatever was
        already flushed. The journal is the only survivor, exactly like
        a process kill."""
        with self._cv:
            self._stop_flag = True
            self._stopped = True
            self._abandoned = True
            t = self._thread
            members = list(self._members.values())
            self._cv.notify_all()
        if t is not None:
            # join the supervisor (its next tick no-ops) BEFORE closing
            # the journal — a close racing a harvest append would turn
            # the simulated kill into a real I/O error
            t.join()
        for m in members:
            m.service.abandon()
        with self._cv:
            if self.journal is not None:
                self.journal.close()
            if self.tiering is not None:
                # like the journal: the vault is the only survivor,
                # exactly as a process kill would leave it
                self.tiering.close()

    def __enter__(self) -> "FleetSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _supervise_loop(self) -> None:
        while True:
            try:
                self.tick()
            # analysis: ignore[broad-except] — the supervision loop's
            # own supervisor: a tick failure (e.g. a journal write
            # hitting a full disk) is counted and survived — a dead
            # supervisor is a dead fleet; per-ticket outcomes were
            # already resolved by _finalize_locked's finally
            except Exception:
                self.counter.bump("loop_faults")
            with self._cv:
                if self._stop_flag:
                    return
                self._cv.wait(self._tick_interval)

    def _renew_lease(self) -> None:
        """Re-stamp ``supervisor.lease`` (atomic tmp+replace — a reader
        sees the old record or the new one, never a torn write). Runs
        at the top of every tick; a write failure is a missed renewal
        (counted, survived) — the standby treats it like a death, which
        is the safe direction."""
        if self._lease_path is None:
            return
        rec = {"owner": self._supervisor_id,
               "epoch": (self.journal.epoch
                         if self.journal is not None else None),
               "t": self._clock(), "lease_s": self._lease_s}
        tmp = self._lease_path + ".tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(json.dumps(rec))
            os.replace(tmp, self._lease_path)
        except OSError as e:
            self.counter.bump("loop_faults")
            warnings.warn(f"supervisor lease renewal failed: {e} — a "
                          "standby may take over", RuntimeWarning)

    # -- client surface ------------------------------------------------------

    def submit(self, space: CellularSpace, *, model=None,
               steps: Optional[int] = None) -> int:
        """Admit one scenario to the fleet, or raise
        :class:`ServiceOverloaded` when EVERY member refuses. Routing is
        structure-affine (docstring); the returned ticket is a
        fleet-level id, stable across member fencing and migration.

        The admission runs inside a ``fleet.submit`` span (ISSUE 15):
        its context rides the ticket (``_Route.trace``, the journal
        submit record, the wire's trace meta), so every downstream
        dispatch span — member-side included — parents under it."""
        with get_tracer().span("fleet.submit") as sm:
            ticket = self._submit_traced(space, model, steps)
            sm["ticket"] = ticket
            return ticket

    def _submit_traced(self, space: CellularSpace, model,
                       steps: Optional[int]) -> int:
        m_model = self.model if model is None else model
        trace = get_tracer().current()
        n = self.default_steps if steps is None else int(steps)
        skey = structure_key(m_model, space) + (n,)
        nbytes = scenario_nbytes(space)
        pressure = (self.tiering is not None
                    and self.tiering.pressure(nbytes))
        with self._cv:
            order = self._candidates_locked(skey)
            last: Optional[ServiceOverloaded] = None
            if pressure:
                order = []  # the residency budget pages before routing
            for mem in order:
                try:
                    # analysis: ignore[blocking-under-lock] — admission
                    # routing must be atomic with the route table, and
                    # members run inline_dispatch=False: their submit
                    # is depth-check + enqueue, never device work (a
                    # wire member's submit RPC is deadline-bounded)
                    mt = mem.service.submit(space, model=model, steps=n)
                except ServiceOverloaded as e:
                    last = e
                    continue
                except WireError:
                    # the member's wire died under us: a member fault —
                    # mark dead (next tick fences), try the next one
                    self.counter.bump("wire_errors")
                    mem.dead = True
                    continue
                ticket = next(self._ids)
                route = _Route(member=mem, member_ticket=mt, space=space,
                               model=m_model, steps=n,
                               submitted_at=self._clock(), trace=trace)
                self._route[ticket] = route
                self._journal_submit_locked(ticket, route)
                if self.tiering is not None:
                    self.tiering.admit(ticket, nbytes)
                return ticket
            if self.tiering is not None \
                    and self.tiering.room_for(nbytes):
                # capacity-aware paging (ISSUE 14): every member
                # refused (or the residency budget is exhausted) — the
                # arrival HIBERNATES instead of shedding; tick() wakes
                # it onto the affinity member as capacity frees. The
                # fleet journal's submit record (full state) is the
                # wake path's last-resort source.
                ticket = next(self._ids)
                self._journal_submit_hibernated_locked(
                    ticket, space, m_model, n, trace)
                self._hib_meta[ticket] = (m_model, n, skey,
                                          self._clock(), trace)
            else:
                ticket = None
                self.counter.bump("shed")
                get_recorder().record("shed", service_id=None)
                depth = sum(m.service.scheduler.pending_count()
                            for m in order)
                self._journal_append_locked(SHED, {
                    "depth": depth,
                    "members": [m.service_id for m in order]})
        if ticket is None:
            reason = ("hibernation tier exhausted"
                      if self.tiering is not None
                      else "every member refused")
            raise ServiceOverloaded(
                f"fleet admission shed — {reason}"
                + (f" (last: {last})" if last is not None else ""),
                queue_depth=depth,
                retry_after_s=(last.retry_after_s if last is not None
                               else self._tick_interval))
        # the chain write happens OUTSIDE the fleet lock: paging I/O
        # must not stall every submit/poll (the vault serializes
        # paging operations against each other only)
        try:
            self.tiering.hibernate(ticket, space, m_model, n,
                                   submitted_at=self._clock(), skey=skey)
        except (OSError, ValueError) as e:
            # the vault is unwritable: the journaled submit record must
            # not become a forever-unresolved ghost — journal the
            # terminal (the replay audit stays complete), drop the
            # registration, and refuse the admission observably (the
            # caller still holds its state)
            with self._cv:
                self._hib_meta.pop(ticket, None)
                self._journal_append_locked(QUARANTINED, {
                    "ticket": ticket, "service_id": "hibernated",
                    "steps": n, "error": type(e).__name__,
                    "detail": f"hibernation write failed: {e}"})
                self.counter.bump("shed")
            raise ServiceOverloaded(
                f"fleet admission shed — hibernation write failed: {e}",
                queue_depth=0,
                retry_after_s=self._tick_interval) from e
        return ticket

    def _candidates_locked(self, skey) -> list[_Member]:
        """Routable members, preferred-first: the structure hash picks
        the affinity member (stable while membership is stable — its
        bucketed runner cache stays hot for this structure group); the
        rest follow in ascending queue depth (the least-loaded
        tiebreak)."""
        cands = sorted(
            (m for m in self._members.values()
             if not m.fenced and not m.dead and not m.retiring),
            key=lambda m: m.slot)
        if not cands:
            return []
        preferred = cands[hash(skey) % len(cands)]
        rest = sorted(
            (m for m in cands if m is not preferred),
            key=lambda m: m.service.scheduler.pending_count())
        return [preferred] + rest

    def poll(self, ticket: int):
        """(space, Report) when resolved, None while outstanding (a
        HIBERNATED ticket polls None exactly like a queued one); raises
        the ticket's quarantine/expiry/member error. Terminal outcomes
        are journaled at first observation (the harvest seam), then
        popped — the collected-ticket contract of the scheduler."""
        with self._cv:
            if ticket in self._resolved:
                res = self._resolved.pop(ticket)
            elif ticket in self._hib_meta:
                return None  # paged out; tick() wakes it
            else:
                route = self._route.get(ticket)
                if route is None:
                    raise KeyError(
                        f"unknown or already-collected fleet ticket "
                        f"{ticket}")
                try:
                    # analysis: ignore[blocking-under-lock] — member
                    # poll runs pump=False: it only checks the results
                    # table (the pump thread owns dispatching), so the
                    # statically-visible dispatch chain never runs here
                    r = route.member.service.poll(route.member_ticket)
                except WireError:
                    # member fault, not a ticket outcome: the next
                    # tick fences the member and re-admits this ticket
                    self.counter.bump("wire_errors")
                    route.member.dead = True
                    return None
                # analysis: ignore[broad-except] — harvest seam: ANY
                # per-ticket resolution error (quarantine, expiry,
                # conservation, dispatch fault) must be journaled and
                # returned to this ticket's caller, never lost
                except Exception as e:
                    self._finalize_locked(ticket, e)
                    res = self._resolved.pop(ticket)
                else:
                    if r is None:
                        return None
                    self._finalize_locked(ticket, r)
                    res = self._resolved.pop(ticket)
        if isinstance(res, Exception):
            raise res
        return res

    def result(self, ticket: int, timeout: Optional[float] = None):
        """Block until ``ticket`` resolves; ``TimeoutError`` after
        ``timeout`` wall seconds. Manual mode pumps synchronously."""
        # analysis: ignore[naked-timer] — result()'s timeout= is a
        # CLIENT-facing wall bound, not a measurement: nothing is
        # recorded, so a span would be noise
        deadline = (
            # analysis: ignore[naked-timer] — client wall bound (see
            # the pragma block above), not a measurement
            None if timeout is None
            # analysis: ignore[naked-timer] — same bound
            else time.monotonic() + float(timeout))
        while True:
            res = self.poll(ticket)
            if res is not None:
                return res
            if not self._threaded:
                did = self.pump_once(force=True)
                if not did:
                    res = self.poll(ticket)
                    if res is not None:
                        return res
                    raise RuntimeError(
                        f"fleet ticket {ticket} pending but no member "
                        "found work — fleet state is inconsistent")
                continue
            with self._cv:
                # analysis: ignore[naked-timer] — the same client wall
                # bound's expiry check (no measurement recorded)
                if (deadline is not None
                        # analysis: ignore[naked-timer] — same bound
                        and time.monotonic() >= deadline):
                    raise TimeoutError(
                        f"fleet ticket {ticket} still pending after "
                        f"{timeout}s")
                self._cv.wait(self._poll_interval)

    def pump_once(self, force: bool = False) -> bool:
        """Manual mode: pump every live member once (supervising the
        pump like the threaded loop would — a ``thread_exc`` is counted
        and survived, a ``MemberKilled`` marks the member dead), then
        run one supervision ``tick``."""
        with self._cv:
            members = [m for m in self._members.values()
                       if not m.fenced and not m.dead]
        did = False
        for m in members:
            try:
                did = m.service.pump_once(force=force) or did
            except inject.MemberKilled:
                with self._cv:
                    m.dead = True
                did = True
            except WireError:
                # the member's wire died mid-pump: a member fault —
                # dead now, fenced by this pump's tick
                self.counter.bump("wire_errors")
                with self._cv:
                    m.dead = True
                did = True
            # analysis: ignore[broad-except] — the manual-mode pump
            # supervisor mirrors AsyncEnsembleService._loop: a pump
            # fault is counted and survived, never fatal to the fleet
            except Exception:
                m.service.scheduler.counter.bump("loop_faults")
                did = True
        # tick() wakes hibernated scenarios (ISSUE 14); a wake or a
        # hibernated-ticket resolution is pump WORK — result()'s
        # manual-mode progress check must see it (plain GIL-atomic
        # counter reads, same discipline as _progress_sig)
        c = self.counter
        before = c.wakes + c.expired + c.quarantined
        self.tick()
        return did or (self.tiering is not None
                       and c.wakes + c.expired + c.quarantined > before)

    # -- supervision ---------------------------------------------------------

    def tick(self) -> None:
        """One supervision pass: harvest resolved tickets into the
        fleet (journaling terminals), health-check and fence failed
        members, spawn replacements, advance drain-before-retire,
        evaluate autoscaling, wake hibernated scenarios into freed
        capacity.

        Member SPAWNS happen OUTSIDE the fleet lock (ISSUE 14
        satellite — the PR 13 remainder): a process member's
        spawn+connect takes seconds, and under the lock it stalled
        every submit/poll for the duration. The tick is three phases:
        (1) under the lock — harvest, mark fences (the fenced member
        stops routing immediately), collect spawn requests; (2) no
        lock — build the replacement members; (3) under the lock —
        install them and drain the fenced members (harvest what
        resolved, migrate what is queued, re-admit the rest from the
        fleet's stored state). Between (1) and (3) admissions proceed
        on the surviving members.

        Retired members are STOPPED after the lock is released: stop()
        joins the member's pump thread (and in manual mode force-drains
        it), and the concurrency auditor's blocking-under-lock rule is
        right that a join under the fleet lock would stall every
        submit/poll for the duration of the drain. By removal time the
        member holds no routes and takes no intake, so nothing can race
        its shutdown.

        Wire transports add a phase BEFORE the lock: every live member
        is heartbeat-RPCed (deadline-bounded, outside the fleet lock —
        a slow wire must not stall submit/poll), refreshing the cached
        telemetry the locked phase then reads."""
        if self._supervisor_id is not None:
            st = inject.active()
            if st is not None and st.member_fault(
                    self._supervisor_id, ("supervisor_kill",),
                    site="lease", count=True) is not None:
                # ISSUE 20 — the simulated ``kill -9`` of the ACTIVE
                # supervisor: supervision stops DEAD mid-soak. The
                # flags are set inline (abandon() would join the
                # supervisor thread — the thread we are ON); the
                # journal handle stays OPEN, exactly like a zombie
                # process that still holds its fd — the failover bench
                # asserts the epoch fence rejects its next append
                self.counter.bump("supervisor_kills")
                get_recorder().record("supervisor_kill",
                                      service_id=self._supervisor_id)
                with self._cv:
                    self._abandoned = True
                    self._stop_flag = True
                    self._stopped = True
                    self._cv.notify_all()
                return
            self._renew_lease()
        self._heartbeat_members()
        with self._cv:
            if self._abandoned:
                return  # a simulated kill: supervision is dead
            self._harvest_locked()
            to_fence, spawn_reqs = self._health_check_locked()
            to_fence = self._pending_fences + to_fence
            self._pending_fences = []
            spawn_reqs = self._pending_spawns + spawn_reqs
            self._pending_spawns = []
            retired = self._advance_retirements_locked()
            if self._policy is not None and not self._stop_flag:
                req = self._autoscale_locked()
                if req is not None:
                    spawn_reqs.append(req)
            self._cv.notify_all()
        spawned = []
        failed_reqs = []
        for slot, gen in spawn_reqs:
            try:
                spawned.append(self._make_member(slot, gen))
            # analysis: ignore[broad-except] — spawn isolation: one
            # replacement failing to come up (a dead spawner, a full
            # tmpdir) must not unwind the tick past the fence drain
            # that resolves the dead member's tickets; counted, and
            # RE-QUEUED for the next tick (the fenced slot was deleted
            # from the membership, so nothing else would re-request it)
            except Exception:
                self.counter.bump("loop_faults")
                failed_reqs.append((slot, gen))
        completed_fences = []
        with self._cv:
            if not self._abandoned:
                self._pending_spawns.extend(failed_reqs)
                failed_slots = {slot for slot, _gen in failed_reqs}
                for m in spawned:
                    self._install_locked(m)
                live = any(not x.fenced and not x.dead
                           and not x.retiring
                           for x in self._members.values())
                for m, reason in to_fence:
                    if (m.slot in failed_slots and not m.retiring
                            and not live and not self._stop_flag):
                        # its replacement never came up AND nobody
                        # else can take its tickets: defer the drain
                        # until the re-queued spawn lands, instead of
                        # resolving everything as MemberFailure for
                        # want of a one-tick-late candidate (at stop
                        # there IS no next tick — the drain completes
                        # now with counted MemberFailures)
                        self._pending_fences.append((m, reason))
                        continue
                    self._complete_fence_locked(m, reason)
                    completed_fences.append(m)
                self._cv.notify_all()
        # the flight-recorder dump rides BESIDE each fence's
        # FailureEvent (ISSUE 15), outside the fleet lock — the dump
        # may write a file, and the ring already holds the run-up.
        # Only COMPLETED fences dump: a deferred fence re-enters
        # to_fence every tick until its respawn lands, and dumping it
        # per tick would churn the bounded dump ledger with duplicates
        for m in completed_fences:
            get_recorder().dump("fence", service_id=m.service_id)
        self._wake_due()
        for m in retired:
            try:
                m.service.stop()
            # analysis: ignore[broad-except] — retiree-stop isolation:
            # every member in `retired` is already out of the
            # membership, so a failing drain on one (a chaos fault in
            # its final pump) must not unwind past the next retiree's
            # shutdown or out of tick(); counted, never silent
            except Exception:
                self.counter.bump("loop_faults")

    def _heartbeat_members(self) -> None:
        """The wire transports' liveness phase (inproc: no-op): beat
        every live member OUTSIDE the fleet lock (the RPC is
        deadline-bounded, but even a bounded stall must not hold
        submit/poll), refreshing the per-member telemetry cut. Misses
        are counted; ``is_alive`` ages them against
        ``heartbeat_deadline_s`` on the injectable clock and the
        health check fences what went stale."""
        if self._transport == "inproc":
            return
        with self._cv:
            if self._abandoned or self._stopped:
                return
            members = [m for m in self._members.values()
                       if not m.fenced and not m.dead]
        for m in members:
            self.counter.bump("heartbeats")
            if not m.service.heartbeat():
                self.counter.bump("heartbeat_misses")

    def _harvest_locked(self) -> None:
        for ticket, route in list(self._route.items()):
            m = route.member
            if m.fenced or m.dead:
                continue  # the fencing path owns these
            try:
                # analysis: ignore[blocking-under-lock] — member poll
                # runs pump=False: results-table check only, the
                # dispatch chain the auditor sees is the pump's
                r = m.service.poll(route.member_ticket)
            except WireError:
                # a broken wire is a MEMBER fault, not a ticket
                # outcome: mark the member dead — this same tick's
                # health check fences it and re-admits its tickets
                self.counter.bump("wire_errors")
                m.dead = True
                continue
            # analysis: ignore[broad-except] — harvest seam (see poll)
            except Exception as e:
                self._finalize_locked(ticket, e)
                continue
            if r is not None:
                self._finalize_locked(ticket, r)

    def _journal_append_locked(self, kind: str, meta: dict,
                               arrays=None) -> None:
        """Every fleet journal write goes through here: an append
        failure (full disk, closed handle) is WARNED and counted as a
        loop fault, never allowed to unwind the supervision path that
        called it — a broken journal degrades recovery to re-running
        (at-least-once), it must not strand live tickets or fences.
        The in-memory ledger is always authoritative for this process's
        lifetime.

        Known cost, deliberately accepted: appends run UNDER the fleet
        lock (record ordering per ticket — submit before terminal — is
        what recovery's replay depends on, and the lock is what
        provides it today), so journaled state serialization is on the
        admission/harvest critical path. For large grids either pass
        ``journal_results=False`` (terminal records become metadata-
        only) or leave ``journal_dir`` unset; moving appends to a
        dedicated journal mutex with per-ticket ordering is the next
        optimization if a journaled fleet ever becomes
        admission-latency-bound."""
        if self.journal is None:
            return
        try:
            # analysis: ignore[blocking-under-lock] — THE documented
            # journal-append-under-the-fleet-lock cost (docstring
            # above): per-ticket record ordering (submit before
            # terminal) is exactly what this lock provides; the
            # latency escapes are journal_results=False (metadata-only
            # terminals) or journal_dir=None, both regression-tested
            self.journal.append(kind, meta, arrays)
        except StaleEpochError as e:
            # ISSUE 20 — the epoch fence fired: a NEWER supervisor owns
            # this journal, so this one is a zombie whose append wrote
            # NOTHING. Counted separately from loop_faults (the bench's
            # failover leg asserts the rejection happened) — and unlike
            # an I/O fault this is not transient: every later append
            # from this handle is equally fenced.
            self.counter.bump("stale_epoch_rejections")
            warnings.warn(
                f"fleet journal append ({kind}) fenced: {e} — this "
                "supervisor was superseded; stop it", RuntimeWarning)
        except (OSError, ValueError) as e:
            self.counter.bump("loop_faults")
            warnings.warn(
                f"fleet journal append ({kind}) failed: {e} — serving "
                "continues; crash-restart recovery will re-run instead "
                "of replaying whatever this record would have resolved",
                RuntimeWarning)

    def _finalize_locked(self, ticket: int, outcome) -> None:
        route = self._route[ticket]
        sid = (route.member.service_id if route.member is not None
               else "recovery")
        try:
            if isinstance(outcome, Exception):
                kind = (EXPIRED
                        if isinstance(outcome, TicketExpired)
                        else QUARANTINED)
                self._journal_append_locked(kind, {
                    "ticket": ticket, "service_id": sid,
                    "steps": route.steps,
                    # a wire-crossed error journals its ORIGINAL
                    # member-side class (RemoteError.remote_type), so
                    # the ledger reads the same in both transports
                    "error": getattr(outcome, "remote_type",
                                     type(outcome).__name__),
                    "detail": str(outcome)})
            elif self.journal is not None:
                space, report = outcome
                # analysis: ignore[blocking-under-lock] — journaled
                # state serialization rides the harvest path under the
                # lock by design (see _journal_append_locked); the
                # journal_results=False escape skips the array payload
                meta, arrays = space_payload(space)
                if not self._journal_results:
                    arrays = None
                meta.update({
                    "ticket": ticket, "service_id": sid,
                    "steps": route.steps,
                    "initial_total": dict(report.initial_total),
                    "final_total": dict(report.final_total),
                    "wall_time_s": report.wall_time_s})
                self._journal_append_locked(SERVED, meta, arrays)
        finally:
            # the in-memory ledger resolves even if journaling failed
            # in an unforeseen way: a journal failure must never turn
            # into a silently dropped ticket
            self._route.pop(ticket, None)
            self._resolved[ticket] = outcome
            if self.tiering is not None:
                # analysis: ignore[blocking-under-lock] — reclaiming a
                # resolved ticket's chain (a few small files) must be
                # atomic with its resolution, or a racing wake could
                # resurrect a served scenario; the vault lock is a leaf
                self.tiering.release(ticket)
            if not isinstance(outcome, Exception):
                self.counter.record_latency(
                    self._clock() - route.submitted_at)

    def _progress_sig(self, m: _Member) -> tuple:
        # COMPLETION-side progress only: dispatches finishing,
        # scenarios serving, lanes quarantining or recovering — things
        # only a working pump produces. Queue churn (arrivals growing
        # pending, harvest-side expiries shrinking it) and supervised
        # pump faults must NOT count, or a wedged member that keeps
        # receiving traffic would reset its own wedge timer forever and
        # resolve every routed ticket by expiry instead of being
        # fenced. Plain int reads (GIL-atomic); a momentarily torn
        # signature only delays the heuristic by one tick.
        c = m.service.scheduler.counter
        return (c.dispatches, c.scenarios, c.quarantined,
                c.recovered_failures)

    def _health_check_locked(self) -> tuple[list, list]:
        """Mark failed members fenced and collect what the tick must do
        next: returns ``(to_fence, spawn_requests)`` — the fence DRAIN
        and the replacement SPAWN happen in the tick's later phases
        (the spawn outside the lock), but from this moment the marked
        member takes no routing and no harvest."""
        to_fence: list = []
        spawn_reqs: list = []
        now = self._clock()
        for m in list(self._members.values()):
            if m.fenced:
                continue
            # progress signature includes DUE-ness: work becoming due
            # (a max-wait window closing) resets the wedge timer, and a
            # member merely waiting out its batching policy (partial
            # bucket inside max_wait_s, nothing launched) is never
            # "wedged" — only due work with zero progress is
            due = m.service.has_work_due()
            sig = self._progress_sig(m) + (due,)
            if sig != m.progress_sig:
                m.progress_sig = sig
                m.progress_t = now
            pending = m.service.scheduler.pending_count()
            reason = None
            if m.dead:
                reason = "pump thread died"
            elif (self._transport != "inproc" and not self._stop_flag
                  and not m.service.is_alive()):
                # wire members: liveness IS heartbeat freshness (there
                # is no thread to probe across a process boundary) —
                # checked in manual AND threaded fleets
                reason = ("missed heartbeats: last good beat "
                          f"{m.service.heartbeat_age():.3f}s ago "
                          "(heartbeat deadline "
                          f"{self._heartbeat_deadline}s)")
            elif (self._transport == "inproc" and self._threaded
                  and not self._stop_flag and not m.service.is_alive()):
                reason = "pump thread died"
            elif (pending > 0 and due
                  and now - m.progress_t > self._supervision_deadline):
                reason = (f"wedged: no progress for "
                          f"{now - m.progress_t:.3f}s with {pending} "
                          "pending (supervision deadline "
                          f"{self._supervision_deadline}s)")
            if reason is not None:
                req = self._fence_locked(m, reason)
                to_fence.append((m, reason))
                if req is not None:
                    spawn_reqs.append(req)
                continue
            if (self._fence_on_ladder_bottom and not m.retiring
                    and m.service.scheduler.degraded_from is not None
                    and m.service.scheduler.DEGRADE_TO.get(
                        m.service.scheduler.executor.impl) is None):
                # the pump is alive — drain out, never double-dispatch
                spawn_reqs.append(self._fence_live_locked(
                    m, "degradation ladder bottomed out (from "
                    f"{m.service.scheduler.degraded_from!r} to "
                    f"{m.service.scheduler.executor.impl!r})"))
        return to_fence, spawn_reqs

    #: the member-counter fields stats() aggregates — absorbed from a
    #: member at fence/retire time so its work never vanishes from the
    #: fleet-level metrics when the member object does
    _ABSORB_KEYS = ("dispatches", "scenarios", "lanes", "cache_hits",
                    "solo_retries", "recovered_failures", "quarantined",
                    "impl_faults", "expired", "loop_faults", "busy_s",
                    "inflight_s")

    def _absorb_counters_locked(self, m: _Member) -> None:
        c = m.service.scheduler.counter
        for k in self._ABSORB_KEYS:
            self._absorbed[k] = self._absorbed.get(k, 0) + getattr(c, k)
        for k in ("wire_bytes_in", "wire_bytes_out"):
            v = getattr(m.service, k, None)
            if v is not None:
                self._absorbed[k] = self._absorbed.get(k, 0) + int(v)

    def _member_event_locked(self, m: _Member, reason: str) -> None:
        from ..resilience import FailureEvent

        self.member_log.append(FailureEvent(
            step=0, kind="member", detail=reason, rolled_back_to=0,
            attempt=m.gen + 1, wall_time_s=0.0,
            classification="transient", service_id=m.service_id))
        self.counter.bump("member_faults")
        # record only here (this runs under the fleet lock); the
        # ring DUMP beside the FailureEvent happens in tick()'s
        # unlocked phase (ISSUE 15)
        get_recorder().record("fence", service_id=m.service_id,
                              reason=reason)

    def _fence_locked(self, m: _Member, reason: str
                      ) -> Optional[tuple]:
        """Phase-1 fencing for a member whose pump can no longer make
        progress (dead thread / wedge): mark it fenced (no routing, no
        harvest — from this instant), log the kind="member"
        FailureEvent, and return the replacement spawn request
        ``(slot, gen+1)`` the tick fulfills OUTSIDE the lock. The drain
        (``_complete_fence_locked``) runs after the replacement is
        installed, so re-admission always has a candidate."""
        m.fenced = True
        self._member_event_locked(m, reason)
        warnings.warn(
            f"fleet member {m.service_id} fenced ({reason}); "
            f"restarting fresh as m{m.slot}g{m.gen + 1}",
            RuntimeWarning)
        if m.retiring:
            return None
        return (m.slot, m.gen + 1)

    def _complete_fence_locked(self, m: _Member, reason: str) -> None:
        """Phase-3 fencing: move every ticket the fenced member held —
        harvest what resolved, migrate what is still queued, re-admit
        from the fleet's stored state what was claimed/launched (the
        old pump cannot finish it; if a wedged thread later unwedges,
        its results land in an abandoned scheduler nobody reads — the
        fleet's resolution stays exactly-once) — and abandon the old
        pump."""
        self._drain_member_locked(m, reason)
        self._absorb_counters_locked(m)
        m.service.abandon()
        if m.slot in self._members and self._members[m.slot] is m:
            # no replacement was installed over this slot (a retiring
            # member, or its spawn failed — the next tick re-fences)
            del self._members[m.slot]

    def _fence_live_locked(self, m: _Member, reason: str) -> tuple:
        """The failure-domain boundary for a member whose pump still
        WORKS but whose engine is no longer trusted (ladder bottom):
        drain-out instead of kill — intake stops (retiring), a fresh
        replacement starts in a NEW slot (spawned outside the lock;
        the returned request is the tick's to fulfill), queued tickets
        migrate, and in-flight batches FINISH on the old member before
        it is removed (re-admitting them would double-dispatch
        scenarios a live pump is still computing)."""
        m.retiring = True
        m.retire_kind = "fence"
        self._member_event_locked(m, reason)
        warnings.warn(
            f"fleet member {m.service_id} draining out ({reason}); "
            "replacement starts fresh on the configured impl",
            RuntimeWarning)
        self._migrate_queued_locked(m, reason)
        return (next(self._slot_ids), 0)

    def _drain_member_locked(self, m: _Member, reason: str) -> None:
        for ticket, route in list(self._route.items()):
            if route.member is not m:
                continue
            try:
                # analysis: ignore[blocking-under-lock] — member poll
                # runs pump=False (results-table check only)
                r = m.service.poll(route.member_ticket)
            except WireError:
                # the fenced member's wire is gone (a killed process):
                # nothing to harvest or migrate — re-admit from the
                # fleet's stored state
                self.counter.bump("wire_errors")
                self._readmit_locked(ticket, route, reason)
                continue
            # analysis: ignore[broad-except] — harvest seam (see poll)
            except Exception as e:
                self._finalize_locked(ticket, e)
                continue
            if r is not None:
                self._finalize_locked(ticket, r)
                continue
            moved = False
            skey = structure_key(route.model, route.space) + (route.steps,)
            order = self._candidates_locked(skey)
            if order:
                target = order[0]
                try:
                    # analysis: ignore[blocking-under-lock] — fencing
                    # drain must stay atomic with the route table (a
                    # concurrent submit must not route onto the fenced
                    # member mid-move); migration is rare (fence only)
                    # and the CRC-verified handoff is the point
                    new_mt = m.service.scheduler.migrate_ticket(
                        route.member_ticket, target.service.scheduler)
                except (TicketNotMigratable, KeyError):
                    pass  # claimed/launched — re-admit from stored state
                except WireError:
                    # dead wire — re-admit from stored state
                    self.counter.bump("wire_errors")
                # analysis: ignore[broad-except] — fence-drain
                # isolation: a wire-crossed migrate can surface ANY
                # member-side error (RemoteError, a reconstructed
                # expiry); unwinding would strand the fenced member's
                # remaining tickets (fenced members are never
                # revisited) — the fleet's stored copy re-admits
                except Exception:
                    self.counter.bump("loop_faults")
                else:
                    route.member, route.member_ticket = target, new_mt
                    moved = True
                    self._journal_append_locked(MIGRATE, {
                        "ticket": ticket, "from": m.service_id,
                        "to": target.service_id, "reason": reason})
            if not moved:
                self._readmit_locked(ticket, route, reason)

    def _readmit_locked(self, ticket: int, route: _Route,
                        reason: str) -> None:
        """Re-admit a ticket whose member can no longer serve it, from
        the fleet's own copy of the scenario. Bypasses the admission
        bound (recovery must not shed an already-admitted ticket); if
        no healthy member exists the ticket resolves as a
        MemberFailure — counted, never silent."""
        old_sid = (route.member.service_id if route.member is not None
                   else "recovery")
        skey = structure_key(route.model, route.space) + (route.steps,)
        for target in self._candidates_locked(skey):
            try:
                with get_tracer().attach(route.trace):
                    # analysis: ignore[blocking-under-lock] — re-admission must be atomic
                    # with the route table, and members run
                    # inline_dispatch=False: the scheduler's
                    # inline-dispatch tail the auditor sees is
                    # unreachable on this path
                    new_mt = target.service.scheduler.submit(
                        route.space, route.model, route.steps)
            except WireError:
                # a rescue target whose own wire is dead: mark it (its
                # fencing is the next health check's) and try the next
                # candidate — a re-admission must never strand mid-fence
                self.counter.bump("wire_errors")
                target.dead = True
                continue
            route.member, route.member_ticket = target, new_mt
            self.counter.bump("readmitted")
            self._journal_append_locked(READMIT, {
                "ticket": ticket, "from": old_sid,
                "to": target.service_id, "reason": reason})
            return
        self._finalize_locked(ticket, MemberFailure(
            f"member {old_sid} failed ({reason}) and no healthy "
            f"member remains to re-admit ticket {ticket}", old_sid))

    def _advance_retirements_locked(self) -> list[_Member]:
        """Advance every drain-before-retire: migrate queued tickets
        off, and once a retiree holds nothing, remove it from the
        membership and absorb its counters. Returns the removed members
        so ``tick`` can stop them OUTSIDE the fleet lock — ``stop()``
        joins the retiree's pump thread, and a join under the lock
        would stall every submit/poll for the whole drain."""
        retired: list[_Member] = []
        for m in list(self._members.values()):
            if not m.retiring or m.fenced or m.dead:
                continue
            self._migrate_queued_locked(m, "retiring")
            if m.service.scheduler.pending_count() > 0:
                continue  # in-flight work still resolving; next tick
            held = [t for t, r in self._route.items() if r.member is m]
            if held:  # pragma: no cover - defensive (harvest precedes)
                continue
            # zero ticket loss, asserted: nothing routed here anymore
            del self._members[m.slot]
            self._absorb_counters_locked(m)
            retired.append(m)
            if m.retire_kind == "scale":
                self.counter.bump("scale_downs")
        return retired

    def _migrate_queued_locked(self, m: _Member, reason: str) -> None:
        """Move every still-QUEUED ticket off ``m`` (drain-before-
        retire / fencing); claimed/launched tickets are left to resolve
        in place (retire) or re-admitted (fencing path)."""
        try:
            queued = m.service.scheduler.queued_tickets()
        except WireError:
            # the retiree's wire died mid-drain: a member fault — dead
            # now; the fencing path re-admits what it held
            self.counter.bump("wire_errors")
            m.dead = True
            return
        for mt in queued:
            ticket = next((t for t, r in self._route.items()
                           if r.member is m and r.member_ticket == mt),
                          None)
            if ticket is None:  # pragma: no cover - defensive
                continue
            route = self._route[ticket]
            skey = structure_key(route.model, route.space) + (route.steps,)
            order = self._candidates_locked(skey)
            if not order:
                return  # nowhere to drain to; try again next tick
            try:
                # analysis: ignore[blocking-under-lock] — the
                # drain-before-retire move must stay atomic with the
                # route table; retirement is rare and the CRC-verified
                # handoff is the point
                new_mt = m.service.scheduler.migrate_ticket(
                    mt, order[0].service.scheduler)
            except (TicketNotMigratable, KeyError):
                continue
            except WireError:
                # either side's wire died mid-move (extract done,
                # landing unknown): the fleet's own copy of the
                # scenario is the one source that is still certain —
                # re-admit from it now; whichever side actually died
                # is fenced by its missed heartbeats
                self.counter.bump("wire_errors")
                self._readmit_locked(ticket, route, reason)
                continue
            # analysis: ignore[broad-except] — same mid-move shape for
            # any OTHER wire-crossed member error (RemoteError …): the
            # extract may have landed, so the route must not keep
            # pointing at the source — re-admit from the stored copy
            except Exception:
                self.counter.bump("loop_faults")
                self._readmit_locked(ticket, route, reason)
                continue
            route.member, route.member_ticket = order[0], new_mt
            self._journal_append_locked(MIGRATE, {
                "ticket": ticket, "from": m.service_id,
                "to": order[0].service_id, "reason": reason})

    # -- scenario tiering (ISSUE 14) -----------------------------------------

    def _wake_due(self) -> int:
        """Wake hibernated fleet tickets FIFO into freed capacity. The
        chain restore runs OUTSIDE the fleet lock (paging I/O must not
        stall submit/poll); the placement — structure-affine routing +
        member submit — is atomic with the route table, so the woken
        scenario lands on the member whose bucket runner is already
        compiled. A wake that finds every member refusing goes back to
        the HEAD of the queue (its chain is untouched). Hibernated
        tickets past the member deadline resolve as ``TicketExpired``.
        Returns wakes + resolutions performed."""
        if self.tiering is None:
            return 0
        did = 0
        while True:
            nxt = self.tiering.peek_next()
            if nxt is None:
                return did
            ticket, nbytes = nxt
            with self._cv:
                meta = self._hib_meta.get(ticket)
                if meta is None:
                    # a vault entry nothing routes to (e.g. recovery
                    # found a chain whose fleet-journal ticket already
                    # resolved): reclaim it
                    # analysis: ignore[blocking-under-lock] — the
                    # reclaim (a few small files + one journal line)
                    # must be atomic with the bookkeeping check, or a
                    # racing wake could resurrect the orphan; the
                    # vault lock is a leaf
                    self.tiering.drop(ticket)
                    continue
                model, steps, skey, submitted_at, trace = meta
                live = [m for m in self._members.values()
                        if not m.fenced and not m.dead
                        and not m.retiring]
                room = (any(m.service.scheduler.pending_count()
                            < self._max_queue for m in live)
                        and self.tiering.fits(nbytes))
                idle = not self._route and bool(live)
                if not (room or idle):
                    return did
                ddl = self._member_kwargs.get("deadline_s")
                if ddl is not None \
                        and self._clock() - submitted_at > ddl:
                    age = self._clock() - submitted_at
                    self._resolve_hibernated_locked(ticket, TicketExpired(
                        f"fleet ticket {ticket} expired after "
                        f"{age:.3f}s in the hibernation tier (deadline "
                        f"{ddl}s) — never dispatched"), steps)
                    did += 1
                    continue
            try:
                # the wake re-attaches the ticket's submit-span context
                # (ISSUE 15): the tiering.wake span parents under it,
                # so a paged-out flight reads as one trace
                with get_tracer().attach(trace):
                    space, entry = self.tiering.wake(
                        ticket, fallback=self._journal_state_fallback)
            except HibernationError as e:
                with self._cv:
                    self._resolve_hibernated_locked(ticket, e, steps)
                # dump OUTSIDE the fleet lock (the recorder dump may
                # touch the filesystem)
                get_recorder().dump("hibernation", ticket=ticket)
                did += 1
                continue
            placed = self._place_woken(ticket, space, model, steps,
                                       skey, submitted_at, nbytes,
                                       bypass=False, trace=trace)
            if not placed:
                # every member refused mid-wake: back to the head; the
                # next tick retries once capacity really freed
                self.tiering.requeue(ticket, entry)
                return did
            did += 1

    def _place_woken(self, ticket: int, space, model, steps: int,
                     skey, submitted_at, nbytes: int,
                     bypass: bool, trace=None) -> bool:
        """Route one woken scenario onto a live member and install its
        route (atomic with the route table). ``bypass=True`` submits
        scheduler-level (the stop()-drain path — an admitted ticket is
        never shed by its own drain)."""
        with self._cv:
            if skey is None:
                skey = structure_key(model, space) + (steps,)
            for mem in self._candidates_locked(skey):
                try:
                    # the ticket's submit-span context re-attaches for
                    # the placement (ISSUE 15): member dispatch spans
                    # keep parenting under the original submit span
                    # even after a hibernation round trip
                    with get_tracer().attach(trace):
                        if bypass:
                            # analysis: ignore[blocking-under-lock] — the re-admission
                            # contract of _readmit_locked: placement
                            # must be atomic with the route table;
                            # members run inline_dispatch=False
                            mt = mem.service.scheduler.submit(
                                space, model, steps)
                        else:
                            # analysis: ignore[blocking-under-lock] — same contract as
                            # submit()'s admission routing
                            mt = mem.service.submit(space, model=model,
                                                    steps=steps)
                except ServiceOverloaded:
                    continue
                except WireError:
                    self.counter.bump("wire_errors")
                    mem.dead = True
                    continue
                self._route[ticket] = _Route(
                    member=mem, member_ticket=mt, space=space,
                    model=model, steps=steps, submitted_at=submitted_at,
                    trace=trace)
                self._hib_meta.pop(ticket, None)
                self.tiering.admit(ticket, nbytes)
                sid = mem.service_id
                self._wakes_by_member[sid] = \
                    self._wakes_by_member.get(sid, 0) + 1
                self._journal_append_locked(WAKE, {
                    "ticket": ticket, "to": sid})
                self._cv.notify_all()
                return True
            return False

    def _resolve_hibernated_locked(self, ticket: int, err: Exception,
                                   steps: int) -> None:
        """Terminal outcome for a ticket still in the hibernation tier
        (deadline expiry, an unwakeable chain, no member left at the
        drain): journaled like any other terminal, counted, published
        to ``_resolved`` — never silent."""
        from ..resilience import FailureEvent

        expired = isinstance(err, TicketExpired)
        kind = EXPIRED if expired else QUARANTINED
        err.ticket = ticket
        ev = FailureEvent(
            step=steps, kind="expired" if expired else "hibernation",
            detail=str(err), rolled_back_to=0, attempt=1,
            wall_time_s=0.0, classification="deterministic",
            ticket=ticket, service_id="hibernated")
        err.failure_event = ev
        self._journal_append_locked(kind, {
            "ticket": ticket, "service_id": "hibernated",
            "steps": steps, "error": type(err).__name__,
            "detail": str(err)})
        self._hib_meta.pop(ticket, None)
        self._resolved[ticket] = err
        self.counter.bump("expired" if expired else "quarantined")
        # analysis: ignore[blocking-under-lock] — reclaiming the
        # resolved ticket's chain must be atomic with its resolution
        # (a racing wake could resurrect it); the vault lock is a leaf
        # and the reclaim is a few small files + one journal line
        self.tiering.drop(ticket)
        self._cv.notify_all()

    def _journal_state_fallback(self, ticket: int):
        """The wake path's last resort (the ``wake_corrupt`` ladder's
        middle rung): materialize the ticket's state from the fleet
        journal's CRC-verified submit record. None without a journal —
        the wake then fails LOUDLY (``HibernationError``). The replay
        is cached on the file's stat signature: several fallback wakes
        in one burst (a vault-wide corruption) scan and CRC the
        journal once, not once per ticket."""
        if self.journal is None:
            return None
        import os as _os

        try:
            st = _os.stat(self.journal.path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:  # pragma: no cover - defensive
            return None
        cached = self._journal_fallback_cache
        if cached is not None and cached[0] == sig:
            state = cached[1]
        else:
            try:
                state = replay(self.journal.path)
            except (OSError, ValueError):  # pragma: no cover - defensive
                return None
            # analysis: ignore[unguarded-shared-mutation] — single
            # writer by construction: fallbacks only run inside
            # tiering.wake, which serializes under the vault lock (and
            # taking the fleet lock here would invert the documented
            # _cv → vault order); a stale one-tuple read is harmless
            self._journal_fallback_cache = (sig, state)
        rec = state.submits.get(ticket)
        if rec is None or rec.arrays is None:
            return None
        warnings.warn(
            f"waking ticket {ticket} from the fleet journal's submit "
            "record (its hibernation chain did not verify)",
            RuntimeWarning)
        return space_from_record(rec)

    def _drain_hibernated(self) -> None:
        """stop()'s paging drain: every hibernated ticket wakes onto a
        live member (scheduler-level submit — an admitted ticket is
        never shed by its own drain) BEFORE the members themselves
        drain, so the final harvest resolves everything; with no live
        member left, the ticket resolves as a counted MemberFailure."""
        if self.tiering is None:
            return
        while True:
            nxt = self.tiering.peek_next()
            if nxt is None:
                return
            ticket, nbytes = nxt
            with self._cv:
                meta = self._hib_meta.get(ticket)
                if meta is None:
                    # analysis: ignore[blocking-under-lock] — orphan
                    # reclaim atomic with the bookkeeping check (see
                    # _wake_due); the vault lock is a leaf
                    self.tiering.drop(ticket)
                    continue
            model, steps, skey, submitted_at, trace = meta
            try:
                with get_tracer().attach(trace):
                    space, _entry = self.tiering.wake(
                        ticket, fallback=self._journal_state_fallback)
            except HibernationError as e:
                with self._cv:
                    self._resolve_hibernated_locked(ticket, e, steps)
                get_recorder().dump("hibernation", ticket=ticket)
                continue
            if not self._place_woken(ticket, space, model, steps, skey,
                                     submitted_at, nbytes, bypass=True,
                                     trace=trace):
                with self._cv:
                    self._resolve_hibernated_locked(
                        ticket, MemberFailure(
                            "no healthy member remains to wake "
                            f"hibernated ticket {ticket} at stop",
                            "hibernated"), steps)

    def _journal_submit_hibernated_locked(self, ticket: int, space,
                                          model, steps: int,
                                          trace=None) -> None:
        if self.journal is None:
            return
        # analysis: ignore[blocking-under-lock] — the documented
        # journal-append-under-the-fleet-lock trade (see
        # _journal_append_locked): the submit record must be ordered
        # before any terminal for this ticket, and it doubles as the
        # wake path's last-resort state source
        meta, arrays = space_payload(space)
        meta.update({
            "ticket": ticket, "service_id": "hibernated",
            "steps": steps, "model": model_meta(model)})
        if trace is not None:
            meta["trace"] = trace.to_meta()
        self._journal_append_locked(SUBMIT, meta, arrays)

    # -- autoscaling ---------------------------------------------------------

    def _autoscale_locked(self) -> Optional[tuple]:
        """Evaluate the policy; a scale-up VOTE returns the spawn
        request ``(slot, 0)`` for the tick to fulfill outside the lock
        (the hysteresis/cooldown state advances at vote time, so the
        policy is unchanged by where the spawn happens)."""
        p = self._policy
        live = [m for m in self._members.values()
                if not m.fenced and not m.dead and not m.retiring]
        n = len(live)
        if n == 0:
            return None
        depth = sum(m.service.scheduler.pending_count() for m in live)
        depth_frac = depth / (n * self._max_queue)
        shed_total = self.counter.shed
        shed_delta = shed_total - self._last_shed
        self._last_shed = shed_total
        p99 = self.counter.snapshot()["latency_p99_s"]
        gated_backlog = any(
            m.service.scheduler.intake_gated
            and m.service.scheduler.pending_count() > 0 for m in live)
        overload = (shed_delta > 0 or depth_frac >= p.depth_high
                    or gated_backlog
                    or (p.latency_p99_target_s is not None
                        and p99 is not None
                        and p99 > p.latency_p99_target_s))
        underload = (not overload and shed_delta == 0
                     and depth_frac <= p.depth_low)
        if self._cooldown > 0:
            self._cooldown -= 1
            self._up_ticks = self._down_ticks = 0
            return None
        if overload:
            self._up_ticks += 1
            self._down_ticks = 0
        elif underload:
            self._down_ticks += 1
            self._up_ticks = 0
        else:
            self._up_ticks = self._down_ticks = 0
        if self._up_ticks >= p.scale_up_after and n < p.max_services:
            self.counter.bump("scale_ups")
            self._cooldown = p.cooldown_ticks
            self._up_ticks = self._down_ticks = 0
            return (next(self._slot_ids), 0)
        if self._down_ticks >= p.scale_down_after and n > p.min_services:
            # drain-before-retire: least-loaded member stops taking
            # intake; _advance_retirements_locked migrates + removes it
            victim = min(live, key=lambda m: (
                m.service.scheduler.pending_count(), -m.slot))
            victim.retiring = True
            self._cooldown = p.cooldown_ticks
            self._up_ticks = self._down_ticks = 0
        return None

    # -- journal / recovery --------------------------------------------------

    def _journal_submit_locked(self, ticket: int, route: _Route) -> None:
        if self.journal is None:
            return
        # analysis: ignore[blocking-under-lock] — journaled admission
        # state serializes under the lock by design (the submit record
        # must be ordered before any terminal for the same ticket; see
        # _journal_append_locked for the contract and the escapes)
        meta, arrays = space_payload(route.space)
        meta.update({
            "ticket": ticket, "service_id": route.member.service_id,
            "steps": route.steps, "model": model_meta(route.model)})
        if route.trace is not None:
            # the trace id rides the submit record (ISSUE 15): the
            # offline timeline joins exported spans through it
            meta["trace"] = route.trace.to_meta()
        self._journal_append_locked(SUBMIT, meta, arrays)

    @classmethod
    def recover(cls, journal_dir: str, model, **kwargs
                ) -> "FleetSupervisor":
        """Crash-restart recovery: replay the journal's CRC-verified
        prefix and build a fresh fleet in which every journaled ticket
        is accounted for — terminal tickets resolve FROM the journal
        (served-but-unacknowledged included: their state replays, the
        scenario is never re-run; quarantines/expiries reconstruct
        their errors), unresolved tickets are re-admitted under their
        ORIGINAL ids from the journaled scenario state. Idempotent: a
        journal whose previous recovery ran to completion has nothing
        unresolved, so a second recovery re-admits nothing."""
        from ..models.model import Report

        state = replay(journal_path(journal_dir))
        fleet = cls(model, journal_dir=journal_dir, **kwargs)
        with fleet._cv:
            fleet._ids = itertools.count(state.max_ticket() + 1)
            # ISSUE 14: tickets that were HIBERNATED at the crash are
            # re-admittable from their chains exactly like journaled
            # tickets — they re-enter the hibernation tier (their
            # state stays on disk; tick() wakes them as capacity
            # frees) instead of being re-materialized here. In-flight
            # hibernations (intent journaled, chain torn) resolve at
            # wake time: verified prefix first, the fleet journal's
            # submit record second, a loud HibernationError last.
            hib = (fleet.tiering.recover(model)
                   if fleet.tiering is not None else {})
            for t, rec in state.terminal.items():
                if rec.kind == SERVED:
                    if rec.arrays is None:
                        err: Exception = MemberFailure(
                            f"ticket {t} was served before the restart "
                            "but its result state was not journaled "
                            "(journal_results=False)", "recovery")
                        err.ticket = t
                        fleet._resolved[t] = err
                        continue
                    # analysis: ignore[blocking-under-lock] — recovery
                    # replays before any client traffic exists; nothing
                    # contends with the fleet lock during the rebuild
                    sp = space_from_record(rec)
                    rep = Report(
                        comm_size=1, rank_id=0,
                        steps=rec.meta.get("steps", 0),
                        initial_total=rec.meta.get("initial_total", {}),
                        final_total=rec.meta.get("final_total", {}),
                        last_execute=[],
                        wall_time_s=rec.meta.get("wall_time_s", 0.0),
                        backend_report={
                            "recovered_from_journal": True,
                            "service_id": rec.meta.get("service_id")})
                    fleet._resolved[t] = (sp, rep)
                elif rec.kind == EXPIRED:
                    err = TicketExpired(
                        rec.meta.get("detail",
                                     f"ticket {t} expired before restart"))
                    err.ticket = t
                    fleet._resolved[t] = err
                else:
                    err = RuntimeError(
                        f"ticket {t} quarantined before restart: "
                        f"{rec.meta.get('detail', '')}")
                    err.ticket = t
                    fleet._resolved[t] = err
            for t in [t for t in hib if t in state.terminal]:
                # terminal wins: a vault entry for a ticket the fleet
                # journal already resolved is a leftover — reclaim it
                fleet.tiering.drop(t)
                hib.pop(t)
            for t in state.unresolved():
                rec = state.submits[t]
                # the journaled trace context survives the crash: the
                # post-restart spans keep the ticket's original
                # trace_id, so obs.timeline's span join still sees one
                # flight across the kill
                trace = TraceContext.from_meta(rec.meta.get("trace"))
                if t in hib:
                    e = hib[t]
                    fleet._hib_meta[t] = (
                        e.model, e.steps or rec.meta.get(
                            "steps", fleet.default_steps),
                        None, fleet._clock(), trace)
                    continue
                # analysis: ignore[blocking-under-lock] — recovery
                # replays before any client traffic exists (see above)
                sp = space_from_record(rec)
                mm = rec.meta.get("model")
                if mm is None:
                    warnings.warn(
                        f"journal submit for ticket {t} carried no "
                        "model recipe; re-admitting with the fleet "
                        "template model", RuntimeWarning)
                m_model = model_from_meta(mm, model)
                route = _Route(
                    member=None, member_ticket=-1, space=sp,
                    model=m_model, steps=rec.meta.get("steps",
                                                      fleet.default_steps),
                    submitted_at=fleet._clock(), trace=trace)
                fleet._route[t] = route
                fleet._readmit_locked(t, route, "crash-restart recovery")
        return fleet

    # -- observability -------------------------------------------------------

    def dispatch_logs(self) -> list:
        """Recent dispatch-log entries across the CURRENT members
        (fenced members' logs die with them) — the bench's donation
        audit reads this; it is a debugging window, not a ledger.
        Gathered OUTSIDE the fleet lock: a wire member's log is an
        RPC, and a debugging window must never stall submit/poll."""
        with self._cv:
            members = [m for m in self._members.values()
                       if not m.dead and not m.fenced]
        out = []
        for m in members:
            try:
                out.extend(dict(e)
                           for e in m.service.scheduler.dispatch_log)
            except WireError:  # pragma: no cover - debugging window
                self.counter.bump("wire_errors")
        return out

    def stats(self) -> dict:
        """One consistent fleet-level cut: member counters aggregated,
        fleet-observed latency percentiles, the supervision ledger
        (member_faults / readmitted / scale actions) and a per-member
        ``services`` breakdown attributable by ``service_id``."""
        with self._cv:
            members = list(self._members.values())
            snap = self.counter.snapshot()
            agg = {k: 0 for k in (
                "dispatches", "scenarios", "lanes", "cache_hits",
                "solo_retries", "recovered_failures", "quarantined",
                "impl_faults", "expired", "loop_faults")}
            per = []
            degraded_from = None
            gated = False
            # the fleet's own supervised-tick faults count beside the
            # members' pump-loop faults
            agg["loop_faults"] += snap["loop_faults"]
            # fenced/retired members' counters were absorbed at removal
            # — the work a member did before dying still counts
            busy = float(self._absorbed.get("busy_s", 0.0))
            inflight = float(self._absorbed.get("inflight_s", 0.0))
            wire_in = int(self._absorbed.get("wire_bytes_in", 0))
            wire_out = int(self._absorbed.get("wire_bytes_out", 0))
            for k in agg:
                agg[k] += self._absorbed.get(k, 0)
            for m in members:
                wire_in += int(getattr(m.service, "wire_bytes_in",
                                       0) or 0)
                wire_out += int(getattr(m.service, "wire_bytes_out",
                                        0) or 0)
                # plain counter reads (GIL-atomic ints/floats): the
                # aggregate is a statistical cut, not a transaction
                c = m.service.scheduler.counter
                for k in agg:
                    agg[k] += getattr(c, k)
                busy += c.busy_s
                inflight += c.inflight_s
                if degraded_from is None:
                    degraded_from = m.service.scheduler.degraded_from
                gated = gated or m.service.scheduler.intake_gated
                per.append({
                    "service_id": m.service_id, "slot": m.slot,
                    "gen": m.gen, "fenced": m.fenced,
                    "retiring": m.retiring, "dead": m.dead,
                    **m.service.stats()})
            return {
                **agg,
                "busy_s": busy,
                "inflight_s": inflight,
                "scenarios_per_s": (agg["scenarios"] / busy
                                    if busy > 0 else None),
                "batch_occupancy": (agg["scenarios"] / agg["lanes"]
                                    if agg["lanes"] else None),
                "compile_cache_hits": agg["cache_hits"],
                "compile_cache_hit_rate": (
                    agg["cache_hits"] / agg["dispatches"]
                    if agg["dispatches"] else None),
                "shed": snap["shed"],
                "latency_n": snap["latency_n"],
                "latency_p50_s": snap["latency_p50_s"],
                "latency_p99_s": snap["latency_p99_s"],
                "member_faults": snap["member_faults"],
                "readmitted": snap["readmitted"],
                "scale_ups": snap["scale_ups"],
                "scale_downs": snap["scale_downs"],
                # ISSUE 13 observability: the wire transport's ledger
                # (all zero for inproc fleets)
                "member_transport": self._transport,
                # ISSUE 20: supervisor identity + failover ledger
                # (anonymous supervisors: id/epoch None, counters zero)
                "supervisor_id": self._supervisor_id,
                "epoch": (self.journal.epoch
                          if self.journal is not None else None),
                "supervisor_kills": snap["supervisor_kills"],
                "stale_epoch_rejections": snap["stale_epoch_rejections"],
                "respawns": snap["respawns"],
                "heartbeats": snap["heartbeats"],
                "heartbeat_misses": snap["heartbeat_misses"],
                "wire_errors": snap["wire_errors"],
                "wire_bytes_in": wire_in,
                "wire_bytes_out": wire_out,
                # hibernated tickets are outstanding work too — a
                # client holding one must see it pending
                "pending": len(self._route) + len(self._hib_meta),
                "degraded_from": degraded_from,
                "intake_gated": gated,
                "fleet": True,
                "members": len(members),
                "journal": (self.journal.path
                            if self.journal is not None else None),
                # ISSUE 14: the paging tier's gauges + counters and
                # the per-member wake attribution (m<slot>g<gen>)
                **({"hibernations": snap["hibernations"],
                    "rehibernations": snap["rehibernations"],
                    "wakes": snap["wakes"],
                    "wake_faults": snap["wake_faults"],
                    "wake_latency_n": snap["wake_latency_n"],
                    "wake_latency_p50_s": snap["wake_latency_p50_s"],
                    "wake_latency_p99_s": snap["wake_latency_p99_s"],
                    "wakes_by_member": dict(self._wakes_by_member),
                    **self.tiering.stats()}
                   if self.tiering is not None else {}),
                "services": per,
            }


class StandbySupervisor:
    """The failover watcher (ISSUE 20): tails a fleet's journal
    directory — ``supervisor.lease`` plus the TJ1 journal — WITHOUT
    owning any member, and takes the fleet over when the active
    supervisor's lease goes stale.

    The protocol, end to end:

    1. The ACTIVE (named) supervisor re-stamps the lease every
       supervision tick on the SHARED clock (``time.monotonic`` is
       host-wide on Linux, so same-host processes compare directly;
       fake-clock tests inject one clock into both sides).
    2. The standby polls ``should_takeover()``: the lease's age
       exceeding its own ``lease_s`` (or the lease vanishing under an
       existing journal) means the active stopped ticking — dead,
       wedged, or partitioned; all three read the same and all three
       are grounds to fence it.
    3. ``takeover()`` runs ``FleetSupervisor.recover`` under THIS
       standby's ``supervisor_id``: the new fleet declares journal
       epoch N+1 (fence file first, EPOCH record second), re-admits
       every unresolved ticket exactly once, and stamps its frames
       with the new epoch.
    4. The OLD supervisor, if it was merely wedged and wakes up a
       zombie, is fenced twice over: its journal appends raise
       :class:`~.journal.StaleEpochError` (writing nothing) and its
       member RPCs come back ``err`` — it can corrupt neither the
       ledger nor the members.

    ``lease_s=None`` (the default) honors the lease's OWN advertised
    ``lease_s`` — the active supervisor declares how fast it promises
    to tick; pass a float to override the staleness bound."""

    def __init__(self, journal_dir: str, model, *,
                 supervisor_id: str,
                 lease_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 **fleet_kwargs):
        self.journal_dir = journal_dir
        self.model = model
        self.supervisor_id = supervisor_id
        self._lease_s = lease_s
        self._clock = clock
        self._fleet_kwargs = dict(fleet_kwargs)
        #: the fleet built by takeover(), None while standing by
        self.fleet: Optional[FleetSupervisor] = None

    def lease(self) -> Optional[dict]:
        return read_lease(lease_path(self.journal_dir))

    def lease_age(self) -> Optional[float]:
        """Seconds since the active's last renewal, or None when no
        lease file exists (never written, or deleted)."""
        rec = self.lease()
        if rec is None or not isinstance(rec.get("t"), (int, float)):
            return None
        return self._clock() - rec["t"]

    def should_takeover(self) -> bool:
        if self.fleet is not None:
            return False  # already took over
        age = self.lease_age()
        if age is None:
            # no lease at all: a journal without one means a PRE-lease
            # supervisor (or a crash before the first stamp) — claim
            # it; no journal means there is nothing to supervise yet
            return os.path.exists(journal_path(self.journal_dir))
        rec = self.lease() or {}
        bound = self._lease_s
        if bound is None:
            bound = rec.get("lease_s") or 2.0
        return age > bound

    def takeover(self) -> FleetSupervisor:
        """Fence the stale active and become THE supervisor: recover
        the fleet from the journal under this standby's id — epoch
        N+1 is declared before any member spawns, so the zombie is
        fenced from the first instant of the new generation."""
        prev = self.lease() or {}
        self.fleet = FleetSupervisor.recover(
            self.journal_dir, self.model,
            supervisor_id=self.supervisor_id,
            takeover_from=prev.get("owner"),
            clock=self._clock,
            **({"lease_s": self._lease_s}
               if self._lease_s is not None else {}),
            **self._fleet_kwargs)
        return self.fleet

    def poll(self) -> Optional[FleetSupervisor]:
        """One standby beat: take over iff the lease is stale. Call it
        from a timer/loop; returns the new fleet on the beat that
        fired, else None."""
        if self.should_takeover():
            return self.takeover()
        return None
