"""Ensemble engine: batched multi-scenario serving with a bucketed
compile cache.

- ``batch``     — EnsembleSpace (stacked SoA pytree, leading batch axis),
                  the vmapped parametric step, per-scenario conservation,
                  EnsembleExecutor (impl="xla" | "pipeline");
- ``mesh``      — the (batch × space) device mesh layer (ISSUE 16):
                  ``EnsembleMesh`` placement contract for ``[B,H,W]``
                  SoA channels and ``[B,F]`` parameter lanes, the
                  pad-to-(bucket × mesh) round-up, and the wire-safe
                  ``(batch, space)`` spec ``resolve_ensemble_mesh``
                  rebuilds against a member's local devices;
- ``scheduler`` — scenario queue with bucketed batching (pad to bucket,
                  max-wait/max-batch flush, runner cache + hit counters,
                  thread-safe launch/complete dispatch phases, ticket
                  deadlines, retry budgets, the health-gated ladder);
- ``service``   — submit/poll facades: the synchronous
                  ``EnsembleService`` and the always-on
                  ``AsyncEnsembleService`` dispatch loop (ISSUE 9:
                  double-buffered launch/finish, bounded admission with
                  ``ServiceOverloaded`` shedding, donated inter-window
                  state), plus the ``run_soak`` open-loop driver;
- ``fleet``     — the ``FleetSupervisor`` (ISSUE 10): one arrival
                  stream sharded over N async members with
                  structure-affine routing, autoscaling, failure-domain
                  isolation (fence + restart + re-admit) and
                  crash-restart ticket recovery;
- ``journal``   — the append-only CRC'd ticket journal behind
                  ``FleetSupervisor.recover``, also a standalone
                  inspection CLI (``python -m
                  mpi_model_tpu.ensemble.journal <dir>``);
- ``wire``      — the TJ1 record format promoted to a socket codec
                  (ISSUE 13): length-prefixed CRC-framed messages,
                  typed errors, per-RPC deadlines;
- ``member_proc`` — fleet members as separate OS processes behind the
                  wire protocol (``FleetSupervisor(member_transport=
                  "process")``): worker entrypoint, supervisor-side
                  client proxy, real-process and in-memory-loopback
                  spawners;
- ``tiering``   — scenario hibernate/wake paging (ISSUE 14):
                  ``ScenarioTiering`` pages idle scenarios to
                  keyframe+delta chains (PR 6 format) with a TJ1
                  lifecycle journal, behind
                  ``AsyncEnsembleService(residency_budget=,
                  hibernate_dir=)`` / ``FleetSupervisor(...)`` —
                  overload degrades to bounded wake latency instead of
                  sheds.

See docs/DESIGN.md "Ensemble serving" / "Always-on serving" / "Fleet
supervision" for why the batch axis sits OUTSIDE the mesh axes and how
the loop overlaps host assembly with device compute.
"""

from .batch import (
    EnsembleConservationError,
    EnsembleExecutor,
    EnsembleInFlight,
    EnsembleSpace,
    complete_ensemble,
    launch_ensemble,
    run_ensemble,
    structure_key,
)
from .fleet import AutoscalePolicy, FleetSupervisor, MemberFailure
from .journal import TicketJournal
from .mesh import EnsembleMesh, make_ensemble_mesh, resolve_ensemble_mesh
from .scheduler import (DEFAULT_BUCKETS, DispatchTimeout,
                        EnsembleScheduler, TicketExpired,
                        TicketNotMigratable, buckets_for)
from .service import (AsyncEnsembleService, EnsembleService,
                      ServiceOverloaded, run_soak)
from .tiering import (HibernationError, ScenarioTiering,
                      scenario_nbytes)
from .wire import FrameConn, RemoteError, WireClosed, WireError, WireTimeout

__all__ = [
    "AsyncEnsembleService",
    "AutoscalePolicy",
    "DispatchTimeout",
    "FleetSupervisor",
    "MemberFailure",
    "TicketJournal",
    "TicketNotMigratable",
    "EnsembleConservationError",
    "EnsembleExecutor",
    "EnsembleInFlight",
    "EnsembleMesh",
    "EnsembleScheduler",
    "EnsembleService",
    "EnsembleSpace",
    "ServiceOverloaded",
    "TicketExpired",
    "HibernationError",
    "ScenarioTiering",
    "scenario_nbytes",
    "FrameConn",
    "RemoteError",
    "WireClosed",
    "WireError",
    "WireTimeout",
    "DEFAULT_BUCKETS",
    "buckets_for",
    "complete_ensemble",
    "launch_ensemble",
    "make_ensemble_mesh",
    "resolve_ensemble_mesh",
    "run_ensemble",
    "run_soak",
    "structure_key",
]
