"""Ensemble engine: batched multi-scenario serving with a bucketed
compile cache.

- ``batch``     — EnsembleSpace (stacked SoA pytree, leading batch axis),
                  the vmapped parametric step, per-scenario conservation,
                  EnsembleExecutor (impl="xla" | "pipeline");
- ``scheduler`` — scenario queue with bucketed batching (pad to bucket,
                  max-wait/max-batch flush, runner cache + hit counters);
- ``service``   — submit/poll facade with throughput counters.

See docs/DESIGN.md "Ensemble serving" for why the batch axis sits
OUTSIDE the mesh axes.
"""

from .batch import (
    EnsembleConservationError,
    EnsembleExecutor,
    EnsembleSpace,
    run_ensemble,
    structure_key,
)
from .scheduler import (DEFAULT_BUCKETS, DispatchTimeout,
                        EnsembleScheduler, buckets_for)
from .service import EnsembleService

__all__ = [
    "DispatchTimeout",
    "EnsembleConservationError",
    "EnsembleExecutor",
    "EnsembleScheduler",
    "EnsembleService",
    "EnsembleSpace",
    "DEFAULT_BUCKETS",
    "buckets_for",
    "run_ensemble",
    "structure_key",
]
