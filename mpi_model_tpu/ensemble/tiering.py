"""Scenario tiering: hibernate/wake paging through the delta stream
(ISSUE 14 tentpole).

"Millions of users" means orders of magnitude more live scenarios than
fit in device/host memory, and until this PR the serving stack's only
pressure valve was refusal: a full admission queue raised
``ServiceOverloaded`` and the scenario was gone. This module gives the
stack a second tier — scenarios that do not fit the configured
residency budget HIBERNATE to disk and WAKE when capacity frees — so
overload degrades to bounded latency instead of sheds.

The format is deliberately nothing new (the one-format discipline):

- **State** pages through the PR 6 delta stream: each hibernated
  scenario owns a :class:`io.delta.DeltaChain` in the vault directory
  (``t<ticket>/hib_*``) — the first hibernation writes a keyframe, a
  re-hibernation of the same (unchanged, still-queued) scenario writes
  a dirty-tile delta with ZERO dirty tiles, so paging a scenario out
  again costs metadata, not state bytes. Every piece is CRC32'd; a
  restore replays keyframe→deltas exactly like a checkpoint restore.
- **Lifecycle metadata** rides a PR 10 TJ1 ticket journal
  (``hibernation.journal``): ``hibernate`` (intent — ticket, chain
  seq, steps, the model's wire recipe) before the chain write,
  ``hibernated`` (commit — seq, disk bytes) after it, ``wake`` and
  ``reclaim`` on the way back. The journal reader stops at the first
  unverifiable byte, so a crash costs exactly the torn suffix.

Crash contract (what :meth:`ScenarioTiering.recover` restores):

- intent + commit, no wake → the scenario is hibernated; it wakes from
  its chain (restore walks back to the newest record that VERIFIES —
  for a queued scenario every chain record is the same bytes, so the
  verified-prefix fallback is bitwise-exact, never stale).
- intent WITHOUT commit (the in-flight hibernation a crash interrupts)
  → the chain's newest record may be torn; the wake walks back to the
  previous committed record, falls back to the caller-supplied journal
  source (the fleet's submit record), or raises
  :class:`HibernationError` — NEVER a silent fresh start.
- wake after the last hibernate → the scenario was resident at the
  crash; the fleet journal's unresolved-submit replay owns it.

The residency policy is LRU over the RESIDENT set: ``admit`` and
``touch`` (submit/poll) refresh a ticket's recency, and
``lru_candidates`` hands the admission path its page-out victims
oldest-first. The hibernated tier wakes FIFO (arrival order), so no
scenario starves and wake latency stays bounded by queue position.
``ServiceOverloaded`` fires only when the hibernation tier itself is
exhausted (``hibernate_budget``).

Chaos seams (``resilience.inject`` discipline — one global read when
disarmed): ``hibernate_torn`` tears the chain record a hibernation
just wrote (silently, like a real torn write), ``wake_corrupt``
damages the newest record before a restore, ``residency_pressure``
forces the paging path without real memory pressure.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import shutil
import time
import warnings
from typing import Callable, Optional

import numpy as np

from ..core.cellular_space import CellularSpace
from ..io.checkpoint import CheckpointCorruptionError
from ..io.delta import DeltaChain
from ..obs.flight import get_recorder
from ..resilience import inject, lockdep
from ..utils.metrics import ThroughputCounter
from ..utils.tracing import get_tracer
from .journal import TicketJournal, model_from_meta, model_meta, read_records
from .lifecycle import HIBERNATE, HIBERNATED, RECLAIM, REQUEUE, TIERING, WAKE

__all__ = ["HibernationError", "HibernatedScenario", "ScenarioTiering",
           "scenario_nbytes"]

#: the TJ1 lifecycle journal inside a vault directory — the basename is
#: the DECLARED machine's (``lifecycle.TIERING``): it is how the
#: protocol witness maps this stream back to its lifecycle
HIBERNATE_JOURNAL = TIERING.journal_name
#: chain file prefix inside each per-ticket chain directory
CHAIN_PREFIX = "hib"


class HibernationError(RuntimeError):
    """A hibernated scenario could not be woken: no chain record
    verified AND no journal fallback held its state. The ticket
    resolves with THIS error (a complete, observable outcome) — the
    tiering layer never hands back fresh or wrong state pretending it
    is the scenario."""


def scenario_nbytes(space: CellularSpace) -> int:
    """Resident byte cost of one scenario's channel state — what the
    residency budget meters."""
    return int(sum(int(v.nbytes) for v in space.values.values()))


@dataclasses.dataclass
class HibernatedScenario:
    """One paged-out scenario: everything needed to wake it except the
    state itself (that lives in its chain / the journal)."""

    ticket: int
    steps: int
    #: the live model object (exact wake within this process); after a
    #: crash-restart recovery it is rebuilt from the journaled wire
    #: recipe (``model_meta``), falling back to the template
    model: object
    nbytes: int
    #: newest chain seq written for this ticket (committed, or the
    #: in-flight intent a crash interrupted — the wake walks back)
    seq: int
    submitted_at: float
    hibernated_at: float
    #: structure key for affinity placement on wake (None after
    #: recovery — recomputed from the restored state)
    skey: Optional[tuple] = None
    #: bytes this ticket's chain holds on disk
    disk_bytes: int = 0


class ScenarioTiering:
    """The hibernate/wake paging engine (module docstring). One
    instance per serving facade (``AsyncEnsembleService`` /
    ``FleetSupervisor``); thread-safe behind a single lock —
    hibernations and wakes serialize against each other (per-stream
    journal ordering: intent before commit before wake), but never
    against the caller's admission lock, which this class must not be
    called under while it does I/O."""

    def __init__(self, directory: str, *, residency_budget: int,
                 hibernate_budget: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 counter: Optional[ThroughputCounter] = None,
                 keyframe_every: int = 8):
        if residency_budget < 1:
            raise ValueError(
                f"residency_budget={residency_budget} must be >= 1 byte")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.residency_budget = int(residency_budget)
        #: on-disk budget of the hibernation tier (None = unbounded);
        #: when THIS is exhausted the caller sheds — the only refusal
        #: left once paging is on
        self.hibernate_budget = (None if hibernate_budget is None
                                 else int(hibernate_budget))
        self.keyframe_every = int(keyframe_every)
        self._clock = clock
        self.counter = counter if counter is not None else ThroughputCounter()
        #: THE tiering lock: tables + the journal/chain write ordering.
        #: A leaf of the serving stack's acquisition graph (nothing is
        #: acquired under it).
        self._lock = lockdep.lock("ScenarioTiering._lock")
        #: ticket → resident nbytes, in LRU order (oldest first)
        self._resident: collections.OrderedDict = collections.OrderedDict()
        self._resident_bytes = 0
        #: ticket → HibernatedScenario, in FIFO wake order
        self._hibernated: collections.OrderedDict = collections.OrderedDict()
        self._hibernated_bytes = 0
        #: per-ticket chain handles — kept alive across wake so a
        #: re-hibernation in this process writes a delta, not a keyframe
        self._chains: dict = {}
        self._next_seq: dict = {}
        self.journal = TicketJournal(
            os.path.join(directory, HIBERNATE_JOURNAL))

    # -- residency accounting (LRU over the resident set) -------------------

    def admit(self, ticket: int, nbytes: int) -> None:
        """Track one scenario as RESIDENT (submitted or woken)."""
        with self._lock:
            if ticket not in self._resident:
                self._resident_bytes += int(nbytes)
            self._resident[ticket] = int(nbytes)
            self._resident.move_to_end(ticket)

    def touch(self, ticket: int) -> None:
        """LRU refresh: the client showed interest (poll) — a recently
        polled scenario is a bad page-out victim."""
        with self._lock:
            if ticket in self._resident:
                self._resident.move_to_end(ticket)

    def fits(self, nbytes: int) -> bool:
        with self._lock:
            return self._resident_bytes + int(nbytes) \
                <= self.residency_budget

    def pressure(self, nbytes: int) -> Optional[str]:
        """Why this admission must PAGE, or None: ``"injected"`` (an
        armed ``residency_pressure`` fault — the paging path must run
        even though the budget would fit, so the page-out shortcut is
        skipped) or ``"budget"`` (the residency budget cannot take the
        scenario)."""
        st = inject.active()
        if st is not None and st.take(
                "tiering", st.bump("tiering"),
                kinds=("residency_pressure",)) is not None:
            return "injected"
        return None if self.fits(nbytes) else "budget"

    def room_for(self, nbytes: int) -> bool:
        """Does the hibernation tier have disk budget for ~one more
        keyframe of this size? (The upper bound — a re-hibernation
        writes a near-empty delta.)"""
        if self.hibernate_budget is None:
            return True
        with self._lock:
            return self._hibernated_bytes + int(nbytes) \
                <= self.hibernate_budget

    def lru_candidates(self) -> list:
        """Resident tickets in LRU order (oldest-touched first) — the
        page-out victim preference."""
        with self._lock:
            return list(self._resident)

    def release(self, ticket: int) -> None:
        """The ticket resolved: free its residency and reclaim its
        chain (if it ever hibernated)."""
        with self._lock:
            n = self._resident.pop(ticket, None)
            if n is not None:
                self._resident_bytes -= n
            self._reclaim_locked(ticket)

    def drop(self, ticket: int) -> None:
        """Resolve a ticket that is still HIBERNATED without waking it
        (deadline expiry, an unwakeable chain): forget the entry and
        reclaim the chain. The caller owns publishing the outcome."""
        with self._lock:
            e = self._hibernated.pop(ticket, None)
            if e is not None:
                self._hibernated_bytes -= e.disk_bytes
            self._reclaim_locked(ticket)

    def _reclaim_locked(self, ticket: int) -> None:
        chain = self._chains.pop(ticket, None)
        self._next_seq.pop(ticket, None)
        e = self._hibernated.pop(ticket, None)
        if e is not None:
            self._hibernated_bytes -= e.disk_bytes
        d = self._chain_dir(ticket)
        if chain is None and not os.path.isdir(d):
            return
        self._append_locked(RECLAIM, {"ticket": ticket})
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    # -- the paging primitives ----------------------------------------------

    def _chain_dir(self, ticket: int) -> str:
        return os.path.join(self.directory, f"t{int(ticket):08d}")

    def _chain_for_locked(self, ticket: int) -> DeltaChain:
        chain = self._chains.get(ticket)
        if chain is None:
            chain = DeltaChain(self._chain_dir(ticket),
                               prefix=CHAIN_PREFIX,
                               keyframe_every=self.keyframe_every)
            self._chains[ticket] = chain
        return chain

    def _append_locked(self, kind: str, meta: dict) -> None:
        try:
            # analysis: ignore[blocking-under-lock] — the tiering
            # journal's per-ticket record ordering (intent before
            # commit before wake) is exactly what this lock provides;
            # same documented trade as the fleet journal's appends
            self.journal.append(kind, meta)
        except (OSError, ValueError) as e:
            self.counter.bump("loop_faults")
            warnings.warn(
                f"hibernation journal append ({kind}) failed: {e} — "
                "paging continues; crash-restart recovery degrades to "
                "the fleet journal for whatever this record described",
                RuntimeWarning)

    def hibernate(self, ticket: int, space: CellularSpace, model,
                  steps: int, *, submitted_at: Optional[float] = None,
                  skey: Optional[tuple] = None) -> HibernatedScenario:
        """Page one scenario out: journal the intent (with the model's
        wire recipe), write the chain record (keyframe first time,
        near-empty delta on re-hibernation), journal the commit. The
        in-memory state reference is the caller's to drop — after this
        returns, the chain + journal ARE the scenario."""
        nbytes = scenario_nbytes(space)
        # the hibernate span (ISSUE 15) parents under whatever context
        # the caller attached (the ticket's submit span), so paging
        # shows up inside the ticket's trace, not as orphan noise
        with self._lock, get_tracer().span(
                "tiering.hibernate", ticket=int(ticket)) as sm:
            if ticket in self._hibernated:
                raise ValueError(f"ticket {ticket} is already hibernated")
            seq = self._next_seq.get(ticket, 0)
            rehib = seq > 0
            self._append_locked(HIBERNATE, {
                "ticket": int(ticket), "seq": seq, "steps": int(steps),
                "nbytes": nbytes, "model": model_meta(model)})
            chain = self._chain_for_locked(ticket)
            # analysis: ignore[blocking-under-lock] — the chain write
            # must land between this ticket's intent and commit journal
            # records (the crash contract recover() replays); paging
            # I/O serializes against other paging I/O only — the
            # caller's admission lock is never held here
            path = chain.save(space, seq)
            inject.hibernate_torn(path, seq)
            self._next_seq[ticket] = seq + 1
            disk = self._dir_bytes(ticket)
            self._append_locked(HIBERNATED, {
                "ticket": int(ticket), "seq": seq, "disk_bytes": disk})
            now = self._clock()
            entry = HibernatedScenario(
                ticket=int(ticket), steps=int(steps), model=model,
                nbytes=nbytes, seq=seq,
                submitted_at=(now if submitted_at is None
                              else float(submitted_at)),
                hibernated_at=now, skey=skey, disk_bytes=disk)
            self._hibernated[ticket] = entry
            self._hibernated_bytes += disk
            # a hibernated ticket is no longer resident
            n = self._resident.pop(ticket, None)
            if n is not None:
                self._resident_bytes -= n
            sm["seq"] = seq
            sm["rehibernation"] = rehib
        self.counter.bump("hibernations")
        if rehib:
            self.counter.bump("rehibernations")
        get_recorder().record("hibernate", ticket=int(ticket),
                              seq=seq, rehibernation=rehib)
        return entry

    def is_hibernated(self, ticket: int) -> bool:
        with self._lock:
            return ticket in self._hibernated

    def hibernated_count(self) -> int:
        with self._lock:
            return len(self._hibernated)

    def peek_next(self) -> Optional[tuple]:
        """(ticket, nbytes) of the next FIFO wake candidate, or None."""
        with self._lock:
            for t, e in self._hibernated.items():
                return t, e.nbytes
            return None

    def entry(self, ticket: int) -> Optional[HibernatedScenario]:
        with self._lock:
            return self._hibernated.get(ticket)

    def wake(self, ticket: int,
             fallback: Optional[Callable] = None
             ) -> tuple[CellularSpace, HibernatedScenario]:
        """Materialize one hibernated scenario: restore the newest
        chain record that VERIFIES (walking back through the chain — a
        torn/corrupt newest record costs nothing for a queued scenario,
        every record is the same bytes), else ``fallback(ticket)`` (the
        fleet journal's submit-record source), else raise
        :class:`HibernationError`. On success the entry leaves the
        hibernated tier (the chain stays on disk until the ticket
        resolves — it is the re-hibernation base and the crash source);
        on failure it stays for the caller to ``drop`` after publishing
        the error. Wall seconds of the materialization feed the
        wake-latency reservoir."""
        # analysis: ignore[naked-timer] — the wake-latency reservoir's
        # anchor: wake p50/p99 must stay REAL wall seconds even under
        # a fake scheduler clock, and the reservoir (not a span
        # rollup) is what stats()/bench publish
        t0 = time.perf_counter()
        # the wake-restore span (ISSUE 15): parents under the ticket's
        # submit-span context (the fleet attaches it), so the restore
        # cost is visible inside the ticket's own trace
        with self._lock, get_tracer().span(
                "tiering.wake", ticket=int(ticket)) as sm:
            e = self._hibernated.get(ticket)
            if e is None:
                raise KeyError(f"ticket {ticket} is not hibernated")
            fault = inject.wake_corrupt(ticket)
            chain = self._chain_for_locked(ticket)
            if fault is not None:
                self._corrupt_newest_locked(ticket, chain, fault)
            space = None
            source = None
            last_err: Optional[Exception] = None
            # analysis: ignore[blocking-under-lock] — the wake's chain
            # walk (manifest read + restore) IS the paging tier's I/O;
            # it serializes only against other paging operations — the
            # caller's admission lock is never held across wake
            for s in sorted(chain.steps(), reverse=True):
                try:
                    # analysis: ignore[blocking-under-lock] — the wake
                    # restore is the paging tier's I/O; it serializes
                    # only against other paging I/O (the caller's
                    # admission lock is never held across wake)
                    ck = chain.restore(s)
                except (CheckpointCorruptionError, FileNotFoundError) as ex:
                    last_err = ex
                    continue
                space, source = ck.space, f"chain:{s}"
                break
            if space is None and last_err is not None:
                warnings.warn(
                    f"wake of ticket {ticket}: no chain record verified "
                    f"({last_err}); falling back to the journal source",
                    RuntimeWarning)
            if space is None and fallback is not None:
                space = fallback(ticket)
                source = "journal"
            if space is None:
                self.counter.bump("wake_faults")
                raise HibernationError(
                    f"ticket {ticket} cannot wake: no chain record "
                    f"verified ({last_err}) and no journal source holds "
                    "its state — resolving loudly instead of resuming "
                    "fresh or wrong state")
            if source == "journal":
                self.counter.bump("wake_faults")
            self._append_locked(WAKE, {
                "ticket": int(ticket), "seq": e.seq, "source": source})
            self._hibernated.pop(ticket)
            self._hibernated_bytes -= e.disk_bytes
            sm["source"] = source
        self.counter.bump("wakes")
        # analysis: ignore[naked-timer] — closes the reservoir anchor
        self.counter.record_wake_latency(time.perf_counter() - t0)
        get_recorder().record("wake", ticket=int(ticket), source=source)
        return space, e

    def requeue(self, ticket: int, entry: HibernatedScenario) -> None:
        """A woken scenario found no placement (every member refused
        mid-wake): put it back at the HEAD of the wake queue without
        rewriting its chain (the state on disk is unchanged). The
        journal records the round trip so recovery still sees it
        hibernated."""
        with self._lock:
            self._append_locked(REQUEUE, {
                "ticket": int(ticket), "seq": entry.seq})
            self._hibernated[ticket] = entry
            self._hibernated.move_to_end(ticket, last=False)
            self._hibernated_bytes += entry.disk_bytes

    def _corrupt_newest_locked(self, ticket: int, chain: DeltaChain,
                               fault) -> None:
        # analysis: ignore[blocking-under-lock] — chaos-only path (an
        # armed wake_corrupt fault): damages the chain under the same
        # vault lock the wake it targets holds, by design
        steps = chain.steps()
        if not steps:
            return
        for kind in ("delta", "keyframe"):
            p = chain.record_path(max(steps), kind)
            if os.path.exists(p):
                inject.tear_file(p, fault.offset, fault.nbytes,
                                 fault.tear)
                return

    def _dir_bytes(self, ticket: int) -> int:
        d = self._chain_dir(ticket)
        total = 0
        for fn in os.listdir(d):
            try:
                total += os.path.getsize(os.path.join(d, fn))
            except OSError:  # pragma: no cover - racing reclaim
                continue
        return total

    # -- crash-restart recovery ----------------------------------------------

    def recover(self, template_model=None) -> dict:
        """Fold the vault journal's verified prefix to the set of
        tickets that were HIBERNATED at the crash (module docstring has
        the contract) and re-enter them in the in-memory tier, FIFO
        order preserved. Models rebuild from their journaled wire
        recipes (``template_model`` when a recipe was absent). Returns
        ticket → entry; in-flight hibernations (intent, no commit) are
        included — their wake walks the chain back or falls through to
        the caller's journal source."""
        records, torn = read_records(self.journal.path)
        if torn:
            warnings.warn(
                f"hibernation journal {self.journal.path} had a torn "
                "tail — recovered the verified prefix",
                RuntimeWarning)
        # the fold consumes the DECLARED machine (lifecycle.TIERING)
        # instead of hand-rolled kind literals: each record advances its
        # ticket to the transition's declared target state, and a ticket
        # is recoverable here iff it ended the prefix on the hibernate
        # side of the machine (intent or commit — not resident).
        state: dict = {}
        for rec in records:
            t = rec.meta.get("ticket")
            tr = TIERING.transition(rec.kind)
            if t is None or tr is None:
                continue
            if tr.terminal:
                state.pop(t, None)
            elif rec.kind == HIBERNATE:
                state[t] = {"meta": rec.meta, "seq": rec.meta["seq"],
                            "committed": False, "state": tr.target,
                            "order": rec.index}
            elif t in state:
                if rec.kind == HIBERNATED:
                    state[t]["committed"] = True
                    state[t]["disk"] = rec.meta.get("disk_bytes", 0)
                state[t]["state"] = tr.target
        out: dict = {}
        now = self._clock()
        with self._lock:
            for t, st in sorted(state.items(),
                                key=lambda kv: kv[1]["order"]):
                if st["state"] not in ("hibernating", "hibernated"):
                    continue
                meta = st["meta"]
                model = model_from_meta(meta.get("model"), template_model)
                if model is None:
                    warnings.warn(
                        f"hibernated ticket {t} has no model recipe and "
                        "no template — it cannot be recovered here",
                        RuntimeWarning)
                    continue
                disk = (st.get("disk") if st["committed"]
                        else None)
                if disk is None:
                    disk = (self._dir_bytes(t)
                            if os.path.isdir(self._chain_dir(t)) else 0)
                e = HibernatedScenario(
                    ticket=int(t), steps=int(meta.get("steps", 0)),
                    model=model, nbytes=int(meta.get("nbytes", 0)),
                    seq=int(st["seq"]), submitted_at=now,
                    hibernated_at=now, skey=None, disk_bytes=int(disk))
                self._hibernated[t] = e
                self._hibernated_bytes += e.disk_bytes
                self._next_seq[t] = e.seq + 1
                out[t] = e
            # orphan sweep: a ticket whose LAST lifecycle record was a
            # wake was resident at the crash — the fleet journal owns
            # its recovery, but its chain directory would otherwise
            # leak on disk forever (and never count against the
            # hibernate budget). Reclaim every vault dir without a
            # recovered entry; a later re-hibernation of the same
            # ticket starts a fresh chain at seq 0.
            for fn in os.listdir(self.directory):
                if not (fn.startswith("t") and fn[1:].isdigit()):
                    continue
                t = int(fn[1:])
                if t in self._hibernated:
                    continue
                self._append_locked(RECLAIM, {"ticket": t})
                shutil.rmtree(os.path.join(self.directory, fn),
                              ignore_errors=True)
                self._next_seq.pop(t, None)
        return out

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "resident_scenarios": len(self._resident),
                "resident_bytes": self._resident_bytes,
                "residency_budget": self.residency_budget,
                "hibernated_scenarios": len(self._hibernated),
                "hibernated_bytes": self._hibernated_bytes,
                "hibernate_budget": self.hibernate_budget,
            }

    def close(self) -> None:
        self.journal.close()
