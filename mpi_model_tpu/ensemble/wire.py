"""Wire protocol for fleet members — the TJ1 record format promoted to
a message codec (ISSUE 13 tentpole, layer 1).

The ticket journal already serializes every message the fleet
exchanges: a ``submit`` record carries the full scenario state plus the
model recipe, a ``served`` record carries the harvested state, both
CRC-framed. This module lifts that format out of the journal file and
onto a socket, so a fleet member can live in ANOTHER PROCESS (see
``ensemble.member_proc``) while the supervisor keeps speaking the same
payloads it journals.

Frame format (the TJ1 discipline, distinct magic)::

    b"TW1 <len:08x> <crc:08x>\\n" + payload + b"\\n"

where the CRC32 covers the whole payload. A payload is the message's
JSON metadata (which must carry ``kind``), optionally followed by
``b"\\x00"`` and a raw binary blob whose slices are described — with
their OWN per-array CRC32s — by the metadata's ``arrays`` table. The
journal imports :func:`encode_payload`/:func:`parse_payload` from here,
so a journal record and a wire message are byte-compatible payloads
with different envelopes (file offset vs socket frame).

Hard rules, all typed and all tested (``tests/test_wire.py``):

- a torn, short, oversized or CRC-failing frame raises
  :class:`WireError` — NEVER a partial apply, never a hang;
- a peer that closes mid-frame raises :class:`WireClosed`;
- every receive carries a deadline: silence past it raises
  :class:`WireTimeout`. The fleet classifies any of the three as a
  MEMBER fault (fence, respawn, recover tickets from the journal) —
  a broken wire is a dead machine, not a dead ticket.

Since ISSUE 20 the codec also rides TCP: :func:`tcp_listener`/
:func:`tcp_dial` put the SAME frames on a network socket, gated by a
mutual HMAC-SHA256 challenge–response at accept
(:func:`serve_handshake`/:func:`client_handshake`, shared secret via
the :data:`SECRET_ENV` child-env contract) — a wrong secret, a
truncated exchange or a peer slower than :data:`HANDSHAKE_DEADLINE_S`
raises :class:`HandshakeError` and closes the socket BEFORE any frame
is parsed. TCP deadlines are retuned for network jitter
(:data:`TCP_HEARTBEAT_DEADLINE_S`/:data:`TCP_RPC_DEADLINE_S`).

Chaos (``resilience.inject``): ``wire_torn`` tears/corrupts one
outgoing frame at this seam — ``tear="corrupt"`` flips bytes so the
receiver's CRC check fires immediately; ``tear="truncate"`` sends the
frame's prefix and CLOSES the connection (the realistic
crash-mid-write shape), so the receiver sees ``WireClosed``, not an
unbounded wait. ``tcp_partition`` makes one send/recv behave as a
network partition (conn closed, ``WireTimeout``); ``handshake_fail``
garbles one handshake proof so the peer must refuse. Every seam costs
one module-global read when disarmed.

This module's socket use is a deliberate BOUNDARY: the
``raw-transport`` analysis rule flags raw ``socket``/``subprocess``
calls anywhere else in the package, so every byte that crosses a
process boundary flows through this codec (and is therefore
CRC-checked and deadline-bounded).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import json
import socket as _socket
import time
import zlib
from typing import Optional

import numpy as np

from ..resilience import inject

__all__ = [
    "WireError",
    "WireTimeout",
    "WireClosed",
    "HandshakeError",
    "RemoteError",
    "FrameConn",
    "encode_payload",
    "parse_payload",
    "frame",
    "serve_handshake",
    "client_handshake",
    "tcp_listener",
    "tcp_dial",
    "REQUEST_KINDS",
    "REPLY_KINDS",
    "MAX_FRAME_BYTES",
    "TRACE_META_KEY",
    "SECRET_ENV",
    "HANDSHAKE_DEADLINE_S",
    "TCP_HEARTBEAT_DEADLINE_S",
    "TCP_RPC_DEADLINE_S",
]

_MAGIC = b"TW1 "
_HEADER_LEN = 22  # b"TW1 " + 8 hex + b" " + 8 hex + b"\n"

#: refuse to allocate for an absurd declared length (a corrupt header
#: must fail as a typed error, not an OOM): 1 GiB bounds any realistic
#: scenario-state payload by orders of magnitude
MAX_FRAME_BYTES = 1 << 30

#: the member RPC vocabulary (supervisor → member); every request gets
#: exactly one reply frame. (A ``stats`` RPC existed once but nothing
#: ever sent it — member stats ride the heartbeat telemetry cut so the
#: fleet's ``stats()`` never blocks on a wire; the layer-4
#: ``rpc-asymmetry`` rule is what keeps this tuple honest now.)
REQUEST_KINDS = ("submit", "poll", "migrate", "queued", "pump", "drain",
                 "dispatch_log", "heartbeat", "shutdown")
#: reply kinds (member → supervisor)
REPLY_KINDS = ("ok", "pending", "overloaded", "err")

#: the meta key a submit frame carries its trace context under
#: (ISSUE 15): ``{"trace_id": ..., "span_id": ...}`` —
#: ``utils.tracing.TraceContext.to_meta``. The member attaches it
#: before admitting, so member-side dispatch spans parent under the
#: fleet-side submit span ACROSS the process boundary; heartbeat
#: replies ship the member's completed-span deltas back under
#: ``telemetry["spans"]`` on the same frames.
TRACE_META_KEY = "trace"


class WireError(ValueError):
    """A frame failed to parse or verify (bad magic, short read,
    oversized length, payload CRC mismatch, per-array CRC mismatch,
    malformed metadata). The connection is UNSYNCHRONIZED after this —
    the fleet treats it as a member fault, never retries the stream.
    (A ``ValueError`` subclass so the journal reader's
    truncate-to-verified-prefix scan handles wire-decoded payloads with
    the same catch it always had.)"""


class WireTimeout(WireError):
    """The RPC deadline passed with the frame incomplete — the
    classified-timeout half of the every-RPC-carries-a-deadline
    contract (a hung wire becomes a member fault, not a hung fleet)."""


class WireClosed(WireError):
    """The peer closed (EOF) — mid-frame or between frames. A member
    process that died mid-write lands here."""


class HandshakeError(WireError):
    """The accept-time HMAC challenge–response failed (wrong secret,
    truncated/garbled exchange, or a peer slower than the handshake
    deadline). The socket is CLOSED before any frame is parsed — an
    unauthenticated peer never reaches the codec."""


class RemoteError(RuntimeError):
    """A member-side exception reconstructed on the supervisor side of
    the wire: ``remote_type`` names the original class (quarantine
    journaling and tests match on it), ``detail`` is its message."""

    def __init__(self, remote_type: str, detail: str):
        super().__init__(f"{remote_type}: {detail}")
        self.remote_type = remote_type
        self.detail = detail


# -- payload codec (shared with the journal: one format, two envelopes) ------

def encode_payload(meta: dict, arrays: Optional[dict] = None) -> bytes:
    """JSON metadata + optional NUL-separated binary blob whose slices
    (dtype/shape/offset/nbytes/crc32) are described by the metadata's
    ``arrays`` table — the TJ1 payload format. ``meta`` is copied, not
    mutated."""
    body = dict(meta)
    blob = b""
    if arrays is not None:
        table = {}
        parts = []
        off = 0
        for name in sorted(arrays):
            a = np.ascontiguousarray(np.asarray(arrays[name]))
            raw = a.tobytes()
            table[name] = {
                "dtype": str(a.dtype), "shape": list(a.shape),
                "offset": off, "nbytes": len(raw),
                "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
            }
            parts.append(raw)
            off += len(raw)
        body["arrays"] = table
        blob = b"\x00" + b"".join(parts)
    return json.dumps(body, sort_keys=True).encode() + blob


def parse_payload(payload: bytes) -> tuple[dict, Optional[dict]]:
    """Decode one payload back to ``(meta, arrays)``, verifying every
    per-array CRC32. Raises :class:`WireError` on any malformation —
    a declared-but-missing blob, a short slice, a CRC mismatch."""
    cut = payload.find(b"\x00")
    meta_bytes = payload if cut < 0 else payload[:cut]
    try:
        meta = json.loads(meta_bytes.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"payload metadata failed to decode: {e}") from e
    if not isinstance(meta, dict):
        raise WireError(
            f"payload metadata is {type(meta).__name__}, expected dict")
    arrays = None
    if "arrays" in meta:
        if cut < 0:
            raise WireError("payload declares arrays but carries no blob")
        blob = payload[cut + 1:]
        arrays = {}
        try:
            items = meta["arrays"].items()
        except AttributeError as e:
            raise WireError("payload arrays table is not a mapping") from e
        for name, spec in items:
            try:
                raw = blob[spec["offset"]:spec["offset"] + spec["nbytes"]]
                if len(raw) != spec["nbytes"]:
                    raise WireError(f"array {name!r} blob slice short")
                if (zlib.crc32(raw) & 0xFFFFFFFF) != spec["crc32"]:
                    raise WireError(
                        f"array {name!r} failed its per-array CRC32")
                arrays[name] = np.frombuffer(
                    raw, dtype=np.dtype(spec["dtype"])
                ).reshape(tuple(spec["shape"])).copy()
            except (KeyError, TypeError, ValueError) as e:
                if isinstance(e, WireError):
                    raise
                raise WireError(
                    f"array {name!r} table entry malformed: {e}") from e
    return meta, arrays


def frame(payload: bytes) -> bytes:
    """One complete wire frame around ``payload``. Refuses an
    over-cap payload on the SENDER: shipping it would make the
    receiver reject the length and close — misclassifying an
    oversized scenario as serial member death across the whole fleet
    instead of one clear error naming the real problem."""
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(
            f"payload is {len(payload)} bytes — over the "
            f"{MAX_FRAME_BYTES}-byte frame cap (a scenario too large "
            "for the wire; shrink the state or raise the cap on BOTH "
            "sides)")
    header = b"TW1 %08x %08x\n" % (
        len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
    return header + payload + b"\n"


# -- accept-time authentication + TCP (ISSUE 20) ------------------------------

#: env var a spawned member reads its shared wire secret from (the
#: spawner generates a per-fleet secret and lays it into the child env
#: — never on the command line, where ``ps`` would show it)
SECRET_ENV = "MMTPU_WIRE_SECRET"

#: handshake wall budget: generous against real network jitter, small
#: enough that a port-scanner holding a socket open cannot park a
#: listener thread for long
HANDSHAKE_DEADLINE_S = 5.0

#: jitter-tolerant TCP deadline retunes (the unix-socket defaults —
#: 2 s heartbeats, 30 s RPCs — assume same-host latency; a real network
#: hiccup must read as jitter, not member death)
TCP_HEARTBEAT_DEADLINE_S = 5.0
TCP_RPC_DEADLINE_S = 60.0

_HS_MAGIC = b"TWA1 "
#: ``b"TWA1 " + 32-hex nonce + b"\n"`` — each side's challenge
_HS_CHALLENGE_LEN = len(_HS_MAGIC) + 32 + 1
#: ``b"TWA1 " + 64-hex digest + b" " + 32-hex nonce + b"\n"`` — the
#: client's proof-of-secret plus its own counter-challenge
_HS_REPLY_LEN = len(_HS_MAGIC) + 64 + 1 + 32 + 1
#: ``b"TWA1 " + 64-hex digest + b"\n"`` — the server's proof
_HS_PROOF_LEN = len(_HS_MAGIC) + 64 + 1


def _hs_digest(secret: str, role: bytes, nonce: bytes) -> bytes:
    """HMAC-SHA256 over ``role + b":" + nonce`` — the role tag makes
    the two directions' proofs distinct, so a reflected server
    challenge can never double as the client's answer."""
    return _hmac.new(secret.encode(), role + b":" + nonce,
                     hashlib.sha256).hexdigest().encode()


def _hs_read(sock, n: int, t_end: float, *, what: str) -> bytes:
    """Read exactly ``n`` handshake bytes before ``t_end`` or raise
    :class:`HandshakeError` (truncated exchange / slow peer)."""
    chunks: list = []
    total = 0
    while total < n:
        # analysis: ignore[naked-timer] — socket-deadline arithmetic
        # (remaining budget for settimeout), not timing
        remaining = t_end - time.monotonic()
        if remaining <= 0:
            raise HandshakeError(
                f"handshake {what} incomplete at its deadline "
                f"({total}/{n} bytes) — peer too slow")
        sock.settimeout(remaining)
        try:
            chunk = sock.recv(n - total)
        except _socket.timeout as e:
            raise HandshakeError(
                f"handshake {what} incomplete at its deadline "
                f"({total}/{n} bytes) — peer too slow") from e
        except OSError as e:
            raise HandshakeError(f"handshake {what} failed: {e}") from e
        if not chunk:
            raise HandshakeError(
                f"peer closed during handshake {what} "
                f"({total}/{n} bytes)")
        chunks.append(chunk)
        total += len(chunk)
    return b"".join(chunks)


def _hs_nonce_of(line: bytes, *, what: str) -> bytes:
    if line[:len(_HS_MAGIC)] != _HS_MAGIC or line[-1:] != b"\n":
        raise HandshakeError(f"malformed handshake {what} {line[:8]!r}")
    return line[len(_HS_MAGIC):-1]


def _hs_maybe_garbled(digest: bytes, chaos_id: Optional[str]) -> bytes:
    """The ``handshake_fail`` chaos seam: a live fault aimed at
    ``chaos_id`` garbles this side's proof, so the PEER must refuse and
    close (one global read when disarmed)."""
    st = inject.active()
    if st is None:
        return digest
    f = st.member_fault(chaos_id, ("handshake_fail",),
                        site="handshake", count=True)
    if f is None:
        return digest
    return bytes(reversed(digest))


def serve_handshake(sock, secret: str,
                    deadline_s: float = HANDSHAKE_DEADLINE_S,
                    chaos_id: Optional[str] = None) -> None:
    """Authenticate an accepted connection (server side) via a mutual
    HMAC-SHA256 challenge–response before ANY frame is parsed:
    challenge the peer, verify its proof, then prove ourselves against
    its counter-challenge. Any failure — wrong secret, truncated or
    malformed exchange, a peer slower than ``deadline_s`` — raises
    :class:`HandshakeError` and CLOSES the socket, so an
    unauthenticated peer never reaches the frame codec."""
    import secrets as _secrets

    # analysis: ignore[naked-timer] — handshake deadline arithmetic
    t_end = time.monotonic() + float(deadline_s)
    try:
        nonce = _secrets.token_hex(16).encode()
        sock.settimeout(deadline_s)
        sock.sendall(_HS_MAGIC + nonce + b"\n")
        reply = _hs_read(sock, _HS_REPLY_LEN, t_end, what="reply")
        body = _hs_nonce_of(reply, what="reply")
        proof, sep, peer_nonce = body.partition(b" ")
        if not sep or len(peer_nonce) != 32:
            raise HandshakeError("malformed handshake reply")
        want = _hs_digest(secret, b"client", nonce)
        if not _hmac.compare_digest(proof, want):
            raise HandshakeError(
                "peer failed the challenge (wrong wire secret)")
        ours = _hs_maybe_garbled(
            _hs_digest(secret, b"server", peer_nonce), chaos_id)
        sock.sendall(_HS_MAGIC + ours + b"\n")
    except (HandshakeError, OSError) as e:
        try:
            sock.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass
        if isinstance(e, HandshakeError):
            raise
        raise HandshakeError(f"handshake failed: {e}") from e


def client_handshake(sock, secret: str,
                     deadline_s: float = HANDSHAKE_DEADLINE_S,
                     chaos_id: Optional[str] = None) -> None:
    """The dialing side of :func:`serve_handshake`: answer the
    listener's challenge, counter-challenge it, verify its proof. Same
    failure contract — :class:`HandshakeError`, socket closed, no frame
    ever parsed on an unauthenticated stream."""
    import secrets as _secrets

    # analysis: ignore[naked-timer] — handshake deadline arithmetic
    t_end = time.monotonic() + float(deadline_s)
    try:
        challenge = _hs_read(sock, _HS_CHALLENGE_LEN, t_end,
                             what="challenge")
        nonce = _hs_nonce_of(challenge, what="challenge")
        if len(nonce) != 32:
            raise HandshakeError("malformed handshake challenge")
        ours = _hs_maybe_garbled(
            _hs_digest(secret, b"client", nonce), chaos_id)
        my_nonce = _secrets.token_hex(16).encode()
        sock.settimeout(deadline_s)
        sock.sendall(_HS_MAGIC + ours + b" " + my_nonce + b"\n")
        proof_line = _hs_read(sock, _HS_PROOF_LEN, t_end, what="proof")
        proof = _hs_nonce_of(proof_line, what="proof")
        want = _hs_digest(secret, b"server", my_nonce)
        if not _hmac.compare_digest(proof, want):
            raise HandshakeError(
                "listener failed the counter-challenge (wrong wire "
                "secret)")
    except (HandshakeError, OSError) as e:
        try:
            sock.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass
        if isinstance(e, HandshakeError):
            raise
        raise HandshakeError(f"handshake failed: {e}") from e


def tcp_listener(host: str = "127.0.0.1", port: int = 0):
    """A listening TCP socket for member accept — ``port=0`` lets the
    OS pick (the spawner reads the bound port back). Part of the
    sanctioned transport boundary the ``raw-transport`` rule pins."""
    srv = _socket.create_server((host, port))
    return srv


def tcp_dial(addr: str, deadline_s: float = HANDSHAKE_DEADLINE_S):
    """Dial a ``host:port`` member address (IPv6 hosts may be
    bracketed); raises :class:`WireClosed` when the peer is
    unreachable within ``deadline_s``."""
    host, sep, port = addr.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"not a host:port address: {addr!r}")
    host = host.strip("[]") or "127.0.0.1"
    try:
        return _socket.create_connection((host, int(port)),
                                         timeout=float(deadline_s))
    except OSError as e:
        raise WireClosed(f"dial {addr} failed: {e}") from e


# -- the connection -----------------------------------------------------------

class FrameConn:
    """Frame-at-a-time messaging over one stream socket.

    Not internally locked: each side serializes its use under its own
    lock (the member client's RPC lock / the member server's single
    serve thread) — the conn is a seam, not a shared service.
    ``chaos_id`` names the member this conn belongs to so the
    ``wire_torn``/``proc_kill``/``heartbeat_loss`` faults can target
    one member by ``channel`` (the client side sets it; the server
    side leaves it None so a fault fires exactly once per plan).
    ``bytes_in``/``bytes_out`` are the observability counters the
    fleet's ``stats()`` aggregates per member."""

    def __init__(self, sock, chaos_id: Optional[str] = None):
        self._sock = sock
        self.chaos_id = chaos_id
        self.bytes_in = 0
        self.bytes_out = 0
        self._buf = b""
        self._closed = False

    # -- sending -------------------------------------------------------------

    def send(self, kind: str, meta: Optional[dict] = None,
             arrays: Optional[dict] = None,
             deadline_s: Optional[float] = None) -> None:
        """Frame and send one message. ``kind`` must be a known request
        or reply kind (a typo'd kind fails HERE, on the sender, with a
        stack trace — not as a mystery error on the peer)."""
        if kind not in REQUEST_KINDS and kind not in REPLY_KINDS:
            raise ValueError(
                f"unknown wire message kind {kind!r} (expected one of "
                f"{REQUEST_KINDS + REPLY_KINDS})")
        body = dict(meta or {})
        body["kind"] = kind
        data = frame(encode_payload(body, arrays))
        st = inject.active()
        if st is not None:
            self._maybe_partitioned(st)
            f = st.member_fault(self.chaos_id, ("wire_torn",),
                                site="wire", count=False)
            if f is not None:
                self._send_torn(data, f)
                return
        self._sendall(data, deadline_s)
        self.bytes_out += len(data)

    def _maybe_partitioned(self, st) -> None:
        """The ``tcp_partition`` chaos seam (ISSUE 20): a live fault
        aimed at this conn makes the operation behave as a network
        partition — the conn closes and the call raises
        :class:`WireTimeout`, exactly what a real partition looks like
        at the RPC deadline (the fleet must classify it a member
        fault and fence)."""
        f = st.member_fault(self.chaos_id, ("tcp_partition",),
                            site="wire", count=False)
        if f is not None:
            self.close()
            raise WireTimeout(
                "injected tcp partition: peer unreachable at the "
                "deadline")

    def _send_torn(self, data: bytes, fault) -> None:
        """The ``wire_torn`` chaos seam: ``corrupt`` flips ``nbytes``
        at ``offset`` (the receiver's CRC fires); ``truncate`` sends
        only the first ``offset`` bytes and CLOSES — a write torn by a
        crash, surfacing as ``WireClosed`` on the peer, never a hang."""
        if fault.tear == "truncate":
            cut = min(max(fault.offset, 0), len(data))
            self._sendall(data[:cut], None)
            self.bytes_out += cut
            self.close()
            return
        off = min(max(fault.offset, 0), max(len(data) - 1, 0))
        chunk = data[off:off + fault.nbytes]
        data = (data[:off] + bytes(b ^ 0xFF for b in chunk)
                + data[off + len(chunk):])
        self._sendall(data, None)
        self.bytes_out += len(data)

    def _sendall(self, data: bytes, deadline_s: Optional[float]) -> None:
        if self._closed:
            raise WireClosed("connection already closed")
        try:
            self._sock.settimeout(deadline_s)
            self._sock.sendall(data)
        except _socket.timeout as e:
            raise WireTimeout(
                f"send blocked past its {deadline_s}s deadline") from e
        except OSError as e:
            raise WireClosed(f"send failed: {e}") from e

    # -- receiving -----------------------------------------------------------

    def recv(self, deadline_s: Optional[float] = None
             ) -> tuple[str, dict, Optional[dict]]:
        """Read exactly one frame: ``(kind, meta, arrays)``. Raises
        :class:`WireTimeout` when ``deadline_s`` wall seconds pass with
        the frame incomplete, :class:`WireClosed` on EOF,
        :class:`WireError` on any framing/CRC failure.

        ANY failure POISONS the connection (it closes): a stream that
        timed out or failed a check is unsynchronized — a late reply
        still in flight would otherwise pair with the NEXT request —
        so the no-retries contract is enforced structurally, not by
        caller discipline."""
        st = inject.active()
        if st is not None:
            self._maybe_partitioned(st)
        try:
            return self._recv(deadline_s)
        except WireError:
            self.close()
            raise

    def _recv(self, deadline_s: Optional[float]
              ) -> tuple[str, dict, Optional[dict]]:
        # analysis: ignore[naked-timer] — socket-deadline arithmetic
        # (settimeout needs remaining wall seconds), not timing: the
        # RPC latency a span would measure lives in the client layer
        t_end = (
            # analysis: ignore[naked-timer] — socket-deadline
            # arithmetic (see the pragma block above)
            None if deadline_s is None
            # analysis: ignore[naked-timer] — same bound
            else time.monotonic() + float(deadline_s))
        header = self._read_exact(_HEADER_LEN, t_end)
        if header[:4] != _MAGIC or header[12:13] != b" " \
                or header[21:22] != b"\n":
            raise WireError(f"bad frame header {header!r}")
        try:
            n = int(header[4:12], 16)
            want = int(header[13:21], 16)
        except ValueError as e:
            raise WireError(f"bad frame header {header!r}") from e
        if n > MAX_FRAME_BYTES:
            raise WireError(
                f"frame declares {n} bytes (> {MAX_FRAME_BYTES} cap) — "
                "refusing a corrupt length")
        body = self._read_exact(n + 1, t_end)
        payload, trailer = body[:n], body[n:]
        if trailer != b"\n":
            raise WireError("frame trailer missing")
        if (zlib.crc32(payload) & 0xFFFFFFFF) != want:
            raise WireError("frame payload failed its CRC32")
        meta, arrays = parse_payload(payload)
        kind = meta.get("kind")
        if not isinstance(kind, str):
            raise WireError("frame metadata carries no kind")
        return kind, meta, arrays

    def _read_exact(self, n: int, t_end: Optional[float]) -> bytes:
        # chunks accumulate in a LIST and join once: `bytes += chunk`
        # re-copies the whole accumulation per chunk — quadratic on
        # the multi-megabyte scenario frames this path exists for
        chunks = [self._buf]
        total = len(self._buf)
        try:
            while total < n:
                if self._closed:
                    raise WireClosed("connection already closed")
                if t_end is not None:
                    # analysis: ignore[naked-timer] — same deadline
                    # arithmetic (remaining budget for settimeout)
                    remaining = t_end - time.monotonic()
                    if remaining <= 0:
                        raise WireTimeout(
                            "frame incomplete at its receive deadline "
                            f"({total}/{n} bytes)")
                    self._sock.settimeout(remaining)
                else:
                    self._sock.settimeout(None)
                try:
                    chunk = self._sock.recv(65536)
                except _socket.timeout as e:
                    raise WireTimeout(
                        "frame incomplete at its receive deadline "
                        f"({total}/{n} bytes)") from e
                except OSError as e:
                    raise WireClosed(f"recv failed: {e}") from e
                if not chunk:
                    raise WireClosed(
                        f"peer closed mid-frame ({total}/{n} bytes)")
                chunks.append(chunk)
                total += len(chunk)
                self.bytes_in += len(chunk)
        finally:
            # whatever arrived belongs to the stream even on an error
            # path (the conn poisons on failure anyway, but the
            # byte-counter/buffer accounting stays exact)
            self._buf = b"".join(chunks)
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    # -- lifecycle -----------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass

    def __enter__(self) -> "FrameConn":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
