"""The declared ticket-lifecycle state machine (ISSUE 19 tentpole).

Before this module the ticket protocol — which journal record kinds
exist, which transitions are legal, which kinds resolve a ticket —
lived as hand-rolled string literals spread over ``journal.py``'s
replay fold, ``tiering.py``'s recovery fold and wake ladder,
``fleet.py``'s append sites and ``obs.postmortem``'s timeline join.
Every reader re-derived the vocabulary independently, so a drifted
literal (a kind written that no reader handles, a meta key read that no
writer stamps) was invisible until a chaos row happened to cross it.

This module is the single source of truth the rest of the package
consumes:

- **kind constants** (``SUBMIT`` … ``RECLAIM``): every append site and
  every reader dispatch references these — a raw record-kind string
  literal outside this module is an ERROR (the ``journal-kind-literal``
  lint rule);
- **two machines** (:data:`FLEET`, :data:`TIERING`), one per journal
  stream, each declaring its states, legal transitions, terminal set
  and the meta keys each record kind carries;
- the **FailureEvent kind set** (:data:`EVENT_KINDS`) — every
  ``FailureEvent(kind=...)`` constructed anywhere must use one of
  these (the ``event-kind-coverage`` protocol rule);
- the **universal stamps** (:data:`STAMPED_META`): keys every record
  carries regardless of kind (``kind`` and ``t_wall`` stamped by
  ``TicketJournal.append``, ``arrays`` by the payload codec).

Consumers: ``journal.fold_records``/``replay`` fold the fleet stream
with :data:`FLEET`; ``tiering.ScenarioTiering.recover`` folds the
lifecycle stream with :data:`TIERING`; ``obs.postmortem`` classifies
timeline events through both; ``analysis.protocol`` (layer 4) audits
the whole program's writers and readers against the declarations; and
``resilience.protocolcheck`` is the runtime witness asserting live
streams only ever take declared transitions.

Declaring a NEW record kind (the checklist DESIGN.md "Protocol
analysis" walks through): add the kind constant, add a
:class:`Transition` to the owning machine (sources, target, the meta
keys the writer stamps), write the append site through the constant,
and teach the reader folds only if the kind needs bespoke handling —
the protocol auditor then proves writer, reader and declaration agree.

IMPORT-LIGHT BY CONTRACT: stdlib only (no numpy/jax), so the obs plane,
the analysis layer and the runtime witness can all load the machine
without pulling the serving stack.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = [
    "EPOCH",
    "EVENT_KINDS",
    "EXPIRED",
    "FLEET",
    "HIBERNATE",
    "HIBERNATED",
    "LifecycleMachine",
    "MIGRATE",
    "QUARANTINED",
    "READMIT",
    "RECLAIM",
    "REQUEUE",
    "SERVED",
    "SHED",
    "STAMPED_META",
    "SUBMIT",
    "TERMINAL_KINDS",
    "TIERING",
    "Transition",
    "WAKE",
    "machine_for_journal",
]

# -- record-kind constants (the only place these strings are spelled) ---------

#: fleet stream — admission/resolution
SUBMIT = "submit"
SERVED = "served"
QUARANTINED = "quarantined"
EXPIRED = "expired"
SHED = "shed"
#: fleet stream — attribution (the ticket moved, nothing resolved)
READMIT = "readmit"
MIGRATE = "migrate"
WAKE = "wake"
#: fleet stream — supervisor-generation audit record (ISSUE 20): a
#: supervisor (first start or failover takeover) declaring it now owns
#: the stream; appends stamped with a LOWER epoch are fenced
EPOCH = "epoch"
#: tiering stream — the hibernate/wake paging lifecycle
HIBERNATE = "hibernate"      # intent (written BEFORE the chain write)
HIBERNATED = "hibernated"    # commit (the chain record verified on disk)
REQUEUE = "requeue"          # woke, found no placement, back at the head
RECLAIM = "reclaim"          # chain deleted (resolution or orphan sweep)

#: kinds that RESOLVE a fleet ticket (everything else is attribution)
TERMINAL_KINDS = (SERVED, QUARANTINED, EXPIRED)

#: meta keys EVERY record carries regardless of kind: ``kind``/``t_wall``
#: are stamped by ``TicketJournal.append``, ``arrays`` (the per-array
#: CRC table) by the shared TJ1/TW1 payload codec when state rides along,
#: and ``epoch`` by an epoch-fenced journal handle (ISSUE 20 — absent on
#: journals opened without a supervisor epoch)
STAMPED_META = ("kind", "t_wall", "arrays", "epoch")

#: every ``resilience.FailureEvent.kind`` the package constructs (the
#: supervisor docstring's taxonomy, now machine-checked by the
#: ``event-kind-coverage`` protocol rule)
EVENT_KINDS = frozenset({
    "exception",      # the step raised
    "nonfinite",      # NaN/Inf in the state
    "conservation",   # the invariant check failed
    "timeout",        # a dispatch/ticket deadline passed
    "expired",        # a ticket aged out before serving
    "member",         # a fleet member died/wedged (fence + re-admit)
    "hibernation",    # the hibernate/wake paging path failed
})


# -- the machines -------------------------------------------------------------

#: the implicit state of a ticket the stream has not mentioned yet
INITIAL = "new"


@dataclasses.dataclass(frozen=True)
class Transition:
    """One declared transition: the journal record ``kind`` that emits
    it, the states it is legal FROM, the state it lands in, and the
    meta keys its writer stamps (beyond :data:`STAMPED_META`).
    ``ticketless`` transitions are stream-level audit records (no
    per-ticket state — the fleet's ``shed``)."""

    kind: str
    sources: tuple
    target: str
    meta: tuple = ()
    terminal: bool = False
    ticketless: bool = False


@dataclasses.dataclass(frozen=True)
class LifecycleMachine:
    """One journal stream's declared protocol. ``journal_name`` is the
    stream's file basename — what maps a live ``TicketJournal`` back to
    its machine (:func:`machine_for_journal`)."""

    stream: str
    journal_name: str
    states: tuple
    transitions: tuple

    def kinds(self) -> tuple:
        """Every declared record kind, in declaration order."""
        return tuple(t.kind for t in self.transitions)

    def terminal_kinds(self) -> tuple:
        return tuple(t.kind for t in self.transitions if t.terminal)

    def attribution_kinds(self) -> tuple:
        """Per-ticket kinds that move a ticket without starting or
        resolving it (what a timeline shows between submit and
        terminal)."""
        return tuple(t.kind for t in self.transitions
                     if not t.terminal and not t.ticketless
                     and INITIAL not in t.sources)

    def transition(self, kind: str) -> Optional[Transition]:
        for t in self.transitions:
            if t.kind == kind:
                return t
        return None

    def is_terminal(self, kind: str) -> bool:
        t = self.transition(kind)
        return t is not None and t.terminal

    def legal(self, kind: str, state: str) -> bool:
        """Is ``kind`` a declared transition out of ``state``?"""
        t = self.transition(kind)
        return t is not None and (t.ticketless or state in t.sources)

    def meta_keys(self) -> frozenset:
        """Every declared per-kind meta key plus the universal stamps —
        the vocabulary the ``journal-meta-drift`` rule checks reader
        key reads against."""
        keys = set(STAMPED_META)
        for t in self.transitions:
            keys.update(t.meta)
        return frozenset(keys)


#: the fleet ticket journal (``tickets.journal``): one record per
#: scheduler seam a ticket crosses. A ticket is ``in-flight`` from its
#: submit (resident OR hibernated — the fleet stream does not
#: distinguish; the tiering stream does) until exactly one terminal.
FLEET = LifecycleMachine(
    stream="fleet",
    journal_name="tickets.journal",
    states=(INITIAL, "in-flight", "resolved"),
    transitions=(
        Transition(SUBMIT, (INITIAL,), "in-flight",
                   meta=("ticket", "service_id", "steps", "model",
                         "trace", "dim_x", "dim_y")),
        Transition(SERVED, ("in-flight",), "resolved", terminal=True,
                   meta=("ticket", "service_id", "steps",
                         "initial_total", "final_total", "wall_time_s",
                         "dim_x", "dim_y", "recovered_from_journal")),
        Transition(QUARANTINED, ("in-flight",), "resolved",
                   terminal=True,
                   meta=("ticket", "service_id", "steps", "error",
                         "detail")),
        Transition(EXPIRED, ("in-flight",), "resolved", terminal=True,
                   meta=("ticket", "service_id", "steps", "error",
                         "detail")),
        Transition(SHED, (), INITIAL, ticketless=True,
                   meta=("depth", "members")),
        Transition(MIGRATE, ("in-flight",), "in-flight",
                   meta=("ticket", "from", "to", "reason")),
        Transition(READMIT, ("in-flight",), "in-flight",
                   meta=("ticket", "from", "to", "reason")),
        Transition(WAKE, ("in-flight",), "in-flight",
                   meta=("ticket", "to")),
        Transition(EPOCH, (), INITIAL, ticketless=True,
                   meta=("epoch", "supervisor", "takeover_from",
                         "lease_s")),
    ),
)

#: the hibernation lifecycle journal (``hibernation.journal``): the
#: intent→commit→wake chain ``ScenarioTiering`` writes around every
#: paging operation. ``hibernate`` is legal from ``resident`` too
#: (re-hibernation of a woken scenario); ``wake`` is legal ONLY from
#: the committed state — a wake whose intent never committed is the
#: torn-hibernation crash shape, and the runtime witness flags it on a
#: LIVE stream (recovery resolves it through the wake ladder instead).
TIERING = LifecycleMachine(
    stream="tiering",
    journal_name="hibernation.journal",
    states=(INITIAL, "hibernating", "hibernated", "resident",
            "reclaimed"),
    transitions=(
        Transition(HIBERNATE, (INITIAL, "resident"), "hibernating",
                   meta=("ticket", "seq", "steps", "nbytes", "model")),
        Transition(HIBERNATED, ("hibernating",), "hibernated",
                   meta=("ticket", "seq", "disk_bytes")),
        Transition(WAKE, ("hibernated",), "resident",
                   meta=("ticket", "seq", "source")),
        Transition(REQUEUE, ("resident",), "hibernated",
                   meta=("ticket", "seq")),
        Transition(RECLAIM, (INITIAL, "hibernating", "hibernated",
                             "resident"), "reclaimed", terminal=True,
                   meta=("ticket",)),
    ),
)

#: both declared machines, keyed by stream name
MACHINES = {FLEET.stream: FLEET, TIERING.stream: TIERING}


def machine_for_journal(path: str) -> Optional[LifecycleMachine]:
    """The machine owning a journal file, by basename — how the runtime
    witness classifies a live ``TicketJournal`` append stream. None for
    a journal the protocol does not declare (a user's ad-hoc journal
    must not trip the witness)."""
    import os

    base = os.path.basename(path)
    for m in MACHINES.values():
        if m.journal_name == base:
            return m
    return None
