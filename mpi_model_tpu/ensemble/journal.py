"""Append-only CRC'd ticket journal — the crash-restart half of the
fleet supervisor (ISSUE 10 tentpole, layer 3).

PR 9's "zero silent drops" contract holds only while the process lives:
every submitted ticket resolves to exactly one of result / quarantine /
expiry / shed — in memory. A hard kill voids all of it. This module
makes the ledger durable: the fleet writes one journal record at each
scheduler seam a ticket crosses —

==============  =============================================================
kind            written when / carries
==============  =============================================================
``submit``      a ticket was ADMITTED (after the member accepted it, so
                a crash in the admission window can never replay a shed
                submission): ticket id, member ``service_id``, steps,
                the scenario model's numeric parameters and the full
                channel state (per-array CRC32)
``served``      the fleet harvested a result: final channel state +
                conservation totals — a served-but-unacknowledged
                ticket resolves FROM THE JOURNAL after a restart,
                without re-running the scenario
``quarantined``/
``expired``     the ticket resolved as a failure: kind + detail, enough
                to reconstruct the error (and the ledger line) exactly
``shed``        an admission was refused fleet-wide (no ticket was ever
                issued; recorded for the audit trail only)
``readmit``/
``migrate``     non-terminal attribution: a ticket moved to another
                member (fencing, retirement, crash-restart recovery)
``epoch``       a supervisor declared ownership of the stream — first
                start or failover takeover (ISSUE 20): the sidecar
                fence file moved first, so appends from any older
                epoch's handle raise ``StaleEpochError`` from then on
==============  =============================================================

Record format (the PR 5/6 checkpoint discipline applied to a log):
every record is ``b"TJ1 <len:08x> <crc:08x>\\n" + payload + b"\\n"``
where the CRC32 covers the whole payload; a payload is the record's
JSON metadata, optionally followed by ``b"\\x00"`` and a raw binary
blob whose slices are described — with their OWN per-array CRC32s — by
the metadata's ``arrays`` table. The reader verifies record CRCs in
order and STOPS at the first record that fails to parse or verify: a
torn tail (the classic crash shape, and the ``journal_torn`` chaos
fault) costs exactly the unverifiable suffix, never the verified
prefix, and never a wrong byte admitted as state. Opening a journal for
append first truncates it back to its verified prefix, so recovery
writes always extend good data.

``replay`` folds the verified records into per-ticket outcomes:
``unresolved()`` (submitted, no terminal record) is exactly the set
``FleetSupervisor.recover`` re-admits; a second recovery of a journal
whose first recovery ran to completion finds nothing unresolved — the
idempotence the tests pin.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
import zlib
from typing import Optional

import numpy as np

from ..core.cellular_space import CellularSpace
from ..models.model import Model
from ..resilience import inject, protocolcheck
from .lifecycle import EPOCH, FLEET, SHED, SUBMIT, TERMINAL_KINDS
from .wire import encode_payload, parse_payload

__all__ = [
    "audit_journal",
    "current_epoch",
    "declare_epoch",
    "fold_records",
    "main",
    "StaleEpochError",
    "TicketJournal",
    "JournalRecord",
    "JournalState",
    "read_records",
    "replay",
    "journal_path",
    "space_payload",
    "space_from_record",
    "model_meta",
    "model_from_meta",
    "TERMINAL_KINDS",
]

# TERMINAL_KINDS (re-exported above) and the full record vocabulary are
# DECLARED in ensemble.lifecycle — the single source of truth the
# protocol auditor (analysis.protocol) and the runtime witness
# (resilience.protocolcheck) audit writers and readers against. This
# module only folds what the machine declares.

_MAGIC = b"TJ1 "
_HEADER_RE = re.compile(rb"^TJ1 ([0-9a-f]{8}) ([0-9a-f]{8})\n$")
_HEADER_LEN = 22  # b"TJ1 " + 8 hex + b" " + 8 hex + b"\n"

#: the journal file name inside a journal directory (one stream per
#: fleet; recovery appends to the same file, so the whole history of a
#: slot — original run + every restart — reads as one ledger). The
#: basename is the machine's: it is how the runtime witness maps a live
#: append stream back to its declared lifecycle.
JOURNAL_NAME = FLEET.journal_name


def journal_path(journal_dir: str) -> str:
    return os.path.join(journal_dir, JOURNAL_NAME)


@dataclasses.dataclass
class JournalRecord:
    """One verified journal record: its 0-based ``index`` in the file,
    the ``kind``, the JSON ``meta`` and the materialized (CRC-verified)
    ``arrays``, if the record carried state."""

    index: int
    kind: str
    meta: dict
    arrays: Optional[dict] = None

    @property
    def ticket(self) -> Optional[int]:
        return self.meta.get("ticket")


class StaleEpochError(ValueError):
    """A journal append was fenced: the handle's supervisor epoch is
    older than the fence file's — a standby took over while this
    (zombie) supervisor still held an open handle. The append wrote
    NOTHING; the zombie must stop, never retry (ISSUE 20)."""


#: sidecar fence-file suffix: ``<journal>.epoch`` holds the current
#: supervisor epoch as ASCII digits, written atomically (tmp + rename)
#: BEFORE the matching ``epoch`` record — a crash between the two
#: over-bumps the fence (harmless) but never leaves a declared epoch
#: unfenced
_EPOCH_SUFFIX = ".epoch"


def current_epoch(path: str) -> int:
    """The fence: the highest supervisor epoch ever declared over this
    journal (0 when no supervisor has declared one — pre-ISSUE-20
    journals and epoch-less tests)."""
    try:
        with open(path + _EPOCH_SUFFIX, "rb") as fh:
            return int(fh.read().strip() or b"0")
    except (FileNotFoundError, ValueError):
        return 0


def _write_fence(path: str, epoch: int) -> None:
    tmp = path + _EPOCH_SUFFIX + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(b"%d\n" % epoch)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path + _EPOCH_SUFFIX)


class TicketJournal:
    """Append handle over one journal file. NOT internally locked: the
    fleet serializes every append under its own supervisor lock (the
    journal is a seam of the fleet, not a shared service).

    ``epoch`` (ISSUE 20) opts the handle into the supervisor fence:
    every append first checks the sidecar fence file and raises
    :class:`StaleEpochError` — writing nothing — once a later
    supervisor has declared a higher epoch, and every record written
    carries the handle's epoch in its meta. ``epoch=None`` (the
    default) keeps the pre-failover behaviour: no check, no stamp."""

    def __init__(self, path: str, epoch: Optional[int] = None):
        self.path = path
        self.epoch = epoch
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._count = 0
        if os.path.exists(path) and os.path.getsize(path) > 0:
            # truncate a torn tail back to the verified prefix so every
            # append extends good data (recover-then-append safety)
            records, _, verified_len = _scan(path)
            self._count = len(records)
            if verified_len < os.path.getsize(path):
                with open(path, "r+b") as fh:
                    fh.truncate(verified_len)
        self._fh = open(path, "ab")

    @property
    def count(self) -> int:
        """Records appended so far (verified prefix + this handle's)."""
        return self._count

    def append(self, kind: str, meta: Optional[dict] = None,
               arrays: Optional[dict] = None) -> int:
        """Write one CRC'd record and flush; returns its index. The
        ``journal_torn`` chaos seam fires AFTER the write, with the
        record's byte offset, so a torn-tail fault lands exactly where
        a real mid-record crash would. Every record is stamped with
        ``t_wall`` (epoch seconds) at append time — the ordering anchor
        ``obs.timeline`` joins journal records against wall-anchored
        spans with (record INDEX stays the authoritative order within
        one journal; the stamp is for cross-source merges).

        An epoch-fenced handle (``epoch`` set at open) re-reads the
        sidecar fence BEFORE writing and raises
        :class:`StaleEpochError` if a later supervisor has taken over —
        the zombie-supervisor write lands nowhere, not even torn. The
        ``stale_epoch_append`` chaos seam makes THIS append behave as a
        one-epoch-older zombie's, exercising the fence without a real
        failover."""
        if self.epoch is not None:
            effective = self.epoch
            if inject.stale_epoch_append(self.path):
                effective -= 1
            fence = current_epoch(self.path)
            if effective < fence:
                raise StaleEpochError(
                    f"append fenced: handle epoch {effective} < "
                    f"journal fence {fence} (a newer supervisor owns "
                    f"{self.path})")
        body = dict(meta or {})
        if self.epoch is not None:
            body.setdefault("epoch", self.epoch)
        body["kind"] = kind
        body.setdefault("t_wall", time.time())
        # ONE payload format for the journal and the fleet wire
        # (ISSUE 13 lifted it into ensemble.wire): a journal record and
        # a wire message differ only in their envelope
        payload = encode_payload(body, arrays)
        header = b"TJ1 %08x %08x\n" % (
            len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        start = self._fh.tell()
        self._fh.write(header + payload + b"\n")
        self._fh.flush()
        idx = self._count
        self._count += 1
        # the protocol witness observes every durable append (one
        # global read when disarmed); it fires BEFORE the torn-tail
        # chaos seam — an injected tear models a crash AFTER this
        # process already advanced its in-memory state
        protocolcheck.journal_append(self.path, kind, body)
        inject.journal_torn(self.path, idx, start)
        return idx

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()

    def __enter__(self) -> "TicketJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def declare_epoch(journal: TicketJournal, *, supervisor: str,
                  takeover_from: Optional[str] = None,
                  lease_s: Optional[float] = None) -> int:
    """Bump the fence and append the matching ``epoch`` audit record —
    what a supervisor does at first start and what a standby does at
    takeover (ISSUE 20). The fence file moves FIRST (atomic rename),
    so from the instant a takeover is durable, every append from the
    previous epoch's still-open handles raises
    :class:`StaleEpochError`; the journal record is the human/audit
    half, carrying who took over and from whom. Returns the new epoch
    and re-arms ``journal`` to it."""
    new = current_epoch(journal.path) + 1
    _write_fence(journal.path, new)
    journal.epoch = new
    journal.append(EPOCH, {
        "epoch": new,
        "supervisor": supervisor,
        "takeover_from": takeover_from,
        "lease_s": lease_s,
    })
    return new


def _parse_record(index: int, payload: bytes) -> JournalRecord:
    # the shared TJ1/TW1 payload codec verifies every per-array CRC32;
    # WireError is a ValueError, so _scan's truncate-to-verified-prefix
    # catch treats a malformed payload exactly like a torn one
    meta, arrays = parse_payload(payload)
    return JournalRecord(index, meta["kind"], meta, arrays)


def _scan(path: str) -> tuple[list[JournalRecord], bool, int]:
    """(verified records, torn?, verified byte length): parse records
    in order, stopping at the first header/CRC/decode failure — the
    recover-up-to-last-CRC-verified-entry contract."""
    with open(path, "rb") as fh:
        data = fh.read()
    records: list[JournalRecord] = []
    pos = 0
    while pos < len(data):
        header = data[pos:pos + _HEADER_LEN]
        m = _HEADER_RE.match(header)
        if m is None:
            return records, True, pos
        n = int(m.group(1), 16)
        want = int(m.group(2), 16)
        payload = data[pos + _HEADER_LEN:pos + _HEADER_LEN + n]
        end = pos + _HEADER_LEN + n + 1
        if (len(payload) != n or end > len(data)
                or data[end - 1:end] != b"\n"
                or (zlib.crc32(payload) & 0xFFFFFFFF) != want):
            return records, True, pos
        try:
            records.append(_parse_record(len(records), payload))
        except (ValueError, KeyError, json.JSONDecodeError):
            return records, True, pos
        pos = end
    return records, False, pos


def read_records(path: str) -> tuple[list[JournalRecord], bool]:
    """Every CRC-verified record in order, plus whether the file had a
    torn/corrupt tail (the suffix after the last verified record)."""
    if not os.path.exists(path):
        return [], False
    records, torn, _ = _scan(path)
    return records, torn


@dataclasses.dataclass
class JournalState:
    """The journal folded to per-ticket outcomes."""

    #: ticket → its submit record (state + model + steps)
    submits: dict
    #: ticket → its FIRST terminal record (served/quarantined/expired)
    terminal: dict
    #: tickets that appeared with MORE than one terminal record — a
    #: duplicate-resolution audit failure (must stay empty)
    duplicate_terminals: list
    #: fleet-level admission refusals recorded (no ticket issued)
    shed: int
    #: the file had a torn tail (the suffix was discarded)
    torn: bool
    #: supervisor-generation history (ISSUE 20): the meta of every
    #: ``epoch`` record in stream order — who owned the journal, when,
    #: and whom they took over from
    epochs: list = dataclasses.field(default_factory=list)
    #: indices of records stamped with an epoch OLDER than the highest
    #: epoch declared before them in the stream — a zombie write the
    #: fence should have refused (must stay empty; the audit fails on
    #: any)
    stale_epoch_records: list = dataclasses.field(default_factory=list)

    def unresolved(self) -> list[int]:
        """Tickets submitted but never resolved — what recovery
        re-admits, in submit order."""
        return [t for t in self.submits if t not in self.terminal]

    def max_ticket(self) -> int:
        return max(self.submits, default=-1)


def replay(path: str) -> JournalState:
    records, torn = read_records(path)
    return fold_records(records, torn)


def fold_records(records: list, torn: bool) -> JournalState:
    """Fold already-verified records to per-ticket outcomes — the
    in-memory half of :func:`replay`, so callers that already hold the
    record list (the inspection CLI) do not re-read and re-CRC the
    whole file per derived view. The fold consumes the DECLARED fleet
    machine (``lifecycle.FLEET``) — what resolves a ticket is whatever
    the declaration says is terminal, never a literal spelled here."""
    submits: dict = {}
    terminal: dict = {}
    dup: list = []
    shed = 0
    epochs: list = []
    stale: list = []
    declared = 0
    for rec in records:
        # epoch-fence audit (ISSUE 20): a record stamped with an epoch
        # below the highest declared BEFORE it in the stream is a
        # zombie write the fence should have refused
        stamped = rec.meta.get("epoch")
        if stamped is not None and stamped < declared:
            stale.append(rec.index)
        if rec.kind == EPOCH:
            epochs.append(rec.meta)
            declared = max(declared, rec.meta["epoch"])
        elif rec.kind == SUBMIT:
            submits[rec.ticket] = rec
        elif FLEET.is_terminal(rec.kind):
            if rec.ticket in terminal:
                dup.append(rec.ticket)
            else:
                terminal[rec.ticket] = rec
        elif rec.kind == SHED:
            shed += 1
    return JournalState(submits=submits, terminal=terminal,
                        duplicate_terminals=dup, shed=shed, torn=torn,
                        epochs=epochs, stale_epoch_records=stale)


# -- scenario (space/model) serialization -------------------------------------

def space_payload(space: CellularSpace) -> tuple[dict, dict]:
    """(meta, arrays) for a FULL-grid scenario space — what a submit or
    served record carries. Partitions never reach the ensemble engine
    (``EnsembleSpace.stack`` refuses them), so geometry is dims only."""
    arrays = {k: np.asarray(v) for k, v in space.values.items()}
    return {"dim_x": space.dim_x, "dim_y": space.dim_y}, arrays


def space_from_record(rec: JournalRecord) -> CellularSpace:
    """Materialize the record's CRC-verified channel state."""
    import jax.numpy as jnp

    if rec.arrays is None:
        raise ValueError(
            f"record {rec.index} ({rec.kind}) carries no state arrays")
    vals = {k: jnp.asarray(a) for k, a in rec.arrays.items()}
    return CellularSpace(vals, rec.meta["dim_x"], rec.meta["dim_y"])


_SCALAR = (int, float, str, bool, type(None))


def model_meta(model) -> Optional[dict]:
    """JSON-able reconstruction recipe for a model whose flows are
    dataclasses of scalar (or int-tuple) fields — every flow the
    package ships. None when a flow carries something richer (a user
    subclass holding a Cell/array): recovery then falls back to the
    fleet's template model, with a warning."""
    import dataclasses as _dc

    flows = []
    for f in model.flows:
        if not _dc.is_dataclass(f):
            return None
        params = {}
        for fld in _dc.fields(f):
            v = getattr(f, fld.name)
            if isinstance(v, tuple) and all(
                    isinstance(e, (int, float)) for e in v):
                params[fld.name] = {"__tuple__": list(v)}
            elif isinstance(v, _SCALAR):
                params[fld.name] = v
            else:
                return None
        flows.append({"type": type(f).__name__, "params": params})
    return {"flows": flows, "time": model.time,
            "time_step": model.time_step,
            "offsets": [list(o) for o in model.offsets]}


def model_from_meta(meta: Optional[dict], template=None):
    """Rebuild the model a submit record described; ``template`` when
    the record carried none (see ``model_meta``)."""
    if meta is None:
        return template
    from ..ops import flow as flow_mod

    flows = []
    for fm in meta["flows"]:
        cls = getattr(flow_mod, fm["type"], None)
        if not (isinstance(cls, type) and issubclass(cls, flow_mod.Flow)):
            raise ValueError(
                f"journal names unknown flow type {fm['type']!r}")
        params = {
            k: tuple(v["__tuple__"])
            if isinstance(v, dict) and "__tuple__" in v else v
            for k, v in fm["params"].items()}
        flows.append(cls(**params))
    return Model(flows, meta["time"], meta["time_step"],
                 offsets=[tuple(o) for o in meta["offsets"]])


# -- inspection CLI (ISSUE 13 satellite) --------------------------------------

def audit_journal(path: str, _records: Optional[list] = None,
                  _torn: Optional[bool] = None) -> dict:
    """The exactly-once audit as one reusable cut (the CLI below and
    the bench's recovery leg share it): verified record counts per
    kind, the torn flag, the unresolved-ticket list and the
    duplicate-terminal list. ``ok`` is the exactly-once verdict —
    no ticket resolved twice (unresolved tickets are a RECOVERY TODO,
    not an audit failure: they are exactly what ``recover`` re-admits).
    A caller that already scanned the file passes the verified records
    through ``_records``/``_torn`` — the file is read and CRC-checked
    exactly once per invocation either way."""
    if _records is None:
        records, torn = read_records(path)
    else:
        records, torn = _records, bool(_torn)
    state = fold_records(records, torn)
    kinds: dict = {}
    for rec in records:
        kinds[rec.kind] = kinds.get(rec.kind, 0) + 1
    return {
        "path": path,
        "records": len(records),
        "kinds": kinds,
        "torn": torn,
        "submits": len(state.submits),
        "terminal": len(state.terminal),
        "shed": state.shed,
        "unresolved": state.unresolved(),
        "duplicate_terminals": list(state.duplicate_terminals),
        "epochs": [
            {"epoch": m["epoch"], "supervisor": m.get("supervisor"),
             "takeover_from": m.get("takeover_from"),
             "lease_s": m.get("lease_s"), "t_wall": m.get("t_wall")}
            for m in state.epochs],
        "stale_epoch_records": list(state.stale_epoch_records),
        "ok": (not state.duplicate_terminals
               and not state.stale_epoch_records),
    }


def main(argv: Optional[list] = None) -> int:
    """``python -m mpi_model_tpu.ensemble.journal <dir-or-file>``:
    print the verified record stream (index, kind, ticket, byte sizes)
    and run the ``replay()`` exactly-once audit standalone — the
    operator's window into a crashed fleet's ledger before (or after)
    ``FleetSupervisor.recover`` replays it. ``--json`` emits the audit
    dict on one line; exit 1 when the audit finds duplicate terminals
    (a ticket resolved twice — the invariant recovery must never
    break) or stale-epoch appends (a zombie supervisor's write got
    past the fence), 0 otherwise (a torn tail or unresolved tickets
    are REPORTED, not fatal: they are the normal crash shape)."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m mpi_model_tpu.ensemble.journal",
        description="Inspect a fleet ticket journal: verified record "
                    "stream + the replay() exactly-once audit.")
    p.add_argument("journal", help="journal directory (containing "
                   f"{JOURNAL_NAME}) or the journal file itself")
    p.add_argument("--json", action="store_true",
                   help="emit the audit as one JSON line (no record "
                        "listing)")
    args = p.parse_args(argv)
    path = args.journal
    if os.path.isdir(path):
        path = journal_path(path)
    if not os.path.exists(path):
        print(f"no journal at {path}", file=sys.stderr)
        return 2
    records, torn = read_records(path)  # ONE scan for every view below
    audit = audit_journal(path, _records=records, _torn=torn)
    if args.json:
        print(json.dumps(audit, sort_keys=True))
    else:
        for rec in records:
            nbytes = sum(spec["nbytes"] for spec in
                         rec.meta.get("arrays", {}).values())
            t = "" if rec.ticket is None else f" ticket={rec.ticket}"
            extra = "" if nbytes == 0 else f" state={nbytes}B"
            sid = rec.meta.get("service_id")
            extra += "" if sid is None else f" member={sid}"
            print(f"[{rec.index:4d}] {rec.kind:<12}{t}{extra}")
        print(f"-- {audit['records']} verified records "
              f"({', '.join(f'{k}={v}' for k, v in sorted(audit['kinds'].items()))})"
              + ("; TORN TAIL discarded" if audit["torn"] else ""))
        for e in audit["epochs"]:
            src = ("first start" if e["takeover_from"] is None
                   else f"took over from {e['takeover_from']}")
            print(f"-- epoch {e['epoch']}: supervisor="
                  f"{e['supervisor']} ({src}, lease_s={e['lease_s']})")
        if audit["stale_epoch_records"]:
            print(f"-- STALE-EPOCH APPENDS (zombie writes past the "
                  f"fence): records {audit['stale_epoch_records']}")
        print(f"-- audit: submits={audit['submits']} "
              f"terminal={audit['terminal']} shed={audit['shed']} "
              f"unresolved={audit['unresolved']} "
              f"duplicate_terminals={audit['duplicate_terminals']}")
        print("-- exactly-once: " + (
            "OK" if audit["ok"] else
            "FAILED (duplicate terminals or stale-epoch appends)"))
    return 0 if audit["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
